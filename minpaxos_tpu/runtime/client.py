"""Benchmark client library: leader discovery, batched proposes,
failover retry, exactly-once checking.

Counterpart of the reference's client family (SURVEY.md section 2.4):
``client`` (closed-loop rounds, conflict-% / Zipfian keys, -check),
``clientretry`` (outer retry loop that re-dials and adopts any
reachable replica when the leader dies, clientretry.go:120-150), and
the latency/throughput probes (clientlat, clienttot, client-ol-lat)
whose measurement styles the CLI reproduces.

Retry semantics: unacknowledged commands are re-sent with the SAME
cmd_id after failover, and replies are deduplicated by cmd_id — an
explicit upgrade over the reference, which restarts CommandIds from 0
on retry and can observe duplicates (clientretry.go:152, SURVEY.md
section 7.4).
"""

from __future__ import annotations

import selectors
import socket
import threading
import time

import numpy as np

from minpaxos_tpu.obs.metrics import MetricsRegistry
from minpaxos_tpu.obs.trace import (
    ST_REPLY_RECV,
    ST_SEND,
    TraceSink,
    monotonic_ns,
    trace_id_for,
)
from minpaxos_tpu.obs.watch import EV_CLIENT_FAILOVER, EventJournal
from minpaxos_tpu.runtime.master import (
    backoff_sleeps,
    get_leader,
    get_replica_list,
)
from minpaxos_tpu.utils.dlog import dlog
from minpaxos_tpu.wire.codec import FrameWriter, StreamDecoder
from minpaxos_tpu.wire.messages import MsgKind, Op, make_batch


def gen_workload(n: int, conflict_pct: int = 0, key_range: int = 100000,
                 zipf_s: float = 0.0, write_pct: int = 100,
                 seed: int = 42, profile=None,
                 ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pre-generated request arrays (ops, keys, vals) — the reference
    pre-builds karray/put with conflict-% or Zipfian keys
    (client.go:68-103; seed 42 at :45).

    ``profile`` (a ``soak.profiles`` name, dict, or WorkloadProfile)
    switches to the paxsoak generator family: EXACT finite-support
    Zipf, read/write mix and value-size envelope, byte-reproducible
    from ``seed``. The legacy knobs are ignored in that mode (numpy's
    ``rng.zipf`` here samples the unbounded Zeta distribution — kept
    for bench continuity, superseded by the profiles)."""
    if profile is not None:
        # soak.profiles imports nothing from runtime — no cycle
        from minpaxos_tpu.soak.profiles import (profile_rows,
                                                resolve_profile)
        return profile_rows(resolve_profile(profile), n, seed)
    rng = np.random.default_rng(seed)
    if zipf_s > 0:
        keys = (rng.zipf(zipf_s, n) - 1) % key_range
    else:
        keys = rng.integers(0, key_range, n)
        conflicted = rng.integers(0, 100, n) < conflict_pct
        keys = np.where(conflicted, 42, keys)  # all conflicts hit one key
    ops = np.where(rng.integers(0, 100, n) < write_pct,
                   int(Op.PUT), int(Op.GET))
    vals = rng.integers(1, 1 << 20, n)
    return ops.astype(np.int64), keys.astype(np.int64), vals.astype(np.int64)


class Client:
    """One TCP connection to one replica + reply collection thread."""

    def __init__(self, maddr: tuple[str, int], check: bool = False,
                 backoff_seed: int | None = None,
                 trace_pow2: int | None = None):
        """``trace_pow2``: paxtrace sampling exponent (None = tracing
        off, the byte-transparent default — the wire then carries no
        TRACE_CTX frames; 0 = trace every command). Sampled proposes
        send a context frame ahead of the PROPOSE and stamp SEND /
        REPLY_RECV spans into this client's own rings
        (``trace_collect``)."""
        self.maddr = maddr
        self.check = check
        self.trace = (None if trace_pow2 is None else
                      TraceSink(enabled=True, sample_pow2=trace_pow2))
        self.nodes = get_replica_list(maddr)
        self.leader = get_leader(maddr)
        self.sock: socket.socket | None = None
        self.writer: FrameWriter | None = None
        self.replies: dict[int, dict] = {}  # cmd_id -> reply
        self.dup_replies = 0
        self.rejected: list[int] = []
        # paxmon client-side registry: retries and failovers are
        # otherwise invisible in bench artifacts (a trial that quietly
        # failed over twice is not the same measurement as a clean one)
        self.metrics = MetricsRegistry(namespace="client")
        self._c_proposed = self.metrics.counter(
            "proposed_rows", "command rows written to the wire "
            "(> workload size means retries happened)")
        self._c_failovers = self.metrics.counter(
            "failovers", "connection re-routes (leader hint / master "
            "/ scan)")
        self._c_connect_attempts = self.metrics.counter(
            "connect_attempts", "individual replica dials tried during "
            "failovers (>> failovers means the cluster was hard to "
            "reach)")
        self._c_backoff_sleeps = self.metrics.counter(
            "backoff_sleeps", "failover rounds that found NO reachable "
            "replica and slept a jittered exponential backoff")
        # paxwatch journal: failovers become queryable events (which
        # replica the client landed on, when, wall+mono stamped) next
        # to the cluster-side journals — a chaos campaign's CHAOS.json
        # carries the counts, and events_collect() hands the rows to
        # whoever merges the incident timeline
        self.journal = EventJournal(capacity=256)
        # failover backoff (seeded): when no replica answers, sleeps
        # grow 50 ms -> 2 s with U[0.5, 1.0] jitter instead of the old
        # fixed 0.5 s — a fleet of chaos-campaign clients redialing a
        # dead cluster must decorrelate, not arrive as one synchronized
        # storm on revival. An explicit seed makes a campaign's redial
        # pattern part of its reproducible schedule.
        self._backoff_rng = np.random.default_rng(backoff_seed)
        self._backoff = None  # live generator while a streak lasts
        self.leader_hint = -1
        self._lock = threading.Lock()
        self._got = threading.Condition(self._lock)
        self._reader: threading.Thread | None = None
        self._closed = threading.Event()
        # permanent shutdown (unlike _closed, never cleared): a
        # wait_less straggler partition must stop retrying when its
        # MultiClient is closed, not resurrect the connection via
        # _failover under a fresh conn_id (which would sidestep the
        # server's same-connection dedup and duplicate slots)
        self._done = False

    # -- connection management --

    def connect(self, replica: int | None = None) -> None:
        self.close_conn()
        self._closed.clear()
        rid = self.leader if replica is None else replica
        host, port = self.nodes[rid]
        self.sock = socket.create_connection((host, port), timeout=5.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.sendall(bytes([int(MsgKind.HANDSHAKE_CLIENT)]))
        self.writer = FrameWriter(self.sock)
        self._reader = threading.Thread(target=self._read_loop, daemon=True)
        self._reader.start()
        self.connected_to = rid

    def close_conn(self) -> None:
        self._closed.set()
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    def _read_loop(self) -> None:
        dec = StreamDecoder()
        sock = self.sock
        while not self._closed.is_set():
            try:
                chunk = sock.recv(1 << 16)
            except OSError:
                break
            if not chunk:
                break
            try:
                for kind, rows in dec.feed(chunk):
                    self._on_frame(kind, rows)
            except ValueError:
                break  # corrupt frame: close and let failover re-dial
            if dec.error is not None:
                break
        with self._got:
            self._got.notify_all()

    def _on_frame(self, kind: MsgKind, rows: np.ndarray) -> None:
        if kind not in (MsgKind.PROPOSE_REPLY, MsgKind.READ_REPLY):
            return
        # t_arrive: reader-thread arrival time (one stamp per frame —
        # the rows arrived together), for the open-loop latency probe
        t = time.monotonic()
        tr = self.trace
        if tr is not None and len(rows) and kind == MsgKind.PROPOSE_REPLY:
            # reply-receipt spans close sampled WRITE chains; this
            # reader thread stamps into its own ring (single-writer).
            # Read replies are skipped — reads never get drain/commit
            # spans, so stamping them only churns the rings.
            t_ns = monotonic_ns()
            tr.stamp_batch(ST_REPLY_RECV, rows["cmd_id"], t_ns, t_ns)
        with self._got:
            # column extraction + zip over plain Python scalars: per-row
            # structured access (r["field"]) cost ~0.8 ms per 512-row
            # frame of pure client CPU on the shared bench core
            if kind == MsgKind.PROPOSE_REPLY:
                okm = rows["ok"] != 0
                rej = rows[~okm]
                if len(rej):
                    self.leader_hint = int(rej["leader"][-1])
                    self.rejected.extend(rej["cmd_id"].tolist())
                    rows = rows[okm]
                replies = self.replies
                for cmd, val, ts in zip(rows["cmd_id"].tolist(),
                                        rows["val"].tolist(),
                                        rows["timestamp"].tolist()):
                    if cmd in replies:
                        self.dup_replies += 1  # -check duplicates
                    else:
                        replies[cmd] = {"val": val, "t_arrive": t,
                                        "ts": ts}
            else:
                replies = self.replies
                for cmd, val in zip(rows["cmd_id"].tolist(),
                                    rows["val"].tolist()):
                    if cmd in replies:
                        self.dup_replies += 1
                    else:
                        replies[cmd] = {"val": val, "t_arrive": t}
            self._got.notify_all()

    def trace_collect(self) -> dict | None:
        """This client's paxtrace span collection (None if tracing is
        off) — merged with the cluster's TRACESPANS fan-out by
        tools/tail.py / bench_tcp to close chains client-to-client."""
        return None if self.trace is None else self.trace.collect()

    def events_collect(self) -> dict:
        """This client's paxwatch journal collection (anchored like
        the cluster-side EVENTS verb payloads, so
        align_event_collections merges it into the same timeline)."""
        return self.journal.collect()

    # -- propose / wait --

    def propose(self, cmd_ids, ops, keys, vals) -> None:
        frame = make_batch(MsgKind.PROPOSE, cmd_id=np.asarray(cmd_ids, np.int32),
                           op=np.asarray(ops), key=np.asarray(keys),
                           val=np.asarray(vals),
                           timestamp=time.monotonic_ns())
        tr = self.trace
        ctx = None
        t_s0 = 0
        if tr is not None:
            # context frame for the SAMPLED commands of this batch,
            # written ahead of the PROPOSE on the same stream (one
            # flush covers both); tracing off sends nothing — the wire
            # is byte-identical to a v1 client
            m = tr.sampled(frame["cmd_id"])
            if m.any():
                ids = frame["cmd_id"][m]
                t_s0 = monotonic_ns()
                ctx = make_batch(MsgKind.TRACE_CTX, cmd_id=ids,
                                 trace_id=trace_id_for(ids),
                                 origin_wall_ns=time.time_ns())
                self.writer.write(MsgKind.TRACE_CTX, ctx)
        self.writer.write(MsgKind.PROPOSE, frame)
        self.writer.flush()
        if ctx is not None:
            # the ctx frame already carries the mask-filtered ids and
            # their trace ids — record them directly instead of paying
            # stamp_batch's redundant re-hash of an all-sampled batch
            t_s1 = monotonic_ns()
            ring = tr.ring()
            for tid, cid in zip(ctx["trace_id"].tolist(),
                                ctx["cmd_id"].tolist()):
                ring.record(tid, ST_SEND, t_s0, t_s1, cid)
        self._c_proposed.inc(len(frame))

    def read(self, cmd_ids, keys) -> None:
        frame = make_batch(MsgKind.READ, cmd_id=np.asarray(cmd_ids, np.int32),
                           key=np.asarray(keys))
        self.writer.write(MsgKind.READ, frame)
        self.writer.flush()

    def wait(self, cmd_ids, timeout_s: float = 10.0) -> bool:
        """Block until every cmd_id has a success reply (or timeout)."""
        deadline = time.monotonic() + timeout_s
        want = set(int(c) for c in cmd_ids)
        with self._got:
            while True:
                missing = want - self.replies.keys()
                if not missing:
                    return True
                left = deadline - time.monotonic()
                if left <= 0 or self._closed.is_set():
                    return not missing
                self._got.wait(timeout=min(left, 0.25))

    # -- the retry driver (clientretry.go:120-150 semantics) --

    def run_workload(self, ops, keys, vals, batch: int = 512,
                     timeout_s: float = 60.0) -> dict:
        """Send everything, retrying unacked commands across failovers
        with the same cmd_ids. Returns stats incl. -check results."""
        n = len(ops)
        t0 = time.monotonic()
        stats = self.run_partition(np.arange(n), ops, keys, vals,
                                   batch=batch, timeout_s=timeout_s)
        wall = time.monotonic() - t0
        done = stats["acked"]
        return {"sent": n, "acked": done, "wall_s": wall,
                "ops_per_s": done / wall if wall > 0 else 0.0,
                "duplicates": stats["duplicates"],
                "missing": n - done,
                "client_metrics": self.metrics.counters()}

    def run_partition(self, idx: np.ndarray, ops, keys, vals,
                      batch: int = 512, timeout_s: float = 60.0) -> dict:
        """run_workload over an explicit cmd_id subset (`idx`), keeping
        the GLOBAL ids — the per-connection driver MultiClient uses."""
        n = len(idx)
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        if self.sock is None:
            self.connect(getattr(self, "connected_to", None))
        # persistent pending list; each loop filters only the HEAD
        # window under the lock (O(batch), so the reader thread is
        # never stalled behind an O(n) scan), and unacked heads are
        # pushed back for retry — an id leaves pending only acked, so
        # commands lost to failover are re-swept without a cursor
        pending = [int(c) for c in idx]
        while pending and not self._done and time.monotonic() < deadline:
            with self._lock:
                head = [c for c in pending[:batch]
                        if c not in self.replies]
            tail = pending[batch:]
            if not head:
                pending = tail
                continue
            w = np.asarray(head)
            broken = False
            try:
                self.propose(w, ops[w], keys[w], vals[w])
                ok = self.wait(w, timeout_s=3.0)
            except OSError:
                ok, broken = False, True
            if ok:
                pending = tail
            else:
                # only fail over when the connection died or NOTHING
                # acked — a slow-but-live cluster keeps the SAME
                # connection, so the server's same-connection dedup
                # absorbs the re-proposal instead of a fresh conn_id
                # allocating duplicate slots (the retry-storm
                # amplifier; reconnecting on every timeout made the
                # dedup unreachable)
                with self._lock:
                    progressed = any(c in self.replies for c in head)
                if broken or not progressed:
                    self._failover()
                pending = head + tail
        with self._lock:
            done = sum(1 for c in idx if int(c) in self.replies)
        return {"sent": n, "acked": done,
                "duplicates": self.dup_replies, "missing": n - done}

    def _failover(self) -> None:
        """Leader died or rejected us: prefer its hint, else ask the
        master, else scan replicas for any that accepts TCP
        (clientretry.go:242-251)."""
        if self._done:
            return
        self._c_failovers.inc()
        candidates: list[int] = []
        if 0 <= self.leader_hint < len(self.nodes):
            candidates.append(self.leader_hint)
        try:
            candidates.append(get_leader(self.maddr, timeout_s=3.0))
        except TimeoutError:
            pass
        candidates.extend(r for r in range(len(self.nodes)))
        for rid in candidates:
            self._c_connect_attempts.inc()
            try:
                self.connect(rid)
                self.leader = rid
                self._backoff = None  # reachable again: reset the streak
                self.journal.record(EV_CLIENT_FAILOVER, subject=rid,
                                    value=self._c_failovers.value)
                dlog(f"client: failed over to replica {rid}")
                return
            except OSError:
                continue
        # nothing reachable: jittered exponential backoff (see __init__)
        self.journal.record(EV_CLIENT_FAILOVER, subject=-1,
                            value=self._c_failovers.value)
        if self._backoff is None:
            self._backoff = backoff_sleeps(0.05, 2.0, self._backoff_rng)
        self._c_backoff_sleeps.inc()
        time.sleep(next(self._backoff))


class MultiClient:
    """One connection per replica: the reference client's multi-target
    send modes (client.go:19-31, send paths :148-209).

    * ``mode="rr"`` — leaderless round-robin (`-e`): command i goes to
      replica i % N on that replica's own connection. This is the
      natural Mencius driver — every owner serves proposals into its
      own slots concurrently, which is the whole point of the
      protocol; a single hinted proposer makes the other owners cede
      every slot (BENCH_TCP round 3: mencius at half of minpaxos).
    * ``mode="fast"`` — fast mode (`-f`): every command goes to ALL
      replicas; the first success reply on any connection wins.
      Non-leaders reject (MinPaxos/classic), so exactly one success
      arrives per command; with -check, per-connection reply books
      keep rejections from counting as duplicates. Not meaningful for
      Mencius (each owner would commit the command into its own slot
      = N× execution).

    Exactly-once bookkeeping is per connection (the server replies on
    the proposing connection only), so sub-clients never see each
    other's replies; stats aggregate across them.
    """

    def __init__(self, maddr: tuple[str, int], check: bool = False,
                 mode: str = "rr", bar_one: bool = False,
                 wait_less: bool = False, trace_pow2: int | None = None):
        """``bar_one``: send to all replicas except the LAST (reference
        clienttot -barOne, clienttot/client.go:31, :76-78 — the
        excluded replica still learns/executes via the protocol, it
        just serves no proposals). ``wait_less``: in rr mode, stop
        waiting once all but one partition finished (clienttot
        -waitLess, :32, :191-199 — tolerate one straggler replica's
        batch; its partition keeps draining in the background)."""
        assert mode in ("rr", "fast")
        self.mode = mode
        self.wait_less = wait_less
        self.nodes = get_replica_list(maddr)
        self.clients: list[Client] = []
        n_targets = len(self.nodes) - 1 if bar_one else len(self.nodes)
        assert n_targets >= 1, "-barOne needs at least 2 replicas"
        for rid in range(n_targets):
            c = Client(maddr, check=check, trace_pow2=trace_pow2)
            c.connect(rid)
            self.clients.append(c)

    def trace_collect(self) -> list[dict]:
        """Per-connection paxtrace collections (rr partitions have
        disjoint cmd_id spaces, so the merge is safe)."""
        out = [c.trace_collect() for c in self.clients]
        return [c for c in out if c is not None]

    def run_workload(self, ops, keys, vals, batch: int = 512,
                     timeout_s: float = 60.0) -> dict:
        n = len(ops)
        t0 = time.monotonic()
        if self.mode == "rr":
            parts = [np.arange(n)[np.arange(n) % len(self.clients) == r]
                     for r in range(len(self.clients))]
            results: list[dict | None] = [None] * len(self.clients)

            def drive(r):
                results[r] = self.clients[r].run_partition(
                    parts[r], ops, keys, vals, batch=batch,
                    timeout_s=timeout_s)

            threads = [threading.Thread(target=drive, args=(r,),
                                        daemon=True)
                       for r in range(len(self.clients))]
            for t in threads:
                t.start()
            if self.wait_less and len(threads) > 1:
                # stop waiting once all but one partition finished
                # (clienttot -waitLess): poll results, leave the
                # straggler's daemon thread draining. Count acks from
                # the reply books, not per-thread results — the
                # straggler HAS acked most of its partition by now and
                # those are real commits
                deadline = time.monotonic() + timeout_s + 10
                while (sum(r is not None for r in results)
                       < len(threads) - 1
                       and time.monotonic() < deadline):
                    time.sleep(0.01)
                # stop the straggler (bounded): a partition thread left
                # proposing into the next -r round's reused cmd_id
                # space would corrupt its ack counts and -check
                for r, res in enumerate(results):
                    if res is None:
                        self.clients[r]._done = True
                for t in threads:
                    t.join(timeout=4.0)
                # re-arm ONLY clients whose thread actually exited: a
                # straggler still inside a blocking failover after the
                # bounded join would resume proposing into the next
                # round's reused cmd_id space if its _done were cleared
                for c, t in zip(self.clients, threads):
                    if not t.is_alive():
                        c._done = False
                done = sum(len(c.replies) for c in self.clients)
                dups = sum(c.dup_replies for c in self.clients)
            else:
                for t in threads:
                    t.join(timeout=timeout_s + 10)
                done = sum(r["acked"] for r in results if r)
                dups = sum(r["duplicates"] for r in results if r)
        else:  # fast: fan out to all, first success wins
            deadline = t0 + timeout_s
            for lo in range(0, n, batch):
                idx = np.arange(lo, min(lo + batch, n))
                for c in self.clients:
                    try:
                        c.propose(idx, ops[idx], keys[idx], vals[idx])
                    except OSError:
                        # dead connection: re-dial the SAME replica (fast
                        # mode offers every command to every replica, so
                        # failing over elsewhere would double-offer) and
                        # retry once; if the replica itself is down the
                        # others cover
                        try:
                            c.connect(c.connected_to)
                            c.propose(idx, ops[idx], keys[idx], vals[idx])
                        except OSError:
                            pass
                while time.monotonic() < deadline:
                    if all(any(int(i) in c.replies for c in self.clients)
                           for i in idx):
                        break
                    time.sleep(0.002)
            done = sum(1 for i in range(n)
                       if any(i in c.replies for c in self.clients))
            # a duplicate = the SAME connection receiving two success
            # replies for one cmd (cross-connection replies are the
            # mode's design, not duplicates)
            dups = sum(c.dup_replies for c in self.clients)
        wall = time.monotonic() - t0
        cm: dict = {}
        for c in self.clients:  # summed across the per-replica conns
            for name, v in c.metrics.counters().items():
                cm[name] = cm.get(name, 0) + v
        return {"sent": n, "acked": done, "wall_s": wall,
                "ops_per_s": done / wall if wall > 0 else 0.0,
                "duplicates": dups, "missing": n - done,
                "client_metrics": cm}

    def close(self) -> None:
        for c in self.clients:
            c._done = True  # stragglers must not resurrect via failover
            c.close_conn()


class ClientSwarm:
    """Many concurrent closed-loop client sessions over ONE selector
    loop — the ingress-coalescer driver (bench_tcp -swarm).

    Each session is a real TCP connection (its own conn_id on the
    server, so the coalescer sees genuinely multiplexed ingress) that
    keeps exactly one command outstanding: propose, wait for the
    reply, propose the next. A thread per session would be 2×1024
    threads at the top of the bench range; instead every socket stays
    blocking (sends are tiny and never fill the kernel buffer) and a
    single ``selectors`` loop in the calling thread drains replies and
    re-kicks sessions, so the swarm's own scheduling noise stays out
    of the measured latency.

    Per-command latency is stamped at write time and read time in the
    driving thread; the result carries the full sorted distribution so
    the bench can report any percentile. Commands outstanding longer
    than ``retransmit_s`` are re-sent with the SAME cmd_id on the same
    connection (the server's same-connection dedup absorbs it) — this
    is the recovery path when the coalescer's admission gate sheds
    rows under overload, so overload degrades to bounded queueing
    plus retransmit rather than a hung session.
    """

    def __init__(self, maddr: tuple[str, int], sessions: int = 256,
                 trace_pow2: int | None = None,
                 retransmit_s: float = 1.0):
        self.maddr = maddr
        self.sessions = sessions
        self.retransmit_s = retransmit_s
        self.nodes = get_replica_list(maddr)
        self.leader = get_leader(maddr)
        self.trace = (None if trace_pow2 is None else
                      TraceSink(enabled=True, sample_pow2=trace_pow2))
        self._socks: list[socket.socket] = []

    def _connect_one(self, rid: int) -> tuple[socket.socket, FrameWriter]:
        host, port = self.nodes[rid]
        sock = socket.create_connection((host, port), timeout=5.0)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.sendall(bytes([int(MsgKind.HANDSHAKE_CLIENT)]))
        return sock, FrameWriter(sock)

    def trace_collect(self) -> dict | None:
        return None if self.trace is None else self.trace.collect()

    def close(self) -> None:
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        self._socks = []

    def _send(self, st: dict, cmd: int, ops, keys, vals) -> None:
        """One single-row PROPOSE (+ TRACE_CTX when sampled) on a
        session's connection; stamps t_send for the latency probe."""
        frame = make_batch(MsgKind.PROPOSE,
                           cmd_id=np.asarray([cmd], np.int32),
                           op=ops[cmd:cmd + 1], key=keys[cmd:cmd + 1],
                           val=vals[cmd:cmd + 1],
                           timestamp=time.monotonic_ns())
        tr = self.trace
        if tr is not None and tr.sampled(frame["cmd_id"]).any():
            t_s0 = monotonic_ns()
            ctx = make_batch(MsgKind.TRACE_CTX, cmd_id=frame["cmd_id"],
                             trace_id=trace_id_for(frame["cmd_id"]),
                             origin_wall_ns=time.time_ns())
            st["writer"].write(MsgKind.TRACE_CTX, ctx)
            st["writer"].write(MsgKind.PROPOSE, frame)
            st["writer"].flush()
            t_s1 = monotonic_ns()
            ring = tr.ring()
            ring.record(int(ctx["trace_id"][0]), ST_SEND, t_s0, t_s1, cmd)
        else:
            st["writer"].write(MsgKind.PROPOSE, frame)
            st["writer"].flush()
        st["out_cmd"] = cmd
        st["t_send"] = time.monotonic()

    def run(self, ops, keys, vals, ops_per_session: int,
            timeout_s: float = 120.0) -> dict:
        """Drive ``sessions`` closed loops of ``ops_per_session``
        commands each. Workload row for session s, op i is
        ``s * ops_per_session + i`` (also its cmd_id — connections have
        distinct server-side client ids, so the spaces never collide).

        Returns acked/sent/wall_s/ops_per_s plus ``lat_ms_sorted``
        (one entry per FIRST ack of a command) and retransmit /
        rejection tallies."""
        n_total = self.sessions * ops_per_session
        assert len(ops) >= n_total, "workload smaller than swarm plan"
        sel = selectors.DefaultSelector()
        states: list[dict] = []
        for s in range(self.sessions):
            sock, writer = self._connect_one(self.leader)
            self._socks.append(sock)
            st = {"sock": sock, "writer": writer,
                  "dec": StreamDecoder(), "next_i": 0, "out_cmd": -1,
                  "t_send": 0.0, "base": s * ops_per_session,
                  "dead": False}
            sel.register(sock, selectors.EVENT_READ, st)
            states.append(st)
        lats: list[float] = []
        acked = retransmits = rejects = dead = 0
        live = self.sessions
        # initial kick: every session's first command, all in flight
        # before the drain loop starts — this is the burst the
        # coalescer exists to merge
        for st in states:
            self._send(st, st["base"], ops, keys, vals)
            st["next_i"] = 1
        t0 = time.monotonic()
        deadline = t0 + timeout_s
        while live > 0 and time.monotonic() < deadline:
            events = sel.select(timeout=0.05)
            now = time.monotonic()
            t_ns = monotonic_ns()
            for key, _ in events:
                st = key.data
                try:
                    chunk = st["sock"].recv(1 << 16)
                except OSError:
                    chunk = b""
                if not chunk:
                    st["dead"] = True
                    sel.unregister(st["sock"])
                    live -= 1
                    dead += 1
                    continue
                for kind, rows in st["dec"].feed(chunk):
                    if kind != MsgKind.PROPOSE_REPLY:
                        continue
                    if self.trace is not None and len(rows):
                        self.trace.stamp_batch(ST_REPLY_RECV,
                                               rows["cmd_id"], t_ns, t_ns)
                    for r in range(len(rows)):
                        cmd = int(rows["cmd_id"][r])
                        if cmd != st["out_cmd"]:
                            continue  # stale retransmit echo
                        if int(rows["ok"][r]) == 0:
                            rejects += 1  # leader moved: re-offer below
                            st["t_send"] = 0.0
                            continue
                        lats.append((now - st["t_send"]) * 1e3)
                        acked += 1
                        st["out_cmd"] = -1
                        if st["next_i"] < ops_per_session:
                            self._send(st, st["base"] + st["next_i"],
                                       ops, keys, vals)
                            st["next_i"] += 1
                        else:
                            live -= 1
            # retransmit sweep: same cmd_id, same connection — covers
            # admission-gate drops and leader rejections
            for st in states:
                if (st["out_cmd"] >= 0 and not st["dead"]
                        and now - st["t_send"] > self.retransmit_s):
                    try:
                        self._send(st, st["out_cmd"], ops, keys, vals)
                        retransmits += 1
                    except OSError:
                        st["dead"] = True
                        sel.unregister(st["sock"])
                        live -= 1
                        dead += 1
        wall = time.monotonic() - t0
        sel.close()
        lats.sort()
        return {"sessions": self.sessions, "sent": n_total,
                "acked": acked, "wall_s": wall,
                "ops_per_s": acked / wall if wall > 0 else 0.0,
                "lat_ms_sorted": lats, "retransmits": retransmits,
                "rejects": rejects, "dead_sessions": dead,
                "missing": n_total - acked}
