"""TCP transport: peer mesh + client listener for a replica process.

Counterpart of the reference's genericsmr connection plumbing
(genericsmr.go:125-400): full TCP mesh where the lower-id replica dials
and the higher-id listens, a 1-byte connection-type handshake
(CLIENT/PEER, genericsmrproto.go:16-17), per-connection buffered
writers flushed once per batch, reconnect-on-failure both outbound
(ReconnectToPeer :254-287) and inbound (peerReconnector :377-400).

Threading: reader threads decode frames and enqueue
``(src_kind, conn_id, kind, rows)`` onto one queue owned by the
protocol thread; writes happen only from the protocol thread through
``send``/``flush_all``. Single-owner by construction — the reference's
benign data races (SURVEY.md section 5) cannot exist here.
"""

from __future__ import annotations

import queue
import socket
import threading
import time

import numpy as np

from minpaxos_tpu.utils.dlog import dlog
from minpaxos_tpu.wire.codec import FrameWriter, StreamDecoder
from minpaxos_tpu.wire.messages import MsgKind

FROM_PEER = 0
FROM_CLIENT = 1
CONN_LOST = 2


class _Conn:
    # frames_in/rows_in/bytes_in are owned by this connection's reader
    # thread and frames_out by the protocol thread (the only writer) —
    # single-writer tallies, aggregated lock-free-at-the-hot-path into
    # the paxmon registry via fn-gauges at snapshot time
    __slots__ = ("sock", "writer", "alive", "frames_in", "rows_in",
                 "bytes_in", "frames_out")

    def __init__(self, sock):
        self.sock = sock
        self.writer = FrameWriter(sock)
        self.alive = True
        self.frames_in = 0
        self.rows_in = 0
        self.bytes_in = 0
        self.frames_out = 0


class Transport:
    """Owns every socket of one replica process."""

    def __init__(self, me: int, addrs: list[tuple[str, int]],
                 inbox_queue: "queue.Queue | None" = None, metrics=None):
        self.me = me
        self.addrs = addrs  # data-port address of every replica, by id
        self.n = len(addrs)
        self.queue: queue.Queue = inbox_queue or queue.Queue()
        self.peers: dict[int, _Conn] = {}
        self.clients: dict[int, _Conn] = {}
        # tallies of connections that were REPLACED (peer redial): the
        # fn-gauges below must stay monotonic — summing live conns
        # only would regress the totals on every reconnect, turning
        # delta-based rates negative. Guarded by _lock.
        self._closed_tallies = {"frames_in": 0, "rows_in": 0,
                                "bytes_in": 0, "frames_out": 0}
        if metrics is not None:
            # wire visibility in the owner's registry: evaluated at
            # snapshot time (obs/metrics.py fn_gauge), so the per-frame
            # hot path stays a plain attribute add on the _Conn
            metrics.fn_gauge("peer_conns_alive", self._peers_alive)
            metrics.fn_gauge("client_conns", lambda: len(self.clients))
            for attr in ("frames_in", "rows_in", "bytes_in", "frames_out"):
                metrics.fn_gauge(f"net_{attr}",
                                 lambda a=attr: self._net_total(a))
        # Client connection ids are globally unique across replicas
        # (replica id in the high bits): command provenance travels
        # through the log as (client_id, cmd_id), and a follower
        # executing a leader-proposed command must never mistake the
        # leader's conn id for one of its own.
        self._next_client = me << 20
        self._lock = threading.Lock()  # guards peers/clients maps only
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._last_dial: dict[int, float] = {}

    def _conns(self) -> list:
        with self._lock:
            return list(self.peers.values()) + list(self.clients.values())

    def _peers_alive(self) -> int:
        with self._lock:
            return sum(c.alive for c in self.peers.values())

    def _net_total(self, attr: str) -> int:
        with self._lock:
            total = self._closed_tallies[attr]
            conns = list(self.peers.values()) + list(self.clients.values())
        return total + sum(getattr(c, attr) for c in conns)

    # -- lifecycle --

    def listen(self) -> None:
        host, port = self.addrs[self.me]
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # retry: a quickly-revived replica (kill/revive harnesses, the
        # reference's singleserverreconnect.sh shape) can race its
        # predecessor's listener close — same retry the control port
        # has always had (replica.py _start_control)
        deadline = time.monotonic() + 10.0
        while True:
            try:
                s.bind((host, port))
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        s.listen(64)
        self._listener = s
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def connect_peers(self) -> None:
        """Dial every lower-id peer (higher ids dial us); the handshake
        byte + our id identifies us on the other side."""
        for q in range(self.me):
            self.dial_peer(q)

    def dial_peer(self, q: int, rate_limit_s: float = 0.5) -> bool:
        """(Re)connect to peer q; rate-limited so a dead peer doesn't
        stall the protocol tick with back-to-back connect timeouts."""
        now = time.monotonic()
        if now - self._last_dial.get(q, -1e9) < rate_limit_s:
            return False
        self._last_dial[q] = now
        try:
            sock = socket.create_connection(self.addrs[q], timeout=1.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(bytes([int(MsgKind.HANDSHAKE_PEER), self.me]))
        except OSError:
            return False
        self._install_peer(q, sock)
        return True

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self.peers.values()) + list(self.clients.values())
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass

    # -- accept / read --

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._handshake, args=(sock,),
                             daemon=True).start()

    def _handshake(self, sock) -> None:
        """First byte: connection type; peers send their id next."""
        try:
            t = sock.recv(1)
            if not t:
                sock.close()
                return
            t = t[0]
            if t == int(MsgKind.HANDSHAKE_PEER):
                pid = sock.recv(1)
                if not pid:
                    sock.close()
                    return
                self._install_peer(pid[0], sock)
            elif t == int(MsgKind.HANDSHAKE_CLIENT):
                with self._lock:
                    cid = self._next_client
                    self._next_client += 1
                    self.clients[cid] = conn = _Conn(sock)
                threading.Thread(
                    target=self._read_loop,
                    args=(FROM_CLIENT, cid, conn), daemon=True).start()
            else:
                sock.close()
        except OSError:
            try:
                sock.close()
            except OSError:
                pass

    def _install_peer(self, q: int, sock) -> None:
        with self._lock:
            old = self.peers.get(q)
            if old is not None:
                # fold the replaced conn's tallies into the carry so
                # the net_* gauges never go backward on redial (the
                # old reader thread may race a final frame in — a
                # bounded monitoring undercount, not a regression)
                for attr in self._closed_tallies:
                    self._closed_tallies[attr] += getattr(old, attr)
            self.peers[q] = conn = _Conn(sock)
        if old is not None:
            try:
                old.sock.close()
            except OSError:
                pass
        dlog(f"replica {self.me}: peer {q} connected")
        threading.Thread(target=self._read_loop,
                         args=(FROM_PEER, q, conn), daemon=True).start()

    def _read_loop(self, src_kind: int, conn_id: int, conn: _Conn) -> None:
        dec = StreamDecoder()
        sock = conn.sock
        while not self._stop.is_set():
            try:
                chunk = sock.recv(1 << 16)
            except OSError:
                break
            if not chunk:
                break
            try:
                frames = dec.feed(chunk)
            except ValueError:
                break
            conn.bytes_in += len(chunk)
            conn.frames_in += len(frames)
            for kind, rows in frames:
                conn.rows_in += len(rows)
                self.queue.put((src_kind, conn_id, kind, rows))
            if dec.error is not None:
                break
        conn.alive = False
        self.queue.put((CONN_LOST, conn_id if src_kind == FROM_CLIENT
                        else -1 - conn_id, None, None))
        try:
            sock.close()
        except OSError:
            pass

    # -- write (protocol thread only) --

    def send_peer(self, q: int, kind: MsgKind, rows: np.ndarray) -> bool:
        conn = self.peers.get(q)
        if conn is None or not conn.alive:
            return False
        try:
            conn.writer.write(kind, rows)
            conn.frames_out += 1
            return True
        except OSError:
            conn.alive = False
            return False

    def send_client(self, cid: int, kind: MsgKind, rows: np.ndarray) -> bool:
        conn = self.clients.get(cid)
        if conn is None or not conn.alive:
            return False
        try:
            conn.writer.write(kind, rows)
            conn.frames_out += 1
            return True
        except OSError:
            conn.alive = False
            return False

    def flush_all(self) -> None:
        with self._lock:
            conns = list(self.peers.items()) + list(self.clients.items())
        for _, conn in conns:
            if conn.alive:
                try:
                    conn.writer.flush()
                except OSError:
                    conn.alive = False

    def peer_alive(self, q: int) -> bool:
        conn = self.peers.get(q)
        return conn is not None and conn.alive
