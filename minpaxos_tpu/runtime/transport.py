"""TCP transport: peer mesh + client listener for a replica process.

Counterpart of the reference's genericsmr connection plumbing
(genericsmr.go:125-400): full TCP mesh where the lower-id replica dials
and the higher-id listens, a 1-byte connection-type handshake
(CLIENT/PEER, genericsmrproto.go:16-17), per-connection buffered
writers flushed once per batch, reconnect-on-failure both outbound
(ReconnectToPeer :254-287) and inbound (peerReconnector :377-400).

Threading: reader threads decode frames and enqueue
``(src_kind, conn_id, kind, rows)`` onto one queue owned by the
protocol thread; writes happen only from the protocol thread through
``send``/``flush_all``. Single-owner by construction — the reference's
benign data races (SURVEY.md section 5) cannot exist here.
"""

from __future__ import annotations

import queue
import socket
import threading
import time

import numpy as np

from minpaxos_tpu.obs.trace import ST_DECODE
from minpaxos_tpu.obs.watch import EV_PEER_DOWN, EV_PEER_UP
from minpaxos_tpu.utils.clock import monotonic_ns
from minpaxos_tpu.utils.dlog import dlog
from minpaxos_tpu.wire.codec import FrameWriter, StreamDecoder
from minpaxos_tpu.wire.messages import MsgKind

FROM_PEER = 0
FROM_CLIENT = 1
CONN_LOST = 2


class _Conn:
    # frames_in/rows_in/bytes_in are owned by this connection's reader
    # thread and frames_out by the protocol thread (the only writer) —
    # single-writer tallies, aggregated lock-free-at-the-hot-path into
    # the paxmon registry via fn-gauges at snapshot time
    __slots__ = ("sock", "writer", "alive", "frames_in", "rows_in",
                 "bytes_in", "frames_out")

    def __init__(self, sock):
        self.sock = sock
        self.writer = FrameWriter(sock)
        self.alive = True
        self.frames_in = 0
        self.rows_in = 0
        self.bytes_in = 0
        self.frames_out = 0


class Transport:
    """Owns every socket of one replica process."""

    def __init__(self, me: int, addrs: list[tuple[str, int]],
                 inbox_queue: "queue.Queue | None" = None, metrics=None):
        self.me = me
        self.addrs = addrs  # data-port address of every replica, by id
        self.n = len(addrs)
        self.queue: queue.Queue = inbox_queue or queue.Queue()
        self.peers: dict[int, _Conn] = {}
        self.clients: dict[int, _Conn] = {}
        # tallies of connections that were REPLACED (peer redial): the
        # fn-gauges below must stay monotonic — summing live conns
        # only would regress the totals on every reconnect, turning
        # delta-based rates negative. Guarded by _lock.
        self._closed_tallies = {"frames_in": 0, "rows_in": 0,
                                "bytes_in": 0, "frames_out": 0}
        # paxchaos shim (chaos/shim.py): consulted per peer frame in
        # send_peer/_read_loop when installed. The disabled path is ONE
        # attribute load + is-None test per frame — no allocation, no
        # branch into chaos code. _chaos_retired carries fault totals
        # of replaced shims so the fn-gauge stays monotonic across
        # install/heal cycles (same contract as _closed_tallies).
        self.chaos = None
        self._chaos_retired = 0
        # paxtrace sink (obs/trace.py): when installed, reader threads
        # stamp a frame-decode span for client PROPOSE frames carrying
        # a SAMPLED command. Same discipline as the chaos shim: the
        # disabled path is one attribute load + is-None test per chunk,
        # and each reader thread writes only its OWN span ring.
        self.trace = None
        # paxwatch journal (obs/watch.py): when installed, peer-link
        # lifecycle (install / reader-loop death) is journaled so a
        # flapping mesh is queryable. Same discipline as the trace
        # sink: one attribute load + is-None test when absent, and
        # every writer thread records into its own ring.
        self.journal = None
        # per-peer dial suppression state: a refused dial doubles the
        # peer's suppression window instead of re-timing out every
        # 0.5 s — a flapping or partitioned peer must not price a
        # connect timeout into every dispatch. Written by the protocol
        # thread (refusal) AND the accept thread (inbound-install
        # reset), both under self._lock; dial_peer's lone window read
        # stays lock-free (a stale read costs one extra suppression)
        self._dial_fails: dict[int, int] = {}
        self._dial_window: dict[int, float] = {}
        self._dial_tallies = {"ok": 0, "refused": 0, "suppressed": 0}
        if metrics is not None:
            # wire visibility in the owner's registry: evaluated at
            # snapshot time (obs/metrics.py fn_gauge), so the per-frame
            # hot path stays a plain attribute add on the _Conn
            metrics.fn_gauge("peer_conns_alive", self._peers_alive)
            metrics.fn_gauge("client_conns", lambda: len(self.clients))
            # ingress depth: works for a plain Queue and for the
            # IngressCoalescer (both expose qsize); sampled at snapshot
            metrics.fn_gauge("ingress_queue_depth", self.queue.qsize)
            for attr in ("frames_in", "rows_in", "bytes_in", "frames_out"):
                metrics.fn_gauge(f"net_{attr}",
                                 lambda a=attr: self._net_total(a))
            # dial outcomes: 'suppressed' (backoff window) vs 'refused'
            # (real connect failure) are distinct signals — peer_alive
            # false + dials_suppressed rising means backoff, not churn
            for k in ("ok", "refused", "suppressed"):
                metrics.fn_gauge(f"dials_{k}",
                                 lambda k=k: self._dial_tallies[k])
            metrics.fn_gauge("chaos_injected", self.chaos_faults_total)
        # Client connection ids are globally unique across replicas
        # (replica id in the high bits): command provenance travels
        # through the log as (client_id, cmd_id), and a follower
        # executing a leader-proposed command must never mistake the
        # leader's conn id for one of its own.
        self._next_client = me << 20
        self._lock = threading.Lock()  # guards peers/clients maps only
        self._listener: socket.socket | None = None
        self._stop = threading.Event()
        self._last_dial: dict[int, float] = {}

    def _conns(self) -> list:
        with self._lock:
            return list(self.peers.values()) + list(self.clients.values())

    def _peers_alive(self) -> int:
        with self._lock:
            return sum(c.alive for c in self.peers.values())

    def _net_total(self, attr: str) -> int:
        with self._lock:
            total = self._closed_tallies[attr]
            conns = list(self.peers.values()) + list(self.clients.values())
        return total + sum(getattr(c, attr) for c in conns)

    # -- paxchaos (chaos/shim.py) --

    def set_chaos(self, shim) -> None:
        """Install (or, with None, heal) the fault-injection shim.
        Called from the control thread; readers grab one reference per
        frame, so the attribute swap is the whole synchronization for
        the DATA path. The tally handoff needs more care: stop the old
        shim FIRST (no tallies advance past its stopped flag), then
        fold its total into the retired carry and swap in the new shim
        under the lock chaos_faults_total shares — folding after the
        swap let a tick-thread read see the counter step down to zero
        and back (a Perfetto counter track going negative). An ingest
        already past the stopped check can still tally after the fold —
        the same bounded monitoring undercount _closed_tallies accepts."""
        if shim is not None:
            from minpaxos_tpu.chaos import shim as _chaos_shim

            assert _chaos_shim.FROM_PEER == FROM_PEER
        old = self.chaos
        if old is not None:
            old.stop()  # outside the lock: stop delivers held frames
        with self._lock:
            if old is not None:
                self._chaos_retired += old.faults_total()
            self.chaos = shim

    def chaos_faults_total(self) -> int:
        ch = self.chaos
        if ch is None:
            # lock-free fast path: the recorder calls this every tick,
            # and with no shim installed it must not price a lock
            # acquire into the tick floor. _chaos_retired only changes
            # inside set_chaos AFTER the fold, so a None read here
            # always sees the retired total already folded — monotonic
            return self._chaos_retired
        with self._lock:
            ch = self.chaos
            total = self._chaos_retired
        return total if ch is None else total + ch.faults_total()

    # -- lifecycle --

    def listen(self) -> None:
        host, port = self.addrs[self.me]
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # retry: a quickly-revived replica (kill/revive harnesses, the
        # reference's singleserverreconnect.sh shape) can race its
        # predecessor's listener close — same retry the control port
        # has always had (replica.py _start_control)
        deadline = time.monotonic() + 10.0
        while True:
            try:
                s.bind((host, port))
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        s.listen(64)
        self._listener = s
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def connect_peers(self) -> None:
        """Dial every lower-id peer (higher ids dial us); the handshake
        byte + our id identifies us on the other side."""
        for q in range(self.me):
            self.dial_peer(q)

    #: dial backoff ceiling: a peer refusing for a while is re-tried at
    #: most this often; any successful connect (either direction)
    #: resets its window to the base rate
    DIAL_BACKOFF_CAP_S = 5.0

    def dial_peer(self, q: int, rate_limit_s: float = 0.5) -> bool:
        """(Re)connect to peer q. The suppression window is PER PEER
        and doubles on every refused dial (up to DIAL_BACKOFF_CAP_S):
        the old per-call wall-clock limit let a flapping link re-pay a
        full connect timeout every 0.5 s on the protocol thread. The
        dials_{ok,refused,suppressed} tallies make 'peer dead' vs
        'dial suppressed by backoff' distinguishable in stats."""
        now = time.monotonic()
        window = self._dial_window.get(q, rate_limit_s)
        if now - self._last_dial.get(q, -1e9) < window:
            self._dial_tallies["suppressed"] += 1
            return False
        self._last_dial[q] = now
        prev = self.peers.get(q)
        try:
            sock = socket.create_connection(self.addrs[q], timeout=1.0)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(bytes([int(MsgKind.HANDSHAKE_PEER), self.me]))
        except OSError:
            with self._lock:
                # an inbound handshake can land (accept thread) while
                # this connect was timing out; growing the window then
                # would suppress the first redial after that live conn
                # later drops — only record the refusal if no install
                # raced us
                if self.peers.get(q) is prev:
                    fails = self._dial_fails.get(q, 0) + 1
                    self._dial_fails[q] = fails
                    self._dial_window[q] = min(
                        rate_limit_s * (2 ** fails),
                        self.DIAL_BACKOFF_CAP_S)
            self._dial_tallies["refused"] += 1
            return False
        self._dial_tallies["ok"] += 1
        self._install_peer(q, sock)
        return True

    def stop(self) -> None:
        self._stop.set()
        ch = self.chaos
        if ch is not None:
            ch.stop(flush=False)  # shutting down: nothing to heal into
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self.peers.values()) + list(self.clients.values())
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass

    # -- accept / read --

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            threading.Thread(target=self._handshake, args=(sock,),
                             daemon=True).start()

    def _handshake(self, sock) -> None:
        """First byte: connection type; peers send their id next."""
        try:
            t = sock.recv(1)
            if not t:
                sock.close()
                return
            t = t[0]
            if t == int(MsgKind.HANDSHAKE_PEER):
                pid = sock.recv(1)
                if not pid:
                    sock.close()
                    return
                self._install_peer(pid[0], sock)
            elif t == int(MsgKind.HANDSHAKE_CLIENT):
                with self._lock:
                    cid = self._next_client
                    self._next_client += 1
                    self.clients[cid] = conn = _Conn(sock)
                threading.Thread(
                    target=self._read_loop,
                    args=(FROM_CLIENT, cid, conn), daemon=True).start()
            else:
                sock.close()
        except OSError:
            try:
                sock.close()
            except OSError:
                pass

    def _install_peer(self, q: int, sock) -> None:
        with self._lock:
            old = self.peers.get(q)
            if old is not None:
                # fold the replaced conn's tallies into the carry so
                # the net_* gauges never go backward on redial (the
                # old reader thread may race a final frame in — a
                # bounded monitoring undercount, not a regression)
                for attr in self._closed_tallies:
                    self._closed_tallies[attr] += getattr(old, attr)
            self.peers[q] = conn = _Conn(sock)
            # live connection (either direction) resets q's dial
            # backoff — under the lock, paired with dial_peer's
            # refused-path write, so a racing refusal can't re-grow
            # the window after this conn landed
            self._dial_fails.pop(q, None)
            self._dial_window.pop(q, None)
        if old is not None:
            try:
                old.sock.close()
            except OSError:
                pass
        j = self.journal
        if j is not None:
            j.record(EV_PEER_UP, subject=q)
        dlog(f"replica {self.me}: peer {q} connected")
        threading.Thread(target=self._read_loop,
                         args=(FROM_PEER, q, conn), daemon=True).start()

    def _read_loop(self, src_kind: int, conn_id: int, conn: _Conn) -> None:
        dec = StreamDecoder()
        sock = conn.sock
        while not self._stop.is_set():
            try:
                chunk = sock.recv(1 << 16)
            except OSError:
                break
            if not chunk:
                break
            # paxtrace ingress stamp: the decode span's t0 must cover
            # the frame parse, so the timestamp is taken before feed —
            # but only when a sink is installed AND enabled (disabled:
            # one attr load + test per chunk, no clock read)
            tr = self.trace
            t_dec0 = (monotonic_ns() if tr is not None and tr.enabled
                      and src_kind == FROM_CLIENT else 0)
            try:
                frames = dec.feed(chunk)
            except ValueError:
                break
            conn.bytes_in += len(chunk)
            conn.frames_in += len(frames)
            for kind, rows in frames:
                conn.rows_in += len(rows)
                if t_dec0 and kind == MsgKind.PROPOSE:
                    # one vectorized hash per propose frame; spans only
                    # for sampled commands (this reader thread's ring)
                    tr.stamp_batch(ST_DECODE, rows["cmd_id"], t_dec0,
                                   monotonic_ns())
                # paxchaos inbound gate, peer links only: the disabled
                # path is one attribute load + is-test per frame
                ch = self.chaos
                if ch is not None and src_kind == FROM_PEER:
                    ch.ingest(conn_id, kind, rows)
                else:
                    self.queue.put((src_kind, conn_id, kind, rows))
            if dec.error is not None:
                break
        conn.alive = False
        j = self.journal
        if (j is not None and src_kind == FROM_PEER
                and not self._stop.is_set()):
            # a peer link died mid-run (shutdown churn is not news)
            j.record(EV_PEER_DOWN, subject=conn_id)
        self.queue.put((CONN_LOST, conn_id if src_kind == FROM_CLIENT
                        else -1 - conn_id, None, None))
        try:
            sock.close()
        except OSError:
            pass

    # -- write (protocol thread only) --

    def send_peer(self, q: int, kind: MsgKind, rows: np.ndarray) -> bool:
        conn = self.peers.get(q)
        if conn is None or not conn.alive:
            return False
        # paxchaos outbound gate: a blocked link blackholes silently —
        # returning True models an asymmetric partition (the TCP
        # connection is up, the network eats the bytes) and keeps the
        # caller from spinning redials at a peer that IS alive
        ch = self.chaos
        if ch is not None and not ch.allow_send(q):
            return True
        try:
            conn.writer.write(kind, rows)
            conn.frames_out += 1
            return True
        except OSError:
            conn.alive = False
            return False

    def send_client(self, cid: int, kind: MsgKind, rows: np.ndarray) -> bool:
        conn = self.clients.get(cid)
        if conn is None or not conn.alive:
            return False
        try:
            conn.writer.write(kind, rows)
            conn.frames_out += 1
            return True
        except OSError:
            conn.alive = False
            return False

    def flush_all(self) -> None:
        with self._lock:
            conns = list(self.peers.items()) + list(self.clients.items())
        for _, conn in conns:
            if conn.alive:
                try:
                    conn.writer.flush()
                except OSError:
                    conn.alive = False

    def peer_alive(self, q: int) -> bool:
        conn = self.peers.get(q)
        return conn is not None and conn.alive
