"""Append-only durable log ("stable store") + replay.

Counterpart of the reference's per-replica ``stable-store-replica<id>``
file: 12-byte instance metadata + marshaled commands appended and
fsync'd per accept (bareminpaxos.go:164-197), replayed wholesale on
boot (getDataFromStableStore :122-161). Two deliberate upgrades:

* **Batched records.** One protocol tick persists every slot it
  accepted as one contiguous numpy write + one fsync, instead of a
  write+sync per instance.
* **Frontier records.** The reference never logs commit progress (a
  revived replica rediscovers it from the leader); we append a tiny
  frontier record when committed_upto advances so recovery can
  re-execute the committed prefix locally and the leader can serve
  beyond-window catch-up from its own log (models/minpaxos.py window
  slide LIMIT note).

The in-memory mirror (``self.slots``) doubles as the leader's
beyond-retention resync source: reads never touch disk.
"""

from __future__ import annotations

import os
import struct

import numpy as np

MAGIC = b"MPXL0001"

_COMMITTED = 4  # models/minpaxos.py status enum (kept import-free here)

# one record per accepted slot
SLOT_DT = np.dtype([
    ("inst", "<i4"), ("ballot", "<i4"), ("status", "u1"), ("op", "u1"),
    ("key", "<i8"), ("val", "<i8"), ("cmd_id", "<i4"), ("client_id", "<i4"),
])
_FRONTIER = struct.Struct("<i")  # committed_upto

REC_SLOTS = 1  # payload: u32 count + count*SLOT_DT
REC_FRONTIER = 2  # payload: i32
_HDR = struct.Struct("<BI")  # record type, payload bytes


class StableStore:
    """Durable redo log for one replica.

    File layout: MAGIC, then records of [type u8][len u32][payload].
    ``sync=False`` trades durability for speed (the reference's
    non--durable mode skips persistence entirely).
    """

    def __init__(self, path: str, sync: bool = True):
        self.path = path
        self.sync = sync
        existed = os.path.exists(path) and os.path.getsize(path) > len(MAGIC)
        self.slots: dict[int, np.void] = {}
        # insts recorded with status >= COMMITTED: commitment is final,
        # so re-appends of these slots are pure log amplification and
        # the runtime's _persist drops them (heal sweeps deliver R-1
        # duplicate COMMIT rows per slot)
        self.committed: set[int] = set()
        self._committed_arr: np.ndarray | None = None  # sorted cache
        # largest c with slot records 0..c all present — maintained
        # incrementally so committed_prefix()/is_committed() never walk
        # or sort the whole mirror
        self._contig = -1
        self.frontier = -1
        if existed:
            self._replay()
            self._f = open(path, "ab")
        else:
            self._f = open(path, "wb")
            self._f.write(MAGIC)
            self._f.flush()

    @property
    def recovered(self) -> bool:
        return bool(self.slots) or self.frontier >= 0

    # -- append --

    def append_slots(self, inst, ballot, status, op, key, val, cmd_id,
                     client_id) -> None:
        n = len(inst)
        if n == 0:
            return
        rec = np.zeros(n, SLOT_DT)
        rec["inst"], rec["ballot"], rec["status"] = inst, ballot, status
        rec["op"], rec["key"], rec["val"] = op, key, val
        rec["cmd_id"], rec["client_id"] = cmd_id, client_id
        payload = rec.tobytes()
        self._f.write(_HDR.pack(REC_SLOTS, len(payload)))
        self._f.write(payload)
        for r in rec:
            i = int(r["inst"])
            old = self.slots.get(i)
            if old is None or int(r["ballot"]) >= int(old["ballot"]):
                self.slots[i] = r.copy()
            if int(r["status"]) >= _COMMITTED:
                self.committed.add(i)
                self._committed_arr = None
        while (self._contig + 1) in self.slots:
            self._contig += 1

    def append_frontier(self, committed_upto: int) -> None:
        if committed_upto <= self.frontier:
            return
        self.frontier = committed_upto
        self._f.write(_HDR.pack(REC_FRONTIER, _FRONTIER.size))
        self._f.write(_FRONTIER.pack(committed_upto))
        # entries at/below min(contig, frontier) are covered by the
        # is_committed() prefix check — prune so the set stays small in
        # steady state instead of growing for the process lifetime
        if self.committed:
            covered = min(self._contig, self.frontier)
            pruned = {i for i in self.committed if i > covered}
            if len(pruned) != len(self.committed):
                self.committed = pruned
                self._committed_arr = None

    def flush(self) -> None:
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self.flush()
        finally:
            self._f.close()

    # -- read --

    def _replay(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        if data[: len(MAGIC)] != MAGIC:
            raise ValueError(f"{self.path}: bad magic")
        pos = len(MAGIC)
        while pos + _HDR.size <= len(data):
            rtype, plen = _HDR.unpack_from(data, pos)
            pos += _HDR.size
            if pos + plen > len(data):
                break  # torn tail write (crash mid-append): ignore
            if rtype == REC_SLOTS and plen % SLOT_DT.itemsize == 0:
                rec = np.frombuffer(data, SLOT_DT, plen // SLOT_DT.itemsize,
                                    pos)
                for r in rec:
                    i = int(r["inst"])
                    old = self.slots.get(i)
                    if old is None or int(r["ballot"]) >= int(old["ballot"]):
                        self.slots[i] = r.copy()
                    if int(r["status"]) >= _COMMITTED:
                        self.committed.add(i)
            elif rtype == REC_FRONTIER and plen == _FRONTIER.size:
                (fr,) = _FRONTIER.unpack_from(data, pos)
                self.frontier = max(self.frontier, fr)
            pos += plen
        while (self._contig + 1) in self.slots:
            self._contig += 1
        covered = min(self._contig, self.frontier)
        self.committed = {i for i in self.committed if i > covered}

    def is_committed(self, insts: np.ndarray) -> np.ndarray:
        """Vectorized: True where inst is already durably committed AND
        its record is present — at/below min(contiguous-records,
        frontier), or an explicit COMMITTED slot record. Slots below
        the frontier whose record is MISSING (torn write) report False
        so peers' re-sends self-heal the hole. Used by the runtime's
        _persist dedup; no per-row Python on the protocol thread."""
        insts = np.asarray(insts)
        out = insts <= min(self._contig, self.frontier)
        if self.committed:
            if (self._committed_arr is None
                    or len(self._committed_arr) != len(self.committed)):
                self._committed_arr = np.fromiter(
                    self.committed, np.int64, len(self.committed))
                self._committed_arr.sort()
            arr = self._committed_arr
            pos = np.searchsorted(arr, insts)
            pos_c = np.minimum(pos, len(arr) - 1)
            out = out | ((pos < len(arr)) & (arr[pos_c] == insts))
        return out

    def committed_prefix(self) -> int:
        """Largest f <= logged frontier with slots 0..f all present."""
        return min(self._contig, self.frontier)

    def read_range(self, lo: int, hi: int) -> np.ndarray:
        """Slot records for inst in [lo, hi] that exist, ascending —
        the leader's beyond-window catch-up source."""
        out = [self.slots[i] for i in range(lo, hi + 1) if i in self.slots]
        if not out:
            return np.zeros(0, SLOT_DT)
        return np.array(out, dtype=SLOT_DT)

    def max_inst(self) -> int:
        return max(self.slots) if self.slots else -1
