"""Append-only durable log ("stable store") + replay.

Counterpart of the reference's per-replica ``stable-store-replica<id>``
file: 12-byte instance metadata + marshaled commands appended and
fsync'd per accept (bareminpaxos.go:164-197), replayed wholesale on
boot (getDataFromStableStore :122-161). Two deliberate upgrades:

* **Batched records.** One protocol tick persists every slot it
  accepted as one contiguous numpy write + one fsync, instead of a
  write+sync per instance.
* **Frontier records.** The reference never logs commit progress (a
  revived replica rediscovers it from the leader); we append a tiny
  frontier record when committed_upto advances so recovery can
  re-execute the committed prefix locally and the leader can serve
  beyond-window catch-up from its own log (models/minpaxos.py window
  slide LIMIT note).

The in-memory mirror (a dense growable structured array — log slots
are dense integers) doubles as the leader's beyond-retention resync
source: reads never touch disk.
"""

from __future__ import annotations

import os
import struct
import sys
import zlib

import numpy as np

#: v1 framing: [type u8][len u32][payload] — no integrity check; a
#: flipped payload byte replayed as protocol state (silent divergence)
MAGIC_V1 = b"MPXL0001"
#: v2 framing (current): [type u8][len u32][crc u32][payload], crc =
#: crc32(header || payload). Replay SKIPS records whose CRC fails
#: (counted + warned) instead of ingesting flipped bytes; the holes
#: report not-committed, so peers' re-sends self-heal them. The magic
#: picks the framing per file: v1 files replay — and keep appending —
#: in v1 form, so an old log stays self-consistent.
MAGIC = b"MPXL0002"

_COMMITTED = 4  # models/minpaxos.py status enum (kept import-free here)

# one record per accepted slot
SLOT_DT = np.dtype([
    ("inst", "<i4"), ("ballot", "<i4"), ("status", "u1"), ("op", "u1"),
    ("key", "<i8"), ("val", "<i8"), ("cmd_id", "<i4"), ("client_id", "<i4"),
])
_FRONTIER = struct.Struct("<i")  # committed_upto

REC_SLOTS = 1  # payload: u32 count + count*SLOT_DT
REC_FRONTIER = 2  # payload: i32
#: snapshot of the APPLIED KV state at an exec frontier: payload is
#: [frontier i32][wall_ns i64][count u32] + count*SNAP_DT, CRC-framed
#: like every v2 record — a flipped byte fails the record CRC and
#: replay falls back to the previous retained snapshot (take_snapshot
#: keeps two) + a longer redo replay. Record-type tags are append-only
#: like wire opcodes (analysis/store_golden.py).
REC_SNAPSHOT = 3
_HDR = struct.Struct("<BI")  # record type, payload bytes
_CRC = struct.Struct("<I")  # v2 framing: crc32(header || payload)

#: one snapshot row: a live KV pair (key, value), sorted by key so the
#: same applied state always snapshots byte-identically regardless of
#: hash-table insertion order
SNAP_DT = np.dtype([("key", "<i8"), ("val", "<i8")])
_SNAP_HDR = struct.Struct("<iqI")  # frontier, wall_ns, pair count

#: rows per REC_SLOTS record when take_snapshot rewrites the suffix
_REWRITE_CHUNK = 8192

#: per-file cap on individually warned corrupt records (the tally
#: keeps counting; the terminal must not scroll a rotted disk forever)
_CORRUPT_WARN_CAP = 5


class StableStore:
    """Durable redo log for one replica.

    File layout: MAGIC, then records of [type u8][len u32][crc u32]
    [payload] (the crc field only under the v2 magic — see MAGIC_V1).
    ``sync=False`` trades durability for speed (the reference's
    non--durable mode skips persistence entirely).
    """

    def __init__(self, path: str, sync: bool = True):
        self.path = path
        self.sync = sync
        # a stale .tmp is a segment swap that died before its
        # os.replace: the original file is still authoritative
        try:
            os.unlink(path + ".tmp")
        except OSError:
            pass
        existed = os.path.exists(path) and os.path.getsize(path) > len(MAGIC)
        # mirror: log slots are DENSE integers, so the in-memory mirror
        # is a growable structured array + presence mask (34 B/slot,
        # vectorized update/read) rather than a dict of numpy scalars —
        # the per-row dict/.copy() loop was the hottest host path in a
        # tick profile
        self._mirror = np.zeros(0, SLOT_DT)
        self._have = np.zeros(0, bool)
        self._max_inst = -1
        # insts recorded with status >= COMMITTED: commitment is final,
        # so re-appends of these slots are pure log amplification and
        # the runtime's _persist drops them (heal sweeps deliver R-1
        # duplicate COMMIT rows per slot)
        self.committed: set[int] = set()
        self._committed_arr: np.ndarray | None = None  # sorted cache
        # largest c with slot records 0..c all present — maintained
        # incrementally so committed_prefix()/is_committed() never walk
        # or sort the whole mirror
        self._contig = -1
        self.frontier = -1
        # CRC-rejected records seen by _replay (surfaced as a paxmon
        # fn-gauge by the replica runtime)
        self.corrupt_records = 0
        # snapshot state. ``base``: highest slot covered by the
        # snapshot THIS replay started from (-1 = replayed the full
        # redo log) — slot records at/below it are not in the mirror
        # after a restart, so readers must treat [0, base] as
        # snapshot-covered. A LIVE take_snapshot never rebases the
        # mirror (disk is bounded, RAM stays complete), so base only
        # moves at restart.
        self.base = -1
        self.snap_frontier = -1  # newest retained snapshot's frontier
        self.snap_wall_ns = 0
        self.snapshot_pairs = np.zeros(0, SNAP_DT)
        self._snapshots: list[tuple[int, int, np.ndarray]] = []
        self.snapshots_taken = 0  # this process, not lifetime
        self.truncated_bytes = 0
        self._crashed = False
        # whether this FILE carries v2 per-record CRCs (decided by its
        # magic on replay; new files are always v2)
        self.crc_framing = True
        if existed:
            self._replay()
            # truncate the torn tail before appending: new records
            # written AFTER leftover partial-record bytes would be
            # swallowed into that record's length field on the next
            # replay (v1 could then silently mis-parse; v2 would skip
            # them as CRC garbage) — cut to the last record boundary
            self._f = open(path, "r+b")
            self._f.seek(self._parsed_end)
            self._f.truncate()
        else:
            self._f = open(path, "wb")
            self._f.write(MAGIC)
            self._f.flush()

    @property
    def recovered(self) -> bool:
        return self._max_inst >= 0 or self.frontier >= 0

    # -- append --

    def _ensure(self, upto: int) -> None:
        if upto < len(self._mirror):
            return
        cap = max(1024, 2 * len(self._mirror), upto + 1)
        mirror = np.zeros(cap, SLOT_DT)
        mirror[: len(self._mirror)] = self._mirror
        have = np.zeros(cap, bool)
        have[: len(self._have)] = self._have
        self._mirror, self._have = mirror, have

    def _update_mirror(self, rec: np.ndarray) -> None:
        """Apply one record batch to the mirror (ballot supersede)."""
        insts = rec["inst"].astype(np.int64)
        if int(insts.min()) < 0:
            # the mirror indexes by inst directly: a negative inst (a
            # padding row slipping through a caller's mask) would
            # wrap-index and silently overwrite the highest slots
            raise ValueError(
                f"stable store: negative inst in record batch "
                f"(min={int(insts.min())}) — caller mask bug")
        self._ensure(int(insts.max()))
        if len(np.unique(insts)) != len(insts):
            # same slot twice in one batch (e.g. ACCEPT + COMMIT in one
            # tick): supersede must see earlier rows' writes — rare, so
            # sequential
            for j in range(len(rec)):
                i = int(insts[j])
                if (not self._have[i]
                        or rec["ballot"][j] >= self._mirror["ballot"][i]):
                    self._mirror[i] = rec[j]
                    self._have[i] = True
        else:
            old_ballot = np.where(self._have[insts],
                                  self._mirror["ballot"][insts], -(2 ** 31))
            take = rec["ballot"] >= old_ballot
            self._mirror[insts[take]] = rec[take]
            self._have[insts[take]] = True
        self._max_inst = max(self._max_inst, int(insts.max()))
        cm = insts[rec["status"] >= _COMMITTED]
        if cm.size:
            self.committed.update(cm.tolist())
            self._committed_arr = None
        # advance the contiguous prefix in one scan of the newly
        # covered region (amortized O(1) per slot over the log's life);
        # bound the scan at _max_inst — everything past it is False, so
        # scanning the full doubled capacity would make this O(cap)
        start = self._contig + 1
        end = self._max_inst + 2
        if start < len(self._have) and self._have[start]:
            gap = np.nonzero(~self._have[start:end])[0]
            self._contig = (start + int(gap[0]) - 1) if gap.size else (
                self._max_inst)

    def append_slots(self, inst, ballot, status, op, key, val, cmd_id,
                     client_id) -> None:
        n = len(inst)
        if n == 0:
            return
        rec = np.zeros(n, SLOT_DT)
        rec["inst"], rec["ballot"], rec["status"] = inst, ballot, status
        rec["op"], rec["key"], rec["val"] = op, key, val
        rec["cmd_id"], rec["client_id"] = cmd_id, client_id
        self._write_record(REC_SLOTS, rec.tobytes())
        self._update_mirror(rec)

    def _write_record(self, rtype: int, payload: bytes) -> None:
        self._write_record_to(self._f, rtype, payload)

    def append_frontier(self, committed_upto: int) -> None:
        if committed_upto <= self.frontier:
            return
        self.frontier = committed_upto
        self._write_record(REC_FRONTIER, _FRONTIER.pack(committed_upto))
        # entries at/below min(contig, frontier) are covered by the
        # is_committed() prefix check — prune so the set stays small in
        # steady state instead of growing for the process lifetime
        if self.committed:
            covered = min(self._contig, self.frontier)
            pruned = {i for i in self.committed if i > covered}
            if len(pruned) != len(self.committed):
                self.committed = pruned
                self._committed_arr = None

    def flush(self) -> None:
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self.flush()
        finally:
            self._f.close()

    def crash(self) -> None:
        """Emulate a process kill for fault injection: everything in
        the userspace write buffer is LOST (like a SIGKILLed process's
        unflushed stdio), the on-disk file keeps only what already
        reached the kernel — possibly ending in a torn record. Further
        appends/flushes land in /dev/null so the protocol thread dies
        quietly instead of racing a closed fd."""
        self._crashed = True
        self.sync = False  # /dev/null rejects fsync on some kernels
        try:
            devnull = os.open(os.devnull, os.O_WRONLY)
            # dup2 swaps the underlying fd: the buffered writer's
            # pending bytes flush into /dev/null on close — gone, as
            # they would be for a real kill
            os.dup2(devnull, self._f.fileno())
            os.close(devnull)
        except OSError:
            pass

    def log_bytes(self) -> int:
        """Current on-disk size — the bound truncation maintains
        (paxmon fn-gauge; safe to call from the control thread)."""
        try:
            return os.path.getsize(self.path)
        except OSError:
            return 0

    def snap_bytes(self) -> int:
        """Bytes the retained snapshots occupy on disk (framing incl.)."""
        per = _HDR.size + (_CRC.size if self.crc_framing else 0) + \
            _SNAP_HDR.size
        return sum(per + len(p) * SNAP_DT.itemsize
                   for _, _, p in self._snapshots)

    def take_snapshot(self, keys, vals, frontier: int,
                      wall_ns: int = 0) -> int:
        """Checkpoint the applied KV state at ``frontier`` and truncate
        the redo log below the PREVIOUS snapshot's frontier, as one
        atomic segment swap (write ``.tmp``, fsync, ``os.replace``).

        Retains the last TWO snapshots: redo records in
        (prev_frontier, new_frontier] stay in the file, so a corrupt
        newest snapshot (bit rot, torn swap tail) falls back to the
        previous one + a longer replay instead of diverging. The first
        snapshot therefore truncates nothing. The in-RAM mirror is NOT
        rebased — only disk is bounded; a live replica keeps serving
        full-history catch-up from memory.

        Returns bytes freed on disk (may be negative right after the
        first snapshot), or -1 when refused (v1 file — no CRC framing
        to protect the snapshot — or a crashed/invalid store).
        """
        if self._crashed or frontier < 0 or not self.crc_framing:
            return -1
        keys = np.asarray(keys, np.int64)
        vals = np.asarray(vals, np.int64)
        pairs = np.zeros(len(keys), SNAP_DT)
        order = np.argsort(keys, kind="stable")
        pairs["key"], pairs["val"] = keys[order], vals[order]
        prev = self._snapshots[-1] if self._snapshots else None
        keep_above = prev[0] if prev else -1
        if self._contig < frontier:
            # a snapshot AHEAD of the log we hold (wire catch-up
            # installing onto a wiped or lagging replica, never a
            # replica checkpointing its own applied state): slots
            # [0, frontier] become snapshot-covered — rebase exactly
            # as a restart replay would, so committed_prefix() and the
            # catch-up readers stay truthful on the live store
            self.base = max(self.base, frontier)
            self._contig = frontier
            start, end = frontier + 1, self._max_inst + 2
            if start < len(self._have) and self._have[start]:
                gap = np.nonzero(~self._have[start:end])[0]
                self._contig = (start + int(gap[0]) - 1) if gap.size \
                    else self._max_inst
        self.frontier = max(self.frontier, frontier)
        self._max_inst = max(self._max_inst, frontier)
        # buffered appends must reach the file before its size is the
        # "before" of the freed-bytes accounting (and before close()
        # would flush them into the about-to-be-replaced file anyway)
        self._f.flush()
        old_size = self.log_bytes()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as tf:
            tf.write(MAGIC)
            for f_s, w_ns, p in ([prev] if prev else []):
                self._write_snapshot(tf, f_s, w_ns, p)
            self._write_snapshot(tf, frontier, wall_ns, pairs)
            hi = self._max_inst
            rows = (self._mirror[: hi + 1][self._have[: hi + 1]]
                    if hi >= 0 else np.zeros(0, SLOT_DT))
            rows = rows[rows["inst"] > keep_above]
            for i in range(0, len(rows), _REWRITE_CHUNK):
                chunk = rows[i: i + _REWRITE_CHUNK]
                self._write_record_to(tf, REC_SLOTS, chunk.tobytes())
            if self.frontier >= 0:
                self._write_record_to(tf, REC_FRONTIER,
                                      _FRONTIER.pack(self.frontier))
            tf.flush()
            os.fsync(tf.fileno())
        # the swap: old file stays authoritative until the replace
        # lands (a crash between fsync and replace leaves a stale .tmp
        # that __init__ discards)
        self._f.close()
        os.replace(tmp, self.path)
        try:
            dfd = os.open(os.path.dirname(self.path) or ".", os.O_RDONLY)
            os.fsync(dfd)
            os.close(dfd)
        except OSError:
            pass
        self._f = open(self.path, "ab")
        self._snapshots = ([prev] if prev else []) + [
            (frontier, wall_ns, pairs)]
        self.snap_frontier = frontier
        self.snap_wall_ns = wall_ns
        self.snapshot_pairs = pairs
        self.snapshots_taken += 1
        freed = old_size - self.log_bytes()
        self.truncated_bytes += max(0, freed)
        return freed

    def _write_snapshot(self, f, frontier: int, wall_ns: int,
                        pairs: np.ndarray) -> None:
        payload = _SNAP_HDR.pack(frontier, wall_ns, len(pairs)) + \
            pairs.tobytes()
        self._write_record_to(f, REC_SNAPSHOT, payload)

    def _write_record_to(self, f, rtype: int, payload: bytes) -> None:
        hdr = _HDR.pack(rtype, len(payload))
        f.write(hdr)
        if self.crc_framing:
            f.write(_CRC.pack(zlib.crc32(payload, zlib.crc32(hdr))))
        f.write(payload)

    # -- read --

    @staticmethod
    def _resync(data: bytes, start: int) -> int | None:
        """Scan past a corrupt length field (v2 framing only) for the
        next whole-record boundary: an offset qualifies iff its header
        is plausible AND its CRC validates, so a false positive is a
        2^-32 coincidence. Runs only on corruption, never on the clean
        replay path. Returns None when no record follows — i.e. the
        unparseable region really is a torn tail."""
        end = len(data)
        off = start + 1
        while off + _HDR.size + _CRC.size <= end:
            rtype, plen = _HDR.unpack_from(data, off)
            body = off + _HDR.size + _CRC.size
            if (rtype in (REC_SLOTS, REC_FRONTIER, REC_SNAPSHOT)
                    and body + plen <= end):
                (crc,) = _CRC.unpack_from(data, off + _HDR.size)
                want = zlib.crc32(data[body: body + plen],
                                  zlib.crc32(data[off: off + _HDR.size]))
                if crc == want:
                    return off
            off += 1
        return None

    def _replay(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        magic = data[: len(MAGIC)]
        if magic == MAGIC:
            crc_framing = True
        elif magic == MAGIC_V1:
            crc_framing = False  # pre-CRC log: replay + append as v1
        else:
            raise ValueError(f"{self.path}: bad magic")
        self.crc_framing = crc_framing
        pos = len(MAGIC)
        self._parsed_end = pos  # last whole-record boundary reached
        snaps: list[tuple[int, int, np.ndarray]] = []
        while pos + _HDR.size <= len(data):
            rtype, plen = _HDR.unpack_from(data, pos)
            body = pos + _HDR.size + (_CRC.size if crc_framing else 0)
            if body + plen > len(data):
                # the declared record runs past EOF. A genuine torn
                # tail (crash mid-append) looks exactly like a flipped
                # LENGTH byte mid-file — but __init__ TRUNCATES at
                # _parsed_end, so treating the latter as a tail would
                # destroy every valid record after it. Resync on the
                # next CRC-valid record boundary: found ⇒ mid-file
                # corruption, skip the garbage; not found ⇒ real tail
                nxt = self._resync(data, pos) if crc_framing else None
                if nxt is None:
                    break  # torn tail write (crash mid-append): ignore
                self.corrupt_records += 1
                if self.corrupt_records <= _CORRUPT_WARN_CAP:
                    print(f"{self.path}: corrupt length field at byte "
                          f"{pos} — resynced at {nxt}, "
                          f"{nxt - pos} B skipped; holes self-heal "
                          f"from peers", file=sys.stderr, flush=True)
                pos = nxt
                self._parsed_end = pos
                continue
            if crc_framing:
                (crc,) = _CRC.unpack_from(data, pos + _HDR.size)
                want = zlib.crc32(data[body: body + plen],
                                  zlib.crc32(data[pos: pos + _HDR.size]))
                if crc != want:
                    # flipped bytes: SKIP the record instead of
                    # ingesting it — the resulting slot holes report
                    # not-committed (is_committed) and peers' re-sends
                    # heal them. A corrupted in-file length field
                    # desyncs the skip and cascades CRC failures until
                    # a garbage header points past EOF, where the
                    # resync above recovers the remaining records.
                    self.corrupt_records += 1
                    if self.corrupt_records <= _CORRUPT_WARN_CAP:
                        print(f"{self.path}: CRC mismatch at byte "
                              f"{pos} (record type {rtype}, "
                              f"{plen} B) — record skipped; holes "
                              f"self-heal from peers",
                              file=sys.stderr, flush=True)
                    pos = body + plen
                    self._parsed_end = pos
                    continue
            if rtype == REC_SLOTS and plen % SLOT_DT.itemsize == 0:
                n = plen // SLOT_DT.itemsize
                if n:
                    self._update_mirror(np.frombuffer(data, SLOT_DT, n, body))
            elif rtype == REC_FRONTIER and plen == _FRONTIER.size:
                (fr,) = _FRONTIER.unpack_from(data, body)
                self.frontier = max(self.frontier, fr)
            elif rtype == REC_SNAPSHOT and plen >= _SNAP_HDR.size:
                f_s, w_ns, cnt = _SNAP_HDR.unpack_from(data, body)
                if plen == _SNAP_HDR.size + cnt * SNAP_DT.itemsize:
                    pairs = np.frombuffer(
                        data, SNAP_DT, cnt, body + _SNAP_HDR.size).copy()
                    snaps.append((f_s, w_ns, pairs))
            pos = body + plen
            self._parsed_end = pos
        if self.corrupt_records > _CORRUPT_WARN_CAP:
            print(f"{self.path}: {self.corrupt_records} corrupt records "
                  f"skipped in total", file=sys.stderr, flush=True)
        if snaps:
            # the newest CRC-valid snapshot is the replay base — a
            # corrupt newest one never reached ``snaps`` (its record
            # was skipped above), so the fallback to the previous
            # snapshot + a longer redo replay happens here for free
            snaps.sort(key=lambda s: s[0])
            f_s, w_ns, pairs = snaps[-1]
            self._snapshots = snaps[-2:]
            self.base = f_s
            self.snap_frontier = f_s
            self.snap_wall_ns = w_ns
            self.snapshot_pairs = pairs
            self.frontier = max(self.frontier, f_s)
            self._max_inst = max(self._max_inst, f_s)
            if self._contig < f_s:
                # slots [0, base] are snapshot-covered: restart the
                # contiguity scan just above the base
                self._contig = f_s
                start, end = f_s + 1, self._max_inst + 2
                if start < len(self._have) and self._have[start]:
                    gap = np.nonzero(~self._have[start:end])[0]
                    self._contig = (start + int(gap[0]) - 1) if gap.size \
                        else self._max_inst
        covered = min(self._contig, self.frontier)
        self.committed = {i for i in self.committed if i > covered}

    def is_committed(self, insts: np.ndarray) -> np.ndarray:
        """Vectorized: True where inst is already durably committed AND
        its record is present — at/below min(contiguous-records,
        frontier), or an explicit COMMITTED slot record. Slots below
        the frontier whose record is MISSING (torn write) report False
        so peers' re-sends self-heal the hole. Used by the runtime's
        _persist dedup; no per-row Python on the protocol thread."""
        insts = np.asarray(insts)
        out = insts <= min(self._contig, self.frontier)
        if self.committed:
            if (self._committed_arr is None
                    or len(self._committed_arr) != len(self.committed)):
                self._committed_arr = np.fromiter(
                    self.committed, np.int64, len(self.committed))
                self._committed_arr.sort()
            arr = self._committed_arr
            pos = np.searchsorted(arr, insts)
            pos_c = np.minimum(pos, len(arr) - 1)
            out = out | ((pos < len(arr)) & (arr[pos_c] == insts))
        return out

    def committed_prefix(self) -> int:
        """Largest f <= logged frontier with slots 0..f all present."""
        return min(self._contig, self.frontier)

    def read_range(self, lo: int, hi: int) -> np.ndarray:
        """Slot records for inst in [lo, hi] that exist, ascending —
        the leader's beyond-window catch-up source. One mirror slice."""
        lo = max(lo, 0)
        hi = min(hi, len(self._mirror) - 1)
        if hi < lo:
            return np.zeros(0, SLOT_DT)
        sl = slice(lo, hi + 1)
        return self._mirror[sl][self._have[sl]]  # mask index = fresh array

    def max_inst(self) -> int:
        return self._max_inst

    def max_ballot(self) -> int:
        """Highest ballot among recorded slots (recovery's promise
        restore, bareminpaxos.go:383-385)."""
        if self._max_inst < 0:
            return 0
        return int(self._mirror["ballot"][self._have].max(initial=0))
