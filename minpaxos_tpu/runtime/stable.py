"""Append-only durable log ("stable store") + replay.

Counterpart of the reference's per-replica ``stable-store-replica<id>``
file: 12-byte instance metadata + marshaled commands appended and
fsync'd per accept (bareminpaxos.go:164-197), replayed wholesale on
boot (getDataFromStableStore :122-161). Two deliberate upgrades:

* **Batched records.** One protocol tick persists every slot it
  accepted as one contiguous numpy write + one fsync, instead of a
  write+sync per instance.
* **Frontier records.** The reference never logs commit progress (a
  revived replica rediscovers it from the leader); we append a tiny
  frontier record when committed_upto advances so recovery can
  re-execute the committed prefix locally and the leader can serve
  beyond-window catch-up from its own log (models/minpaxos.py window
  slide LIMIT note).

The in-memory mirror (a dense growable structured array — log slots
are dense integers) doubles as the leader's beyond-retention resync
source: reads never touch disk.
"""

from __future__ import annotations

import os
import struct

import numpy as np

MAGIC = b"MPXL0001"

_COMMITTED = 4  # models/minpaxos.py status enum (kept import-free here)

# one record per accepted slot
SLOT_DT = np.dtype([
    ("inst", "<i4"), ("ballot", "<i4"), ("status", "u1"), ("op", "u1"),
    ("key", "<i8"), ("val", "<i8"), ("cmd_id", "<i4"), ("client_id", "<i4"),
])
_FRONTIER = struct.Struct("<i")  # committed_upto

REC_SLOTS = 1  # payload: u32 count + count*SLOT_DT
REC_FRONTIER = 2  # payload: i32
_HDR = struct.Struct("<BI")  # record type, payload bytes


class StableStore:
    """Durable redo log for one replica.

    File layout: MAGIC, then records of [type u8][len u32][payload].
    ``sync=False`` trades durability for speed (the reference's
    non--durable mode skips persistence entirely).
    """

    def __init__(self, path: str, sync: bool = True):
        self.path = path
        self.sync = sync
        existed = os.path.exists(path) and os.path.getsize(path) > len(MAGIC)
        # mirror: log slots are DENSE integers, so the in-memory mirror
        # is a growable structured array + presence mask (34 B/slot,
        # vectorized update/read) rather than a dict of numpy scalars —
        # the per-row dict/.copy() loop was the hottest host path in a
        # tick profile
        self._mirror = np.zeros(0, SLOT_DT)
        self._have = np.zeros(0, bool)
        self._max_inst = -1
        # insts recorded with status >= COMMITTED: commitment is final,
        # so re-appends of these slots are pure log amplification and
        # the runtime's _persist drops them (heal sweeps deliver R-1
        # duplicate COMMIT rows per slot)
        self.committed: set[int] = set()
        self._committed_arr: np.ndarray | None = None  # sorted cache
        # largest c with slot records 0..c all present — maintained
        # incrementally so committed_prefix()/is_committed() never walk
        # or sort the whole mirror
        self._contig = -1
        self.frontier = -1
        if existed:
            self._replay()
            self._f = open(path, "ab")
        else:
            self._f = open(path, "wb")
            self._f.write(MAGIC)
            self._f.flush()

    @property
    def recovered(self) -> bool:
        return self._max_inst >= 0 or self.frontier >= 0

    # -- append --

    def _ensure(self, upto: int) -> None:
        if upto < len(self._mirror):
            return
        cap = max(1024, 2 * len(self._mirror), upto + 1)
        mirror = np.zeros(cap, SLOT_DT)
        mirror[: len(self._mirror)] = self._mirror
        have = np.zeros(cap, bool)
        have[: len(self._have)] = self._have
        self._mirror, self._have = mirror, have

    def _update_mirror(self, rec: np.ndarray) -> None:
        """Apply one record batch to the mirror (ballot supersede)."""
        insts = rec["inst"].astype(np.int64)
        if int(insts.min()) < 0:
            # the mirror indexes by inst directly: a negative inst (a
            # padding row slipping through a caller's mask) would
            # wrap-index and silently overwrite the highest slots
            raise ValueError(
                f"stable store: negative inst in record batch "
                f"(min={int(insts.min())}) — caller mask bug")
        self._ensure(int(insts.max()))
        if len(np.unique(insts)) != len(insts):
            # same slot twice in one batch (e.g. ACCEPT + COMMIT in one
            # tick): supersede must see earlier rows' writes — rare, so
            # sequential
            for j in range(len(rec)):
                i = int(insts[j])
                if (not self._have[i]
                        or rec["ballot"][j] >= self._mirror["ballot"][i]):
                    self._mirror[i] = rec[j]
                    self._have[i] = True
        else:
            old_ballot = np.where(self._have[insts],
                                  self._mirror["ballot"][insts], -(2 ** 31))
            take = rec["ballot"] >= old_ballot
            self._mirror[insts[take]] = rec[take]
            self._have[insts[take]] = True
        self._max_inst = max(self._max_inst, int(insts.max()))
        cm = insts[rec["status"] >= _COMMITTED]
        if cm.size:
            self.committed.update(cm.tolist())
            self._committed_arr = None
        # advance the contiguous prefix in one scan of the newly
        # covered region (amortized O(1) per slot over the log's life);
        # bound the scan at _max_inst — everything past it is False, so
        # scanning the full doubled capacity would make this O(cap)
        start = self._contig + 1
        end = self._max_inst + 2
        if start < len(self._have) and self._have[start]:
            gap = np.nonzero(~self._have[start:end])[0]
            self._contig = (start + int(gap[0]) - 1) if gap.size else (
                self._max_inst)

    def append_slots(self, inst, ballot, status, op, key, val, cmd_id,
                     client_id) -> None:
        n = len(inst)
        if n == 0:
            return
        rec = np.zeros(n, SLOT_DT)
        rec["inst"], rec["ballot"], rec["status"] = inst, ballot, status
        rec["op"], rec["key"], rec["val"] = op, key, val
        rec["cmd_id"], rec["client_id"] = cmd_id, client_id
        payload = rec.tobytes()
        self._f.write(_HDR.pack(REC_SLOTS, len(payload)))
        self._f.write(payload)
        self._update_mirror(rec)

    def append_frontier(self, committed_upto: int) -> None:
        if committed_upto <= self.frontier:
            return
        self.frontier = committed_upto
        self._f.write(_HDR.pack(REC_FRONTIER, _FRONTIER.size))
        self._f.write(_FRONTIER.pack(committed_upto))
        # entries at/below min(contig, frontier) are covered by the
        # is_committed() prefix check — prune so the set stays small in
        # steady state instead of growing for the process lifetime
        if self.committed:
            covered = min(self._contig, self.frontier)
            pruned = {i for i in self.committed if i > covered}
            if len(pruned) != len(self.committed):
                self.committed = pruned
                self._committed_arr = None

    def flush(self) -> None:
        self._f.flush()
        if self.sync:
            os.fsync(self._f.fileno())

    def close(self) -> None:
        try:
            self.flush()
        finally:
            self._f.close()

    # -- read --

    def _replay(self) -> None:
        with open(self.path, "rb") as f:
            data = f.read()
        if data[: len(MAGIC)] != MAGIC:
            raise ValueError(f"{self.path}: bad magic")
        pos = len(MAGIC)
        while pos + _HDR.size <= len(data):
            rtype, plen = _HDR.unpack_from(data, pos)
            pos += _HDR.size
            if pos + plen > len(data):
                break  # torn tail write (crash mid-append): ignore
            if rtype == REC_SLOTS and plen % SLOT_DT.itemsize == 0:
                n = plen // SLOT_DT.itemsize
                if n:
                    self._update_mirror(np.frombuffer(data, SLOT_DT, n, pos))
            elif rtype == REC_FRONTIER and plen == _FRONTIER.size:
                (fr,) = _FRONTIER.unpack_from(data, pos)
                self.frontier = max(self.frontier, fr)
            pos += plen
        covered = min(self._contig, self.frontier)
        self.committed = {i for i in self.committed if i > covered}

    def is_committed(self, insts: np.ndarray) -> np.ndarray:
        """Vectorized: True where inst is already durably committed AND
        its record is present — at/below min(contiguous-records,
        frontier), or an explicit COMMITTED slot record. Slots below
        the frontier whose record is MISSING (torn write) report False
        so peers' re-sends self-heal the hole. Used by the runtime's
        _persist dedup; no per-row Python on the protocol thread."""
        insts = np.asarray(insts)
        out = insts <= min(self._contig, self.frontier)
        if self.committed:
            if (self._committed_arr is None
                    or len(self._committed_arr) != len(self.committed)):
                self._committed_arr = np.fromiter(
                    self.committed, np.int64, len(self.committed))
                self._committed_arr.sort()
            arr = self._committed_arr
            pos = np.searchsorted(arr, insts)
            pos_c = np.minimum(pos, len(arr) - 1)
            out = out | ((pos < len(arr)) & (arr[pos_c] == insts))
        return out

    def committed_prefix(self) -> int:
        """Largest f <= logged frontier with slots 0..f all present."""
        return min(self._contig, self.frontier)

    def read_range(self, lo: int, hi: int) -> np.ndarray:
        """Slot records for inst in [lo, hi] that exist, ascending —
        the leader's beyond-window catch-up source. One mirror slice."""
        lo = max(lo, 0)
        hi = min(hi, len(self._mirror) - 1)
        if hi < lo:
            return np.zeros(0, SLOT_DT)
        sl = slice(lo, hi + 1)
        return self._mirror[sl][self._have[sl]]  # mask index = fresh array

    def max_inst(self) -> int:
        return self._max_inst

    def max_ballot(self) -> int:
        """Highest ballot among recorded slots (recovery's promise
        restore, bareminpaxos.go:383-385)."""
        if self._max_inst < 0:
            return 0
        return int(self._mirror["ballot"][self._have].max(initial=0))
