"""Distributed host runtime: real processes over real TCP.

The deployment shape of the reference (SURVEY.md sections 2.1, 3.1):
one OS process per replica, a master process for registration /
liveness / leader election, benchmark clients speaking the framed wire
protocol straight to replicas. The difference is what sits inside the
replica process: instead of a goroutine per message, a single
protocol thread drains sockets into fixed-shape columnar batches and
advances the whole log with one jitted ``replica_step`` per tick
(models/minpaxos.py) — the same kernel the pod-mode cluster and the
sharded mesh composition use.

Modules:

* batches   — frame rows <-> device MsgBatch columns
* stable    — append-only durable log + replay (checkpoint/resume)
* transport — peer mesh, client listener, handshake, reconnect
* replica   — the replica server process (event loop)
* master    — registration/ping/election service
* client    — benchmark client library (failover, -check)
"""
