"""Host-side packing: wire frames <-> device MsgBatch columns.

The device consumes ``MsgBatch`` — 12 parallel i32 columns, one row per
log slot touched (models/minpaxos.py). The wire carries structured
frames (wire/messages.py). This module is the boundary: decoded frames
append into a column buffer that becomes the next step's inbox; outbox
rows flatten back into frames per destination.

Counterpart of the reference's per-message Marshal/Unmarshal +
channel-dispatch plumbing (genericsmr.go:402-446 and the *marsh.go
files); here a 5000-row Accept frame becomes 5000 device rows with a
handful of numpy column copies.

AcceptReply compression is kernel-native (round 4): the device emits
one ACCEPT_REPLY row per contiguous run with the run length in cmd_id
(like the reference's batched AcceptReply covering a whole Accept
batch, minpaxosproto.go:75-80), and consumes ranges the same way — so
this boundary maps count <-> cmd_id 1:1 in both directions with no
expansion.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from minpaxos_tpu.obs.metrics import MetricsRegistry
from minpaxos_tpu.ops.packed import join_i64, split_i64
from minpaxos_tpu.wire.messages import MsgKind, make_batch

COLS = ("kind", "src", "ballot", "inst", "last_committed", "op",
        "key_hi", "key_lo", "val_hi", "val_lo", "cmd_id", "client_id")

#: mirrors transport.FROM_CLIENT (transport imports nothing from here's
#: coalescer, but keeping the literal avoids a runtime import cycle;
#: the wire ledger pins the queue item protocol, not this module)
_FROM_CLIENT = 1

#: per-drain coalesced-row buckets for the occupancy histogram —
#: powers of two up to the largest inbox the shape ladder drives
COALESCE_ROW_BUCKETS: tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048)


class IngressCoalescer:
    """Event-driven ingress front for the protocol thread's inbox queue.

    Drop-in replacement for the ``queue.Queue`` the transport's reader
    threads feed (``put`` / ``get(timeout=...)`` / ``get_nowait`` /
    ``empty`` / ``qsize`` — the whole surface replica.py touches),
    injected via ``Transport(inbox_queue=...)``. Three behaviors turn
    the cadence-driven poll loop into an event-driven one:

    * **Condition-variable kick** — ``put`` notifies a blocked getter
      immediately, so queued traffic wakes the tick loop the moment
      rows arrive instead of riding out the poll sleep (the
      ``work_pending`` idle fast path is untouched: an idle replica
      still parks on the long timeout).
    * **Batch formation (max-wait µs / max-rows)** — once the first
      item lands, the blocking ``get`` lingers up to ``max_wait_us``
      for more client PROPOSE rows (stopping early at ``max_rows``),
      coalescing many small client writes into one device-sized
      proposal batch: one dispatch amortizes its fixed cost over the
      concurrent sessions instead of paying it per connection. A
      linger that times out short of ``max_rows`` counts a
      ``deadline_hit`` (the lone-serial-command case: it pays
      ``max_wait_us``, not a poll interval). ``max_wait_us=0``
      disables lingering entirely.
    * **Admission control** — when ``admit_gate`` (wired by the
      replica to the paxmon exec-backlog bound and the paxwatch
      burn-rate detector) reports overload AND the pending client rows
      already exceed ``max_rows``, new PROPOSE frames are dropped at
      ingress and counted (legal: Paxos tolerates loss, clients retry
      with the same cmd_id) — overload degrades to bounded queueing
      instead of an unbounded tail.

    Lock discipline (paxlint's concurrency pass checks it): every
    mutation happens under the wakeup condition variable, and nothing
    blocking — no socket ops, no sleeps — ever runs while holding it;
    ``wait`` releases the lock by construction. Peer frames, CONTROL
    verbs and CONN_LOST notices pass straight through in arrival
    order; only client PROPOSE rows participate in row accounting.
    """

    def __init__(self, max_wait_us: int = 200, max_rows: int = 256,
                 admit_gate=None, metrics: MetricsRegistry | None = None):
        self.max_wait_us = max_wait_us
        self.max_rows = max_rows
        self._admit_gate = admit_gate
        self._items: list = []
        self._cv = threading.Condition()
        self._pending_rows = 0  # client PROPOSE rows queued
        self._waiting = 0       # getters currently blocked
        self.last_occupancy = 0  # rows coalesced by the newest drain
        self.metrics = metrics if metrics is not None else MetricsRegistry(
            namespace="coalescer")
        self._c_wakeups = self.metrics.counter(
            "coalesce_wakeups", "puts that kicked a blocked tick loop "
            "awake (the event-driven path; 0 means the loop never "
            "slept while traffic arrived)")
        self._c_deadline_hits = self.metrics.counter(
            "coalesce_deadline_hits", "batch-formation lingers that "
            "timed out at max_wait_us short of max_rows (the lone "
            "serial command's bounded wait)")
        self._c_rejects = self.metrics.counter(
            "coalesce_admission_rejects", "client PROPOSE rows dropped "
            "at ingress under overload (exec-backlog / burn-rate "
            "gate) — clients retry with the same cmd_id")
        self._h_batch = self.metrics.histogram(
            "coalesce_batch_rows", "client rows coalesced per blocking "
            "drain", bounds=COALESCE_ROW_BUCKETS)
        self.metrics.fn_gauge("coalesce_pending_rows",
                              lambda: self._pending_rows)

    @staticmethod
    def _client_rows(item) -> int:
        """Row count when the item is a client PROPOSE frame, else 0."""
        src_kind, _conn, kind, rows = item
        if (src_kind == _FROM_CLIENT and kind == MsgKind.PROPOSE
                and rows is not None):
            return len(rows)
        return 0

    # -- producer side (transport reader threads, control threads) --

    def put(self, item, block: bool = True,
            timeout: float | None = None) -> None:
        n = self._client_rows(item)
        with self._cv:
            if (n > 0 and self._admit_gate is not None
                    and self._pending_rows + n > self.max_rows
                    and self._admit_gate()):
                self._c_rejects.inc(n)
                return
            self._items.append(item)
            self._pending_rows += n
            if self._waiting:
                self._c_wakeups.inc()
            self._cv.notify()

    # -- consumer side (the protocol thread only) --

    def get(self, block: bool = True, timeout: float | None = None):
        with self._cv:
            if not self._items:
                if not block:
                    raise queue.Empty
                deadline = (None if timeout is None
                            else time.monotonic() + timeout)
                self._waiting += 1
                try:
                    while not self._items:
                        left = (None if deadline is None
                                else deadline - time.monotonic())
                        if left is not None and left <= 0:
                            raise queue.Empty
                        self._cv.wait(left)
                finally:
                    self._waiting -= 1
            # batch formation: linger for more client rows, bounded by
            # max_wait_us (deadline hit) or max_rows (early dispatch)
            if self.max_wait_us > 0 and 0 < self._pending_rows < self.max_rows:
                t_end = time.monotonic() + self.max_wait_us / 1e6
                while 0 < self._pending_rows < self.max_rows:
                    left = t_end - time.monotonic()
                    if left <= 0:
                        self._c_deadline_hits.inc()
                        break
                    self._cv.wait(left)
            self.last_occupancy = self._pending_rows
            if self._pending_rows > 0:
                self._h_batch.observe(self._pending_rows)
            return self._pop_locked()

    def get_nowait(self):
        with self._cv:
            if not self._items:
                raise queue.Empty
            return self._pop_locked()

    def _pop_locked(self):
        item = self._items.pop(0)
        self._pending_rows -= self._client_rows(item)
        return item

    def empty(self) -> bool:
        with self._cv:
            return not self._items

    def qsize(self) -> int:
        with self._cv:
            return len(self._items)


class ColumnBuffer:
    """Grows rows of MsgBatch columns; drained once per protocol tick."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.cols = {c: np.zeros(capacity, np.int32) for c in COLS}
        self.fill = 0
        self.dropped = 0

    def room(self) -> int:
        return self.capacity - self.fill

    def append(self, n: int, **cols) -> None:
        """Append n rows; unspecified columns stay zero. Overflow rows
        are dropped (legal: Paxos tolerates loss; peers retry)."""
        n_take = min(n, self.room())
        self.dropped += n - n_take
        if n_take <= 0:
            return
        sl = slice(self.fill, self.fill + n_take)
        for name, v in cols.items():
            a = np.asarray(v)
            self.cols[name][sl] = a[:n_take] if a.ndim else a
        self.fill += n_take

    def drain(self) -> tuple[dict, int]:
        """Return (columns, n_rows) and reset. Columns are the full
        capacity-size arrays (zero-padded past n_rows) so the device
        sees a fixed shape — no recompiles."""
        out, n = self.cols, self.fill
        self.cols = {c: np.zeros(self.capacity, np.int32) for c in COLS}
        self.fill = 0
        return out, n


def frame_to_rows(buf: ColumnBuffer, kind: MsgKind, rows: np.ndarray,
                  conn_id: int) -> None:
    """Append one decoded frame's rows into the inbox column buffer.

    ``conn_id``: for client frames, the server-assigned connection id
    (becomes client_id); for peer frames, unused (frames carry ids).
    """
    n = len(rows)
    if n == 0:
        return
    k = int(kind)
    if kind == MsgKind.PROPOSE:
        k_hi, k_lo = split_i64(rows["key"])
        v_hi, v_lo = split_i64(rows["val"])
        buf.append(n, kind=k, src=-1, op=rows["op"].astype(np.int32),
                   key_hi=k_hi, key_lo=k_lo, val_hi=v_hi, val_lo=v_lo,
                   cmd_id=rows["cmd_id"], client_id=conn_id)
    elif kind in (MsgKind.ACCEPT, MsgKind.COMMIT):
        k_hi, k_lo = split_i64(rows["key"])
        v_hi, v_lo = split_i64(rows["val"])
        buf.append(n, kind=k, src=rows["leader_id"].astype(np.int32),
                   ballot=rows["ballot"], inst=rows["inst"],
                   last_committed=rows["last_committed"],
                   op=rows["op"].astype(np.int32),
                   key_hi=k_hi, key_lo=k_lo, val_hi=v_hi, val_lo=v_lo,
                   cmd_id=rows["cmd_id"], client_id=rows["client_id"])
    elif kind == MsgKind.ACCEPT_REPLY:
        # (inst, count) runs pass straight through: the kernel consumes
        # ranges natively (count rides the cmd_id column; vote coverage
        # via difference array + prefix sum in step 6 / mencius step 5).
        # The old per-slot re-expansion would undo the compression and
        # re-inflate the inbox by the ack factor.
        buf.append(n, kind=k, src=rows["id"].astype(np.int32),
                   ballot=rows["ballot"], inst=rows["inst"],
                   last_committed=rows["last_committed"],
                   op=rows["ok"].astype(np.int32),
                   cmd_id=np.maximum(rows["count"], 1).astype(np.int32))
    elif kind == MsgKind.PREPARE:
        buf.append(n, kind=k, src=rows["leader_id"].astype(np.int32),
                   ballot=rows["ballot"],
                   last_committed=rows["last_committed"])
    elif kind == MsgKind.PREPARE_INST:
        buf.append(n, kind=k, src=rows["leader_id"].astype(np.int32),
                   ballot=rows["ballot"], inst=rows["inst"])
    elif kind == MsgKind.PREPARE_REPLY:
        buf.append(n, kind=k, src=rows["id"].astype(np.int32),
                   ballot=rows["ballot"], inst=rows["crt_instance"],
                   last_committed=rows["last_committed"],
                   op=rows["ok"].astype(np.int32))
    elif kind == MsgKind.PREPARE_INST_REPLY:
        # device convention (models/minpaxos.py step 1b/1c): row ballot
        # = the slot's accepted vballot; last_committed = the prepare
        # ballot this reply answers (context tag)
        k_hi, k_lo = split_i64(rows["key"])
        v_hi, v_lo = split_i64(rows["val"])
        buf.append(n, kind=k, src=rows["id"].astype(np.int32),
                   ballot=rows["vballot"], inst=rows["inst"],
                   last_committed=rows["ballot"],
                   op=rows["op"].astype(np.int32),
                   key_hi=k_hi, key_lo=k_lo, val_hi=v_hi, val_lo=v_lo,
                   cmd_id=rows["cmd_id"], client_id=rows["client_id"])
    elif kind == MsgKind.COMMIT_SHORT:
        # frontier broadcast: inst carries committed_upto (count==0)
        buf.append(n, kind=k, src=rows["leader_id"].astype(np.int32),
                   ballot=rows["ballot"], last_committed=rows["inst"])
    elif kind == MsgKind.SKIP:
        # Mencius cede range (menciusproto.go:7-11); device convention
        # (models/mencius.py step 3): inst = cede end, last_committed =
        # cede start
        buf.append(n, kind=k, src=rows["leader_id"].astype(np.int32),
                   inst=rows["end_inst"],
                   last_committed=rows["start_inst"])
    # READ / BEACON / TRACE_CTX / handshake kinds are handled on the
    # host path (transport/replica), never as device rows — a
    # TRACE_CTX frame reaching here (tracing toggled off mid-stream)
    # is deliberately a no-op, not an error.


def rows_to_frames(cols: dict, mask: np.ndarray) -> list[tuple[MsgKind, np.ndarray]]:
    """Convert masked outbox rows (one destination's worth) into wire
    frames, one frame per message kind present."""
    out: list[tuple[MsgKind, np.ndarray]] = []
    kinds = cols["kind"][mask]
    if len(kinds) == 0:
        return out
    sub = {c: cols[c][mask] for c in COLS}
    for k in np.unique(kinds):
        m = kinds == k
        kind = MsgKind(int(k))
        if kind in (MsgKind.ACCEPT, MsgKind.COMMIT):
            frame = make_batch(
                kind, leader_id=sub["src"][m], inst=sub["inst"][m],
                ballot=sub["ballot"][m],
                op=sub["op"][m], key=join_i64(sub["key_hi"][m], sub["key_lo"][m]),
                val=join_i64(sub["val_hi"][m], sub["val_lo"][m]),
                cmd_id=sub["cmd_id"][m], client_id=sub["client_id"][m],
                last_committed=sub["last_committed"][m])
        elif kind == MsgKind.ACCEPT_REPLY:
            # rows arrive pre-compressed from the kernel (cmd_id = run
            # length); map them 1:1 onto wire rows
            frame = make_batch(
                kind, id=sub["src"][m], ok=sub["op"][m],
                inst=sub["inst"][m],
                count=np.maximum(sub["cmd_id"][m], 1).astype(np.int32),
                ballot=sub["ballot"][m],
                last_committed=sub["last_committed"][m])
        elif kind == MsgKind.PREPARE:
            frame = make_batch(kind, leader_id=sub["src"][m],
                               ballot=sub["ballot"][m],
                               last_committed=sub["last_committed"][m])
        elif kind == MsgKind.PREPARE_INST:
            frame = make_batch(kind, leader_id=sub["src"][m],
                               inst=sub["inst"][m], ballot=sub["ballot"][m])
        elif kind == MsgKind.PREPARE_REPLY:
            frame = make_batch(kind, id=sub["src"][m], ok=sub["op"][m],
                               ballot=sub["ballot"][m],
                               last_committed=sub["last_committed"][m],
                               crt_instance=sub["inst"][m])
        elif kind == MsgKind.PREPARE_INST_REPLY:
            frame = make_batch(
                kind, id=sub["src"][m], ok=1, inst=sub["inst"][m],
                ballot=sub["last_committed"][m], vballot=sub["ballot"][m],
                op=sub["op"][m],
                key=join_i64(sub["key_hi"][m], sub["key_lo"][m]),
                val=join_i64(sub["val_hi"][m], sub["val_lo"][m]),
                cmd_id=sub["cmd_id"][m], client_id=sub["client_id"][m])
        elif kind == MsgKind.COMMIT_SHORT:
            frame = make_batch(kind, leader_id=sub["src"][m],
                               inst=sub["last_committed"][m], count=0,
                               ballot=sub["ballot"][m])
        elif kind == MsgKind.SKIP:
            frame = make_batch(kind, leader_id=sub["src"][m],
                               start_inst=sub["last_committed"][m],
                               end_inst=sub["inst"][m])
        else:
            continue  # PROPOSE_REPLY etc. are built by the reply path
        out.append((kind, frame))
    return out
