"""The replica server process: one protocol thread, one jitted step.

Counterpart of the reference's server binary + genericsmr runtime +
bareminpaxos event loop (server.go:36-117, genericsmr.go:70-111,
bareminpaxos.go:247-381), restructured TPU-first: instead of a
goroutine per connection feeding per-message channels into a
select loop, reader threads enqueue decoded frames; the protocol
thread drains them into a fixed-shape column batch once per tick and
advances the WHOLE replica with one ``replica_step`` call; the outbox
scatters back to peer/client sockets. Durability, beacons, READ
serving, beyond-window catch-up, and control RPCs ride the host path
around the device step (SURVEY.md section 7.4: ragged/cold paths stay
off the device).

Single-owner: protocol state, writers, and the stable store are
touched only by the protocol thread — the reference's benign races
(SURVEY.md section 5) are structurally impossible.
"""

from __future__ import annotations

import functools
import heapq
import json
import queue
import socket
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from minpaxos_tpu.chaos import ChaosShim, FaultPlan
from minpaxos_tpu.models.minpaxos import (
    ACCEPTED,
    COMMITTED,
    NO_BALLOT,
    MinPaxosConfig,
    MsgBatch,
    become_leader,
    init_replica,
    replica_step_impl,
)
from minpaxos_tpu.obs.metrics import MetricsRegistry, TICK_MS_BUCKETS
from minpaxos_tpu.obs.recorder import (
    KIND_FULL,
    KIND_FUSED,
    KIND_IDLE_SKIP,
    KIND_NARROW,
    FlightRecorder,
)
from minpaxos_tpu.obs.trace import (
    ST_COMMIT,
    ST_DRAIN,
    ST_EXEC,
    ST_ORIGIN,
    ST_REPLY_SER,
    TraceSink,
    trace_id_for,
)
from minpaxos_tpu.obs.watch import (
    DET_BURN,
    EV_ALARM,
    EV_ALARM_CLEAR,
    EV_CHAOS_CLEAR,
    EV_CHAOS_INSTALL,
    EV_ELECTION,
    EV_FATAL,
    EV_LEADER_CHANGE,
    EV_NARROW_FALLBACK,
    EV_PHASE,
    EV_RECOVERY,
    EV_SNAPSHOT,
    EV_STORE_CORRUPT,
    EV_TRUNCATE,
    EventJournal,
    burn_alarm,
    event_chrome_events,
)
from minpaxos_tpu.ops.kvstore import LIVE, kv_insert_unique
from minpaxos_tpu.ops.packed import join_i64, split_i64
from minpaxos_tpu.ops.substeps import (
    SCAL_NAMES,
    SCAL_CRT_INST,
    SCAL_EXEC_COUNT,
    SCAL_EXEC_LO,
    SCAL_EXECUTED,
    SCAL_FRONTIER,
    SCAL_HIGH_ANCHOR,
    SCAL_KV_DROPPED,
    SCAL_LEADER,
    SCAL_LOW_ANCHOR,
    SCAL_PREPARED,
    SCAL_WINDOW_BASE,
    SCAL_WORK_PENDING,
    merge_view,
    narrow_view,
    scan_ticks,
)
from minpaxos_tpu.runtime import batches
from minpaxos_tpu.runtime.stable import StableStore
from minpaxos_tpu.runtime.transport import (
    CONN_LOST,
    FROM_CLIENT,
    FROM_PEER,
    Transport,
)
from minpaxos_tpu.utils.clock import cputicks, monotonic_ns
from minpaxos_tpu.utils.dlog import DLOG, dlog
from minpaxos_tpu.utils.netutil import CONTROL_OFFSET
from minpaxos_tpu.wire.messages import MsgKind, Op, empty_batch, make_batch

CONTROL = 3  # queue item source tag (transport uses 0..2)


@functools.partial(jax.jit, static_argnums=(0, 3, 4, 5), donate_argnums=1)
def _packed_step(cfg, state, inbox, step_impl, k=1, narrow=0, off=0):
    """k protocol substeps + device-side packing of everything the
    host reads per dispatch into THREE stacked arrays: the per-tick
    host cost used to be ~30 per-column/per-scalar ``np.asarray``
    device reads (~1 s of the leader's CPU over a 50k-op run,
    tools/profile_tcp_leader.py); one [k, 14, M] outbox stack, one
    [k, 6, E] exec stack and one [k, N_SCAL] scalar matrix make it
    three transfers for ALL k substeps (ops/substeps.py). Module-level
    jit: every replica in the process shares one compile cache (see
    ReplicaServer.step note).

    ``k`` (static): fused substeps per dispatch — the real inbox feeds
    substep 0, the rest run with empty inboxes, amortizing the
    0.3-0.9 ms dispatch floor over the follow-up ticks a bursty batch
    was going to need anyway. ``narrow``/``off`` (static width, traced
    offset): run the substeps on a ``narrow``-slot resident view of
    the window at offset ``off`` — the small-window specialized step;
    the host only selects it when every slot the step could touch fits
    the view (see _choose_narrow).
    """
    if narrow:
        ncfg = cfg._replace(window=narrow, slide_window=False)
        view, fields = narrow_view(state, off, narrow, cfg.window)
        view, (out_mats, exec_mats, scals) = scan_ticks(
            ncfg, view, inbox, step_impl, k)
        state = merge_view(state, view, off, fields)
        # the view's shifted window_base is an artifact (slide is off
        # in the view); report the real one
        scals = scals.at[:, SCAL_WINDOW_BASE].set(state.window_base)
        return state, out_mats, exec_mats, scals
    state, (out_mats, exec_mats, scals) = scan_ticks(
        cfg, state, inbox, step_impl, k)
    return state, out_mats, exec_mats, scals


@functools.partial(jax.jit, donate_argnums=0)
def _kv_install(kv, k_hi, k_lo, v, valid):
    """Batch-insert snapshot pairs into the KV table (snapshot keys
    are distinct by construction — the stable store sorts and the
    sender's table held them uniquely). Module-level jit like
    _packed_step: every replica in the process shares one compiled
    variant per (chunk, capacity) shape, and donation updates the
    table in place across the chunk loop."""
    return kv_insert_unique(kv, k_hi, k_lo, v,
                            delete=jnp.zeros_like(valid), valid=valid)


@dataclass
class _InflightTick:
    """One dispatched tick's host-phase inputs, already read back from
    the device. The pipeline completes these either immediately
    (serial order, -nopipeline or an empty queue) or one call later —
    between the NEXT tick's enqueue and readback, so persist/dispatch/
    reply run while the device computes (the hidden wall is recorded
    as the row's ``overlap_us``)."""

    cols: dict            # this tick's drained inbox columns
    n_rows: int
    out_mats: np.ndarray  # [k, 14, M] stacked outbox matrices
    exec_mats: np.ndarray  # [k, 6, E] stacked exec matrices
    scals: np.ndarray     # [k, N_SCAL] per-substep scalar vectors
    k: int
    kind: int             # recorder regime (KIND_FULL/FUSED/NARROW)
    persist: bool
    dispatch: bool
    frontier: int         # final (substep k-1) committed frontier
    backlog: int          # frontier - executed after the tick
    rows_out: int
    peer_commits: np.ndarray | None  # state's [R] vector (non-mencius)
    snap: dict            # the snapshot published at this readback
    drain_us: int
    enqueue_us: int
    readback_us: int
    t_rb_ns: int          # monotonic_ns at readback (trace anchoring)
    coal_occ: int = 0     # rows the ingress coalescer batched for this tick
    coal_wake: int = 0    # cumulative coalescer wakeup kicks at this tick


class FatalReplicaError(RuntimeError):
    """The replica can no longer execute correctly and must fail-stop
    (consensus tolerates a crashed replica; serving wrong data is the
    one thing it cannot tolerate)."""


@dataclass
class RuntimeFlags:
    """Server knobs — the reference's flag set (server.go:19-34).

    The reference's ``-exec`` (run executeCommands at all) has no
    counterpart here and is deliberately absent: execution is fused
    into the device step and drives sliding-window reclamation
    (models/minpaxos.py step 8 feeds step 9), so a non-executing
    replica would wedge its own log window. The CLI still accepts
    ``-exec`` for command-line compatibility; it is always on.
    """

    dreply: bool = True    # -dreply: reply after execution (with value)
    durable: bool = False  # -durable: fsync accepted slots per tick
    thrifty: bool = False  # -thrifty: send accepts to a quorum only
    beacon: bool = False   # -beacon: RTT beacons -> preferred quorum
    tick_s: float = 0.002  # protocol tick (reference clock: 5ms)
    # idle poll interval: a quiet replica wakes this often to drive
    # retries/stall detection. Message arrival always wakes it
    # immediately (queue.get), so this only prices background wakeups
    # — on a single-core host every idle tick preempts whoever is
    # doing real work, which directly inflates serial commit latency
    # (round-5 measurement: ~2x per-tick wall vs isolated).
    idle_s: float = 0.05
    # fused burst ticks: when the snapshot shows the batch will need
    # follow-up ticks (exec backlog beyond one exec_batch, lagging
    # catch-up/broadcast cursors), run this many protocol substeps in
    # ONE device dispatch (lax.scan, ops/substeps.py) instead of one
    # per host tick. 1 disables fusion.
    fuse_ticks: int = 3
    # idle fast path: when the inbox is empty and the published
    # snapshot's work_pending scalar says an empty step would be a
    # no-op, skip the device dispatch entirely — the idle-poll wakeups
    # then cost microseconds of host time instead of a full dispatch
    # (PERF.md: idle ticks stole ~2x per-tick wall on the 1-core
    # host). idle_skip_max_s bounds the skip streak: one real tick at
    # least this often, a belt-and-braces timer for anything the
    # work_pending derivation misses.
    idle_fastpath: bool = True
    idle_skip_max_s: float = 0.25
    # small-window specialized step: execute low-occupancy ticks
    # through a compiled-once narrow resident view of this many slots
    # (0 = off). Lets a server sized -window 16384 tick at the ~4x
    # cheaper W=512 cost the dedicated serial cluster measured,
    # falling back to the full-width step whenever the live span or
    # the inbox's addressed slots don't fit the view.
    narrow_window: int = 0
    # precompile the (k, narrow) step variants on the protocol thread
    # before serving (see _warm_step_variants). Default OFF: the
    # in-process test harnesses boot dozens of short-lived clusters
    # whose tests are calibrated to one lazy compile, and eager
    # warming blew their first-workload timeouts. The server CLI turns
    # it on — long-lived deployments must not pay a variant's first
    # compile mid-traffic.
    warm_variants: bool = False
    # operator's estimate of the workload's distinct-key count (0 =
    # unknown): start() logs projected KV load against the table
    # capacity, loudly, because saturation fail-stops the replica
    # (-kvpow2 footgun, VERDICT round-5 weak #5)
    key_hint: int = 0
    # depth-2 pipelined tick loop ("Paxos in the Cloud": pipelining is
    # the throughput lever next to batching): enqueue tick k's jitted
    # step WITHOUT blocking (JAX async dispatch), run tick k-1's
    # deferred host phases (persist -> dispatch -> reply, the -durable
    # fsync-before-reply ordering preserved per tick) while the device
    # computes, then read tick k back. Host phases are deferred ONLY
    # when follow-up traffic is already queued — a closed-loop serial
    # op (empty queue after its tick) completes immediately, so its
    # reply never waits for the next wakeup. -nopipeline restores the
    # strictly serial enqueue->readback->host order for A/Bs.
    pipeline: bool = True
    # event-driven ingress coalescer (batches.IngressCoalescer): the
    # inbox queue the transport's reader threads feed becomes a
    # condition-variable front that kicks the tick loop the moment
    # rows arrive and lingers up to coalesce_wait_us for more client
    # rows (stopping early at coalesce_rows) so concurrent sessions
    # share one dispatch. Admission control rides it: under exec-
    # backlog, window-full, or burn-rate overload (_ingress_overloaded)
    # client
    # PROPOSE frames beyond the pending bound are dropped at ingress
    # (clients retry) — bounded queueing instead of tail blowup. The
    # work_pending idle fast path is untouched (an idle replica still
    # parks on idle_s). -nocoalesce restores the plain queue.Queue;
    # coalesce_wait_us=0 keeps the kick but never lingers.
    coalesce: bool = True
    coalesce_wait_us: int = 200
    coalesce_rows: int = 0  # 0 = half the device inbox (sized at boot)
    # overlapped commit->exec->reply: when a dispatch's readback still
    # shows committed-but-unexecuted slots and no follow-up traffic is
    # queued, immediately run the follow-up dispatch in the SAME
    # wakeup instead of letting execution wait out the next poll
    # interval (the entire <exec_wait> paxtrace stage). The chased
    # dispatch is the identical deterministic step the next wakeup
    # would have run — byte-exact vs the strict-order path (pinned by
    # tests/test_coalescer.py) and no new compiled variant.
    # -nooverlapexec restores the one-dispatch-per-wakeup cadence.
    overlap_exec: bool = True
    # paxmon flight recorder (obs/recorder.py): per-tick ring logging
    # dispatch regime + per-phase wall, served over the control
    # socket's TRACE verb. Default ON — the recorder's hot-path cost
    # is one ring write per tick (the CI overhead guard in
    # tools/obs_smoke.py pins it); -norecorder disables for A/Bs.
    recorder: bool = True
    recorder_ring: int = 4096
    # paxtrace (obs/trace.py): sampled per-command stage spans served
    # over the control socket's TRACESPANS verb. Default ON at the
    # 1-in-2^trace_pow2 sample rate — unsampled commands pay one
    # vectorized hash per batch, sampled ones a handful of ring writes
    # (the obs_smoke per-command overhead guard pins the budget);
    # -notrace disables for A/Bs, trace_pow2=0 traces every command
    # (the serial-latency bench leg).
    trace: bool = True
    trace_pow2: int = 4
    trace_ring: int = 4096
    # paxwatch event journal (obs/watch.py): structured cluster events
    # (elections, leader changes, chaos installs, narrow fallbacks,
    # store-corruption recoveries, fail-stops, peer link up/down)
    # served over the control socket's EVENTS verb and rendered as
    # instant events in merged traces (schema v6). Default ON — a
    # journal write is one ring slice-assign plus two clock reads
    # (the obs_smoke <=5 us/event guard pins it); -nowatch disables.
    watch: bool = True
    watch_ring: int = 1024
    # paxdur snapshot + truncation policy (PR 20): checkpoint the
    # applied KV state into the stable store (stable.py REC_SNAPSHOT)
    # and truncate redo records below the PREVIOUS snapshot's frontier
    # — two snapshots are retained so a corrupt newest one falls back
    # to the older + a longer replay. The size trigger fires when the
    # on-disk log grows snap_every_bytes past the last snapshot
    # (-snap-every; 0 disables it); snap_interval_s adds a wall-clock
    # trigger (0 = off). -nosnap turns the whole policy off: the log
    # then grows unboundedly, exactly the pre-PR-20 behavior.
    snapshots: bool = True
    snap_every_bytes: int = 8 << 20
    snap_interval_s: float = 0.0
    store_dir: str = "."
    # -cpuprofile: a cProfile.Profile the PROTOCOL THREAD enables on
    # start (cProfile is per-thread; enabling it on the main thread —
    # the obvious wiring — would profile an idle sleep loop and dump
    # nothing, while all the work happens here)
    profile: object | None = None


class ReplicaServer:
    def __init__(self, me: int, addrs: list[tuple[str, int]],
                 cfg: MinPaxosConfig | None = None,
                 flags: RuntimeFlags | None = None,
                 protocol: str = "minpaxos"):
        self.me = me
        self.addrs = addrs
        self.cfg = cfg or MinPaxosConfig(
            n_replicas=len(addrs), window=1 << 14, inbox=4096,
            exec_batch=4096, kv_pow2=16, catchup_rows=256,
            recovery_rows=256)
        assert self.cfg.n_replicas == len(addrs)
        self.flags = flags or RuntimeFlags()
        # protocol selection (reference server.go:58-79 — where every
        # protocol but -min is commented out, mencius here actually
        # runs): "minpaxos" / "classic" share replica_step (classic via
        # cfg.explicit_commit); "mencius" swaps in the rotating-
        # ownership kernel. Leaderless paths (elections, leader-serving
        # catch-up, ballot-promise restore) are gated on self.protocol.
        self.protocol = protocol
        if protocol == "mencius":
            from minpaxos_tpu.models.mencius import (
                init_mencius,
                mencius_step_impl,
            )

            step_impl, init_fn = mencius_step_impl, init_mencius
        else:
            step_impl, init_fn = replica_step_impl, init_replica
        # paxmon registry (obs/metrics.py) — replaces the old bare
        # `stats` dict. Counter handles are bound once here so the hot
        # path pays one attribute add per advance; `self.stats` is now
        # a snapshot property (see below)
        self.metrics = MetricsRegistry(namespace=f"replica{me}")
        m = self.metrics
        self._c_ticks = m.counter(
            "ticks", "protocol-thread wakeups (WALL ticks — advances "
            "by tick_inc, never by fused substeps)")
        self._c_dispatches = m.counter("dispatches", "device round-trips")
        self._c_fused_substeps = m.counter(
            "fused_substeps", "protocol substeps those dispatches ran "
            "(>= dispatches under fusion)")
        self._c_full_steps = m.counter(
            "full_steps", "dispatches through the full-width k=1 step")
        self._c_fused_dispatches = m.counter(
            "fused_dispatches", "dispatches that fused k>1 substeps")
        self._c_narrow_steps = m.counter(
            "narrow_steps", "dispatches through the small-window view")
        self._c_idle_skips = m.counter(
            "idle_skips", "timer wakeups the idle fast path answered "
            "without touching the device")
        self._c_pipelined = m.counter(
            "pipelined_ticks", "dispatches whose host phases ran "
            "deferred, under the NEXT dispatch's device compute")
        self._c_narrow_fallbacks = m.counter(
            "narrow_fallbacks", "narrow dispatches whose post-readback "
            "anchor validation failed; the next dispatch recounts "
            "through the full-width step")
        self._c_proposals = m.counter("proposals", "client command rows "
                                      "admitted to the inbox")
        self._c_rejected = m.counter(
            "proposals_rejected", "admitted command rows the kernel "
            "bounced back to the client (not leader / unprepared) — "
            "no log slot was assigned, so paxwatch's in-flight "
            "estimate (proposals - rejected - committed) subtracts "
            "them; without this a boot-window rejection burst biases "
            "the estimate high forever and an IDLE cluster looks "
            "permanently loaded to the stall detector")
        self._c_executed = m.counter("executed", "commands executed")
        self._g_committed = m.gauge("committed",
                                    "committed prefix length (frontier+1)")
        self._h_tick = m.histogram(
            "tick_wall_ms", "whole-dispatch host wall (drain work + "
            "enqueue + readback + persist + dispatch + reply, wherever "
            "the host phases ran)", TICK_MS_BUCKETS)
        self._h_step = m.histogram(
            "device_step_ms", "host-visible dispatch wall (enqueue + "
            "readback; device compute hidden under the previous tick's "
            "host phases does not appear here)", TICK_MS_BUCKETS)
        self.recorder = (FlightRecorder(self.flags.recorder_ring)
                         if self.flags.recorder else None)
        # paxtrace sink: one per replica, shared with the transport's
        # reader threads (each thread gets its own ring inside). The
        # sink exists even when disabled so every touch point stays
        # one `.enabled` test.
        self.trace_sink = TraceSink(enabled=self.flags.trace,
                                    sample_pow2=self.flags.trace_pow2,
                                    ring_capacity=self.flags.trace_ring)
        m.fn_gauge("trace_spans", self.trace_sink.spans_total)
        m.fn_gauge("trace_dropped", self.trace_sink.spans_dropped)
        # paxwatch journal: one per replica, shared with the
        # transport's reader threads (each writer thread gets its own
        # ring inside) — the journal exists even when disabled so
        # every touch point stays one `.enabled` test
        self.journal = EventJournal(enabled=self.flags.watch,
                                    capacity=self.flags.watch_ring)
        m.fn_gauge("events", self.journal.events_total)
        m.fn_gauge("events_dropped", self.journal.events_dropped)
        self._c_elections = m.counter(
            "elections", "become_leader rounds this replica ran "
            "(paxwatch churn detection reads the cluster-wide delta)")
        # sampled in-flight bookkeeping (protocol thread only): a
        # min-heap of (log slot, cmd_id) awaiting commit stamps
        # (bounded by the sampled in-flight count, 1-in-2^k of the
        # window; heap so the per-dispatch pop is O(covered), never a
        # scan of everything still above the frontier)
        self._trace_slots: list[tuple[int, int]] = []
        self._drain_wait_s = 0.0  # blocking queue wait (idle pacing)
        self._drain_work_s = 0.0  # frame-decode/dedup work in _drain
        self._last_scals = None  # newest published scalar vector
        # ingress admission state — written by the protocol thread
        # (_update_burn), read lock-free by the coalescer's gate on
        # the transport reader threads (a plain bool + the published
        # snapshot; never self.state). Backlog bound: a few exec
        # batches of committed-but-unexecuted slots is normal pipeline
        # depth; an order of magnitude past it means execution lost
        # the race and new load must queue at the clients.
        self._admit_backlog_limit = max(8 * self.cfg.exec_batch, 256)
        # commit-bound overload (paxdur follow-up): when the device
        # window is within one exec batch of full, the kernel will
        # window-reject any admitted PROPOSE anyway — each reject
        # costs a device round trip plus a client retransmit, and on
        # a commit-bound cluster (durable appends, snapshot pauses)
        # that reject/retransmit loop is what melts the tail. Shed at
        # the door instead: same counted drop, none of the wasted work.
        self._admit_window_limit = self.cfg.window - self.cfg.exec_batch
        self._burn_hot = False
        self._burn_samples: deque[dict] = deque(maxlen=32)
        self._burn_last_s = 0.0
        # event-driven ingress front (tentpole of the p99-tail PR):
        # injected as the transport's inbox queue, so reader threads,
        # control verbs and beacons all feed the same cv-kicked,
        # batch-forming, admission-gated path. -nocoalesce falls back
        # to the transport's own queue.Queue.
        self.coalescer = (batches.IngressCoalescer(
            max_wait_us=self.flags.coalesce_wait_us,
            max_rows=self.flags.coalesce_rows or max(self.cfg.inbox // 2, 1),
            admit_gate=self._ingress_overloaded,
            metrics=self.metrics) if self.flags.coalesce else None)
        self.transport = Transport(me, addrs, inbox_queue=self.coalescer,
                                   metrics=self.metrics)
        self.transport.trace = self.trace_sink
        self.transport.journal = self.journal
        self.queue = self.transport.queue
        # the MODULE-level jitted packed step (static cfg + impl):
        # every replica in the process shares ONE compile cache — N
        # private jax.jit wrappers would compile the same kernel N
        # times concurrently, which starves small hosts (in-process
        # test clusters)
        cfg_ = self.cfg
        self.step = lambda state, inbox, k=1, narrow=0, off=0: _packed_step(
            cfg_, state, inbox, step_impl, k, narrow, off)
        # copy every leaf: jax caches/aliases equal small constants, and
        # donation rejects the same buffer appearing twice
        self.state = jax.tree_util.tree_map(
            lambda x: x.copy(), init_fn(self.cfg, me))
        self.store = StableStore(
            f"{self.flags.store_dir}/stable-store-replica{me}",
            sync=self.flags.durable)
        # CRC-rejected log records (stable.py replay): nonzero after a
        # recovery that skipped flipped-byte records — the holes self-
        # heal via peers, but the operator must see the disk went bad
        m.fn_gauge("store_corrupt_records",
                   lambda: self.store.corrupt_records)
        # paxdur durability gauges: the on-disk bound truncation
        # maintains, snapshot churn, and how stale the newest snapshot
        # is (paxtop's SNAP column reads these; -1 = never snapshotted)
        m.fn_gauge("store_log_bytes", self.store.log_bytes)
        m.fn_gauge("snap_count", lambda: self.store.snapshots_taken)
        m.fn_gauge("store_truncated_bytes",
                   lambda: self.store.truncated_bytes)
        m.fn_gauge("snap_age_s", self._snap_age_s)
        # snapshot policy state (protocol thread only): next log size
        # that triggers the size policy, last snapshot wall time, and
        # the policy-check rate limiter (log_bytes is a stat() call —
        # not per-tick material)
        self._snap_goal_bytes = max(self.flags.snap_every_bytes, 1)
        self._snap_last_s = time.monotonic()
        self._snap_check_s = 0.0
        self._snap_disabled = False
        # snapshot catch-up: per-peer pacing of pushes (a transfer in
        # flight must not be re-sent every tick) and the receive-side
        # assembly buffers keyed by the announced snapshot frontier
        self._snap_sent_s: dict[int, float] = {}
        self._snap_seq = 0
        self._snap_rx: dict[int, dict] = {}
        # crash-restart fault injection: crash() emulates a process
        # kill — no flush, no clean close, buffered store bytes lost
        self._crashed = False
        self.inbox = batches.ColumnBuffer(self.cfg.inbox)
        # reply bookkeeping: (conn_id, cmd_id) -> reply kind to send
        self._pending: dict[tuple[int, int], MsgKind] = {}
        self.rtt_ewma = np.full(len(addrs), np.inf)
        self._stop = threading.Event()
        self._recovered = self.store.recovered
        # fail-stop reason: set when the replica can no longer execute
        # correctly (e.g. KV table saturation — see _device_tick); the
        # control plane reports it so operators/tests see the cause
        self.fatal: str | None = None
        self._ctl_sock: socket.socket | None = None
        self._proto_thread: threading.Thread | None = None
        self._idle = False  # last step produced no work (throttle ticks)
        self._last_step = 0.0
        self._seen_leader = False  # any PREPARE/ACCEPT/COMMIT from a peer
        self._boot_pending: float | None = None  # deferred boot election
        # control-plane snapshot: the protocol thread swaps in a fresh
        # plain-Python dict each tick; other threads only ever read it.
        # They must NOT touch self.state — its arrays are donated into
        # the jitted step and die mid-tick. Keys here must match what
        # _device_tick publishes: readers (_mencius_store_answer, the
        # control plane) can run off a frame drained BEFORE the first
        # tick ever replaces this dict.
        # work_pending defaults True (no "low"/"high" keys yet): until
        # the first device tick publishes real scalars, the idle fast
        # path and the narrow view stay off
        self.snapshot = {"frontier": -1, "leader": -1, "prepared": False,
                         "window_base": 0, "work_pending": True}
        self._last_dispatch = 0.0  # wall time of the last device tick
        self._kv_warned = False  # one-shot near-saturation warning
        # pipeline state (protocol thread only): the one tick whose
        # host phases are deferred, and the narrow-view doubt flag the
        # post-readback anchor validation sets (next dispatch recounts
        # anchors through the full-width step)
        self._inflight: _InflightTick | None = None
        self._narrow_doubt = False

    @property
    def stats(self) -> dict:
        """Flat counter/gauge snapshot — a FRESH dict per read, taken
        under the registry lock. The old attribute handed out the live
        dict the tick thread was mutating, so a control-thread
        ``json.dumps`` (or a test comparing before/after) raced the
        protocol loop; a snapshot cannot."""
        return self.metrics.counters()

    # ---------------- lifecycle ----------------

    def start(self) -> None:
        self._log_kv_sizing()
        self.transport.listen()
        self._start_control()
        if self._recovered:
            self._recover_from_store()
        self.transport.connect_peers()
        self._proto_thread = threading.Thread(target=self._run, daemon=True)
        self._proto_thread.start()
        if self.flags.beacon:
            threading.Thread(target=self._beacon_loop, daemon=True).start()

    def _log_kv_sizing(self) -> None:
        """Loud, unconditional startup line: KV capacity vs the
        operator's workload hint. The table fail-stops on saturation
        (a dropped insert means silent state divergence), so -kvpow2
        vs distinct-key-count is an operational contract — state it
        where it cannot be missed instead of only in a flag help
        string (VERDICT round-5 weak #5)."""
        cap = 1 << self.cfg.kv_pow2
        hint = self.flags.key_hint
        msg = (f"replica {self.me}: KV table capacity {cap} "
               f"(-kvpow2 {self.cfg.kv_pow2}); fail-stops if the live "
               f"key space saturates it")
        if hint > 0:
            load = hint / cap
            msg += (f"; workload hint {hint} distinct keys -> "
                    f"projected load {load:.2f}")
            if load > 0.7:
                msg += (" — OVER the 0.7 comfort bound for two-choice "
                        "placement; raise -kvpow2 or expect fail-stop")
        else:
            msg += ("; no -keyhint given — size -kvpow2 so distinct "
                    "keys stay under ~0.7 of capacity")
        print(msg, file=sys.stderr, flush=True)

    def _check_kv_load(self) -> None:
        """Periodic near-saturation warning (one shot): counts live
        table slots off the hot path (every 1024 dispatches) so the
        operator hears about load > 0.7 BEFORE the kv.dropped
        fail-stop triggers."""
        if self._kv_warned or self._c_dispatches.value % 1024:
            return
        cap = 1 << self.cfg.kv_pow2
        live = int(np.asarray((self.state.kv.slot == LIVE).sum()))
        if live > 0.7 * cap:
            self._kv_warned = True
            print(f"replica {self.me}: KV table NEAR SATURATION — "
                  f"{live}/{cap} slots live (load {live / cap:.2f} > "
                  f"0.7); the replica fail-stops when an insert "
                  f"cannot place. Raise -kvpow2.",
                  file=sys.stderr, flush=True)

    def stop(self) -> bool:
        """Returns True when the protocol thread joined cleanly; False
        if it was still running at the join timeout (callers that dump
        its profiler state must not trust the data then)."""
        # order matters: signal, JOIN the protocol thread (it may be
        # mid-_persist), and only then close the store — the reference's
        # single event-loop goroutine gets this for free
        self._stop.set()
        joined = True
        if self._proto_thread is not None:
            self._proto_thread.join(timeout=10.0)
            joined = not self._proto_thread.is_alive()
        self.transport.stop()
        if self._ctl_sock is not None:
            try:
                self._ctl_sock.close()
            except OSError:
                pass
        self.store.close()
        return joined

    def crash(self) -> None:
        """paxchaos process-kill emulation: die like a SIGKILLed
        process, NOT like stop(). The store's buffered userspace bytes
        are lost (StableStore.crash — the on-disk file keeps only what
        already reached the kernel, possibly ending in a torn record),
        sockets close without flushing, no deferred host phase
        completes, and the control port goes dark so the master's
        observe fan-out sees a dead replica. In-process threads cannot
        be SIGKILLed, so this is the closest emulation the harness can
        run: every durable artifact matches a real kill."""
        self._crashed = True
        self.store.crash()
        self._stop.set()
        # wake the protocol thread immediately (it may be parked on an
        # idle-interval queue.get; the inbox queue is unbounded)
        self.queue.put((CONTROL, 0, "crashed", None))
        self.transport.stop()
        if self._ctl_sock is not None:
            try:
                self._ctl_sock.close()
            except OSError:
                pass
        if self._proto_thread is not None:
            self._proto_thread.join(timeout=10.0)

    def _snap_age_s(self) -> int:
        """Seconds since the newest retained snapshot (-1 = none) —
        wall-clock based so the age survives a restart."""
        w = self.store.snap_wall_ns
        if not w:
            return -1
        return max(0, int((time.time_ns() - w) // 1_000_000_000))

    # ---------------- recovery (stable-store replay) ----------------

    def _recover_from_store(self) -> None:
        """Rebuild device state by replaying the durable log through
        the SAME protocol kernel: committed prefix as COMMIT rows
        (commits + executes + rebuilds the KV + slides the window),
        accepted tail as ACCEPT rows. The reference's
        getDataFromStableStore (bareminpaxos.go:122-161) rebuilt Go
        structs; here recovery IS the protocol.

        Snapshot-first (PR 20): a truncated store replays as the
        newest CRC-valid snapshot's KV pairs installed directly into
        the table + the redo SUFFIX above its frontier — the records
        below it no longer exist on disk. A corrupt newest snapshot
        already fell back inside StableStore._replay (base = the
        previous snapshot, longer suffix), so this path never sees it."""
        t_rec0 = time.perf_counter()
        frontier = self.store.committed_prefix()
        max_ballot = self.store.max_ballot()
        chunk = self.cfg.exec_batch
        own_max = -1  # highest recorded slot owned by me (mencius)
        start = 0
        if self.store.base >= 0 and self.protocol != "mencius":
            self._install_snapshot_pairs(self.store.snapshot_pairs,
                                         self.store.base)
            start = self.store.base + 1

        def _own_slots_max(rec) -> int:
            mine = rec["inst"][rec["inst"] % self.cfg.n_replicas == self.me]
            return int(mine.max()) if len(mine) else -1

        for lo in range(start, frontier + 1, chunk):
            rec = self.store.read_range(lo, min(lo + chunk, frontier + 1) - 1)
            own_max = max(own_max, _own_slots_max(rec))
            self._feed_records(rec, MsgKind.COMMIT)
        tail = self.store.read_range(frontier + 1, self.store.max_inst())
        if len(tail):
            own_max = max(own_max, _own_slots_max(tail))
            self._feed_records(tail, MsgKind.ACCEPT)
        if self.protocol == "mencius":
            # no global ballot promise to restore. But crt_own MUST
            # move past every recorded own slot: the propose path
            # writes at crt_own unguarded (fresh slots by invariant),
            # so a stale cursor would overwrite recovered state. The
            # maximum is accumulated during the chunked replay above —
            # one whole-mirror read here would defeat that chunking.
            if own_max >= 0:
                self.state = self.state._replace(
                    crt_own=jnp.maximum(
                        self.state.crt_own,
                        jnp.int32(own_max + self.cfg.n_replicas)))
        elif max_ballot > 0:
            # restore the ballot promise (ballot low 4 bits = proposer
            # id, bareminpaxos.go:383-385)
            buf = batches.ColumnBuffer(self.cfg.inbox)
            buf.append(1, kind=int(MsgKind.PREPARE), src=max_ballot % 16,
                       ballot=max_ballot,
                       last_committed=int(np.asarray(self.state.committed_upto)))
            self._device_tick(buf)
        if self.store.corrupt_records:
            # the stable store's replay already printed its (parser-
            # safe, byte-identical) warning lines; the journal makes
            # the recovery QUERYABLE — paxtop's HEALTH column and the
            # EVENTS fan-out see it without scraping stderr
            self.journal.record(EV_STORE_CORRUPT, subject=self.me,
                                value=self.store.corrupt_records)
        # EV_RECOVERY: the replica rebuilt serving state from durable
        # artifacts — value = the recovered frontier, aux = recovery
        # wall ms (trend.py's recovery-cost row reads this)
        self.journal.record(
            EV_RECOVERY, subject=self.me, value=frontier,
            aux=int((time.perf_counter() - t_rec0) * 1e3))
        dlog(f"replica {self.me}: recovered frontier={frontier} "
             f"base={self.store.base} tail={len(tail)} "
             f"ballot={max_ballot}")

    def _feed_records(self, rec: np.ndarray, kind: MsgKind) -> None:
        if len(rec) == 0:
            return
        k_hi, k_lo = split_i64(rec["key"])
        v_hi, v_lo = split_i64(rec["val"])
        # row src: MinPaxos ballots encode the proposer in their low 4
        # bits; Mencius ownership is positional (owner = inst mod R,
        # mencius.go:431-432) and its accept guard checks exactly that
        src_all = (rec["inst"] % self.cfg.n_replicas
                   if self.protocol == "mencius" else rec["ballot"] % 16)
        for lo in range(0, len(rec), self.cfg.inbox):
            sl = slice(lo, lo + self.cfg.inbox)
            buf = batches.ColumnBuffer(self.cfg.inbox)
            buf.append(len(rec[sl]), kind=int(kind),
                       src=src_all[sl], ballot=rec["ballot"][sl],
                       inst=rec["inst"][sl],
                       last_committed=self.store.frontier,
                       op=rec["op"][sl].astype(np.int32),
                       key_hi=k_hi[sl], key_lo=k_lo[sl],
                       val_hi=v_hi[sl], val_lo=v_lo[sl],
                       cmd_id=rec["cmd_id"][sl],
                       client_id=rec["client_id"][sl])
            self._device_tick(buf, persist=False, dispatch=False)

    def _install_snapshot_pairs(self, pairs: np.ndarray,
                                frontier: int) -> None:
        """Fast-forward device state to a snapshot: install its live
        KV pairs (chunked through the module-jitted insert, fixed
        exec_batch shapes so no new compile per transfer size) and
        move every protocol cursor to frontier+1. The log-window
        arrays are re-zeroed — whatever they described is at/below the
        snapshot's frontier, which the installed table already covers
        — leaving exactly the state a replica that executed slots
        0..frontier and slid its window would hold. Scalars are fresh
        buffers (.copy()/computed) because the jitted step's donation
        rejects one buffer appearing twice."""
        chunk = max(self.cfg.exec_batch, 1)
        k_hi, k_lo = split_i64(np.ascontiguousarray(pairs["key"]))
        v_hi, v_lo = split_i64(np.ascontiguousarray(pairs["val"]))
        kv = self.state.kv
        for lo in range(0, len(pairs), chunk):
            n = min(chunk, len(pairs) - lo)
            ck_hi = np.zeros(chunk, np.int32)
            ck_lo = np.zeros(chunk, np.int32)
            cv = np.zeros((chunk, 2), np.int32)
            valid = np.zeros(chunk, bool)
            ck_hi[:n], ck_lo[:n] = k_hi[lo:lo + n], k_lo[lo:lo + n]
            cv[:n, 0], cv[:n, 1] = v_hi[lo:lo + n], v_lo[lo:lo + n]
            valid[:n] = True
            kv = _kv_install(kv, ck_hi, ck_lo, cv, valid)
        s = self.cfg.window
        fj = jnp.int32(frontier)
        self.state = self.state._replace(
            ballot=jnp.full(s, NO_BALLOT, jnp.int32),
            status=jnp.zeros(s, jnp.uint8),
            op=jnp.zeros(s, jnp.uint8),
            key_hi=jnp.zeros(s, jnp.int32),
            key_lo=jnp.zeros(s, jnp.int32),
            val_hi=jnp.zeros(s, jnp.int32),
            val_lo=jnp.zeros(s, jnp.int32),
            cmd_id=jnp.zeros(s, jnp.int32),
            client_id=jnp.zeros(s, jnp.int32),
            votes=jnp.zeros(s, jnp.uint16),
            pvotes=jnp.zeros(s, jnp.uint16),
            kv=kv,
            window_base=fj + 1,
            crt_inst=jnp.maximum(self.state.crt_inst, fj + 1),
            committed_upto=fj.copy(),
            executed_upto=fj.copy(),
            rec_cursor=jnp.maximum(self.state.rec_cursor, fj + 1),
            tenure_start=jnp.maximum(self.state.tenure_start, fj + 1),
            gossip_upto=fj.copy())

    # ---------------- control plane (port + 1000) ----------------

    def _start_control(self) -> None:
        host, port = self.addrs[self.me]
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # retry: the control port (data port + 1000, the reference's
        # scheme) can transiently collide with an ephemeral outbound
        # port (e.g. a master ping's source port); those clear quickly
        deadline = time.monotonic() + 10.0
        while True:
            try:
                s.bind((host, port + CONTROL_OFFSET))
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.25)
        s.listen(16)
        self._ctl_sock = s
        threading.Thread(target=self._control_loop, daemon=True).start()

    def _control_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._ctl_sock.accept()
            except OSError:
                return
            threading.Thread(target=self._control_conn, args=(conn,),
                             daemon=True).start()

    def _control_conn(self, conn) -> None:
        f = conn.makefile("rw")
        try:
            for line in f:
                try:
                    req = json.loads(line)
                except json.JSONDecodeError:
                    break
                m = req.get("m")
                if m == "ping":
                    snap = self.snapshot  # one read: dict swap is atomic
                    resp = {"ok": self.fatal is None,
                            "frontier": snap["frontier"],
                            "leader": snap["leader"], "stats": self.stats,
                            "window_base": snap["window_base"],
                            "crt_inst": snap.get("crt_inst", -1),
                            "prepared": snap.get("prepared"),
                            "fatal": self.fatal}
                elif m == "stats":
                    # full typed snapshot (counters/gauges/histograms)
                    # plus the newest device-published scalar vector —
                    # everything here is a fresh copy; the tick thread
                    # is never exposed to the control connection
                    snap = self.snapshot
                    scals = self._last_scals
                    resp = {"ok": self.fatal is None, "id": self.me,
                            "protocol": self.protocol,
                            "leader": snap["leader"],
                            "frontier": snap["frontier"],
                            "window_base": snap["window_base"],
                            "executed": snap.get("executed", -1),
                            "work_pending": snap.get("work_pending", True),
                            "metrics": self.metrics.snapshot(),
                            "scalars": (None if scals is None else
                                        dict(zip(SCAL_NAMES,
                                                 scals.tolist()))),
                            "fatal": self.fatal}
                elif m == "trace":
                    # flight-recorder export as Chrome trace events
                    # (pid = replica id so merged cluster traces keep
                    # one track group per replica); "last" bounds the
                    # response size for pollers
                    last = req.get("last")
                    events = ([] if self.recorder is None else
                              self.recorder.to_events(
                                  pid=self.me,
                                  last=int(last) if last else 1024))
                    if self.recorder is not None and self.journal.enabled:
                        # paxwatch journal rides the merged timeline
                        # as instant events on the reserved WATCH_PID
                        # (schema v6), one tid per replica. Gated on
                        # the recorder too: -norecorder keeps TRACE
                        # answering empty-but-ok (pinned by test), and
                        # the journal stays queryable via EVENTS.
                        events += event_chrome_events(
                            self.journal.snapshot(), tid=self.me)
                    resp = {"ok": True, "id": self.me,
                            "recorder": self.recorder is not None,
                            "events": events}
                elif m == "events":
                    # paxwatch EVENTS verb: the journal's retained
                    # events (every writer thread's ring) plus the
                    # (mono, wall) clock anchor align_event_collections
                    # shifts processes into one domain by
                    resp = {"ok": True, "id": self.me,
                            "journal": self.journal.collect()}
                elif m == "tracespans":
                    # paxtrace collection: every span ring of this
                    # process (protocol thread, transport readers) plus
                    # the monotonic<->wall clock anchor tail.py aligns
                    # processes by. The copy is taken under the sink's
                    # tiny locks; the writers never block.
                    resp = {"ok": True, "id": self.me,
                            "trace": self.trace_sink.collect()}
                elif m == "chaos":
                    # paxchaos verb: install/clear/status a fault plan
                    # on the LIVE transport. Installing is an attribute
                    # swap the reader threads observe per frame, so a
                    # partition can be flipped mid-workload; status
                    # reports per-kind injected-fault tallies.
                    resp = self._chaos_verb(req)
                elif m == "phase":
                    # paxsoak verb: journal a scenario-phase boundary
                    # (EV_PHASE) on THIS replica's journal so phase
                    # edges share the detector/chaos monotonic domain.
                    # Journaled from this control thread's own ring,
                    # the established _chaos_verb pattern.
                    self.journal.record(
                        EV_PHASE, subject=int(req.get("ordinal", 0)),
                        value=int(req.get("duration_ms", 0)),
                        aux=int(req.get("kind_id", 0)))
                    resp = {"ok": True, "id": self.me}
                elif m == "be_the_leader":
                    self.queue.put((CONTROL, 0, "be_the_leader", None))
                    resp = {"ok": True}
                else:
                    resp = {"ok": False, "error": f"unknown method {m}"}
                f.write(json.dumps(resp) + "\n")
                f.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _chaos_verb(self, req: dict) -> dict:
        op = req.get("op", "status")
        try:
            if op == "install":
                plan = FaultPlan.from_dict(req["plan"])
                if plan.n != self.cfg.n_replicas:
                    raise ValueError(
                        f"plan sized for {plan.n} replicas, cluster "
                        f"has {self.cfg.n_replicas}")
                self.transport.set_chaos(
                    ChaosShim(self.me, plan, self.queue))
                # journaled from this control thread's own ring: a
                # campaign's fault window is queryable next to the
                # alarms it provoked (value = the plan's seed)
                self.journal.record(EV_CHAOS_INSTALL, subject=self.me,
                                    value=int(plan.seed))
            elif op == "clear":
                self.transport.set_chaos(None)
                self.journal.record(EV_CHAOS_CLEAR, subject=self.me)
            elif op != "status":
                raise ValueError(f"unknown chaos op {op!r}")
        except (KeyError, TypeError, ValueError) as e:
            return {"ok": False, "id": self.me, "error": repr(e)[:200]}
        ch = self.transport.chaos
        return {"ok": True, "id": self.me, "installed": ch is not None,
                "faults": ch.counts() if ch is not None else {},
                "faults_total": self.transport.chaos_faults_total()}

    # ---------------- beacons ----------------

    def _beacon_loop(self) -> None:
        """Reference SendBeacon/ReplyBeacon + EWMA RTT
        (genericsmr.go:537-551, :429). This thread only ENQUEUES the
        beacon; the protocol thread writes it — peer writers are
        single-threaded by contract (transport.py), and a concurrent
        write racing the protocol thread's flush is silently dropped
        (append between flush's snapshot and clear)."""
        while not self._stop.is_set():
            self.queue.put((CONTROL, 0, "send_beacon", None))
            time.sleep(0.2)

    # ---------------- the protocol loop ----------------

    def _warm_step_variants(self) -> None:
        """Compile every (k, narrow) step variant the tick loop can
        select BEFORE serving traffic: a variant first compiled
        mid-trial stalls the protocol thread for seconds — long enough
        for client retry timeouts and duplicate replies (observed when
        the need-scaled k=2 variant first compiled inside a bench
        trial). With the persistent compile cache this is a cache load
        on every boot after the first. Runs on the protocol thread
        (same thread that ticks), on empty inboxes; the handful of
        consumed tick counters is boot noise."""
        empty = MsgBatch(
            **{c: np.zeros(self.cfg.inbox, np.int32) for c in batches.COLS})
        nw = self.flags.narrow_window
        narrows = [0] + ([nw] if nw and nw < self.cfg.window else [])
        ks = {1, max(1, self.flags.fuse_ticks)}  # k is quantized to these
        for k in sorted(ks):
            for narrow in narrows:
                self.state, *_ = self.step(self.state, empty, k, narrow, 0)

    def _run(self) -> None:
        prof = self.flags.profile
        if prof is not None:
            prof.enable()
        try:
            if self.flags.warm_variants:
                self._warm_step_variants()
            if (not self._recovered and self.me == 0
                    and self.protocol != "mencius"):
                # initial boot: replica 0 self-elects
                # (bareminpaxos.go:286-290); wait until the mesh is up
                # so the PREPARE reaches everyone. Mencius has no
                # leader — every replica proposes into its own slots.
                self._wait_for_peers()
                self.queue.put((CONTROL, 0, "be_the_leader", "boot"))
            while not self._stop.is_set():
                self._tick()
            # clean shutdown: complete any deferred host phases so the
            # last tick's replies/persistence aren't dropped with the
            # thread (a FATAL tick deliberately skips this — fail-stop
            # must not keep serving; a crash() drops them by design —
            # a killed process never got to flush either)
            if not self._crashed:
                self._flush_inflight()
        except FatalReplicaError as e:
            # fail-stop: stop serving; the control plane keeps
            # answering pings with ok=False + the fatal reason
            print(f"FATAL: {e}", file=sys.stderr, flush=True)
        except Exception:
            # a crash() races the protocol thread mid-tick (closed
            # sockets, swapped store fd): any exception it provokes is
            # the kill itself, not a bug — die quietly like the killed
            # process would. Everything else propagates.
            if not self._crashed:
                raise
        finally:
            if prof is not None:
                prof.disable()

    def _wait_for_peers(self, timeout_s: float = 15.0) -> None:
        deadline = time.monotonic() + timeout_s
        need = self.cfg.n_replicas - 1
        while time.monotonic() < deadline and not self._stop.is_set():
            n = sum(self.transport.peer_alive(q)
                    for q in range(self.cfg.n_replicas) if q != self.me)
            if n >= need:
                return
            for q in range(self.me):
                if not self.transport.peer_alive(q):
                    self.transport.dial_peer(q)
            time.sleep(0.05)

    # SLO the replica-local burn evaluation runs against (the paxwatch
    # SLO dataclass defaults, on a window short enough for admission
    # to react within a couple of seconds)
    _BURN_SLO_MS = 50.0
    _BURN_WINDOW_S = 2.0

    def _ingress_overloaded(self) -> bool:
        """Admission signal for the ingress coalescer — called by the
        transport's READER threads, so it reads only the published
        snapshot and a plain bool (never ``self.state``). Overload =
        the paxmon exec backlog (committed-but-unexecuted) beyond the
        boot-sized bound, the device window nearly full (commits are
        the bottleneck — a commit-bound cluster would window-reject
        the rows downstream at full device-round-trip cost, so the
        occupancy arm sheds them at the door before the reject/
        retransmit loop amplifies the load), or the replica-local
        paxwatch burn-rate alarm (_update_burn). The coalescer turns
        a True verdict into counted ingress drops once its own
        pending bound is exceeded — bounded queueing at the clients
        instead of tail blowup."""
        snap = self.snapshot
        fr = int(snap.get("frontier", -1))
        ex = int(snap.get("executed", fr))
        wb = int(snap.get("window_base", 0))
        return (fr - ex > self._admit_backlog_limit
                or fr - wb >= self._admit_window_limit
                or self._burn_hot)

    def _update_burn(self, now: float) -> None:
        """Feed the tick-wall histogram's cumulative bad/total pair
        through the SAME ``burn_alarm`` detector the cluster watcher
        runs (obs/watch.py), replica-locally at ~4 Hz, and edge-journal
        the verdict — the admission gate's second input. The bad-bucket
        derivation mirrors ``flatten_cluster_stats``: a bucket is bad
        when its LOWER edge clears the SLO; the overflow bin is always
        bad."""
        if now - self._burn_last_s < 0.25:
            return
        self._burn_last_s = now
        h = self._h_tick
        bad = sum(c for i, c in enumerate(h.counts)
                  if i == len(h.counts) - 1
                  or (0 < i <= len(h.bounds)
                      and h.bounds[i - 1] >= self._BURN_SLO_MS))
        self._burn_samples.append({"t": now, "hist_total": h.total,
                                   "hist_bad": bad, "replicas": {}})
        alarm = burn_alarm(list(self._burn_samples),
                           window_s=self._BURN_WINDOW_S,
                           slo_ms=self._BURN_SLO_MS)
        hot = alarm is not None
        if hot and not self._burn_hot:
            self.journal.record(
                EV_ALARM, subject=self.me,
                value=int(alarm["evidence"].get("window_s", 0) * 1e3),
                aux=DET_BURN)
        elif self._burn_hot and not hot:
            self.journal.record(EV_ALARM_CLEAR, subject=self.me,
                                aux=DET_BURN)
        self._burn_hot = hot

    def _tick(self) -> None:
        # idle throttle: a quiet replica (empty inbox, no output, no
        # pending execution last step) steps at ~20Hz instead of every
        # tick_s — incoming messages still trigger an immediate step
        # via the queue wakeup. Keeps an idle N-replica in-process
        # cluster from saturating small hosts with no-op device steps.
        timeout = self.flags.idle_s if self._idle else self.flags.tick_s
        # one wakeup = one WALL tick: fused device substeps (k > 1)
        # and skipped dispatches alike ride this single increment
        # (paxlint wall-honesty — a k-advance here would age the tick
        # counter k times faster than wall time)
        tick_inc = 1
        t0 = time.perf_counter()
        elect = self._drain(timeout)
        # drain WORK (decode/dedup/registration), with the blocking
        # queue wait subtracted — idle pacing is not drain cost
        self._drain_work_s = (time.perf_counter() - t0
                              - self._drain_wait_s)
        self._update_burn(time.monotonic())
        if (self._boot_pending is not None
                and time.monotonic() >= self._boot_pending):
            self._boot_pending = None
            stale = (self._seen_leader
                     or self.snapshot["frontier"] >= 0
                     or self.snapshot["leader"] not in (-1, self.me))
            if stale:
                dlog(f"replica {self.me}: skipping stale boot "
                     f"self-election (leader={self.snapshot['leader']},"
                     f" frontier={self.snapshot['frontier']})")
            else:
                elect = True
        if (self._idle and not elect and self.inbox.fill == 0
                and time.monotonic() - self._last_step < self.flags.idle_s):
            # going quiet: deferred host phases must not sit out the
            # idle window (their replies/broadcasts are already late
            # by one enqueue — never by a poll interval)
            self._flush_inflight()
            return
        # idle fast path: the device itself said (work_pending scalar,
        # published with the last snapshot) that an empty-inbox step
        # would be a no-op — skip the dispatch entirely instead of
        # burning a 0.3-0.9 ms device round trip per idle poll. A real
        # tick still runs at least every idle_skip_max_s as a safety
        # net, and any drained frame or election falls through.
        if (self.flags.idle_fastpath and not elect
                and self.inbox.fill == 0
                and not self.snapshot.get("work_pending", True)
                and time.monotonic() - self._last_dispatch
                < self.flags.idle_skip_max_s):
            self._flush_inflight()  # see the idle-throttle note above
            self._c_idle_skips.inc()
            self._c_ticks.inc(tick_inc)
            if self.recorder is not None:
                self.recorder.record(
                    monotonic_ns(), KIND_IDLE_SKIP, 0, 0, 0,
                    self.snapshot["frontier"], 0,
                    int(self._drain_work_s * 1e6), 0, 0, 0, 0, 0, 0,
                    chaos_faults=self.transport.chaos_faults_total(),
                    coal_wake=(self.coalescer._c_wakeups.value
                               if self.coalescer is not None else 0))
            # skipping IS being idle: without this the next poll waits
            # only tick_s (2 ms) and a quiet replica spins the skip
            # check at 500 Hz instead of idle_s pacing
            self._idle = True
            # _drain can have BUFFERED frames this iteration without
            # making the inbox non-empty (beacons, beacon replies) —
            # flush them now or they sit until the safety-net tick and
            # the RTT EWMA measures buffering delay instead of network
            # (flush_all on empty writers is a cheap no-op)
            self.transport.flush_all()
            return
        if elect:
            self._become_leader()
            self._last_elect = time.monotonic()
        elif (self.snapshot["leader"] == self.me
              and not self.snapshot["prepared"]
              and time.monotonic() - getattr(self, "_last_elect", 0.0) > 0.5):
            # the one-shot PREPARE broadcast can be lost (a peer mid
            # store-replay or reconnecting isn't reading yet), which
            # would wedge an elected leader unprepared forever — re-run
            # the prepare round at a fresh ballot until majority answers
            self._become_leader()
            self._last_elect = time.monotonic()
        self._device_tick(self.inbox)
        # overlapped commit->exec->reply (the exec chase): a slot this
        # dispatch committed executes in the NEXT dispatch — which,
        # with an empty queue, used to fire only after the poll
        # timeout: the entire <exec_wait> paxtrace stage. Run the
        # follow-up dispatch(es) in THIS wakeup while backlog remains
        # and no fresh traffic is queued. Each chased dispatch is the
        # identical deterministic step the next wakeup would have run
        # (same fuse/narrow decision inputs, no new compiled variant),
        # so replies and state are byte-exact vs the strict cadence —
        # merely earlier in wall time. Bounded, with a forward-
        # progress check: a wedged backlog (execution blocked on a
        # commit hole) must park on the poll loop, not spin here.
        if self.flags.overlap_exec:
            for _ in range(8):
                snap = self.snapshot
                prev_exec = int(snap.get("executed", -1))
                if (snap["frontier"] <= prev_exec or self.inbox.fill
                        or not self.queue.empty()):
                    break
                self._device_tick(self.inbox)
                if int(self.snapshot.get("executed", -1)) <= prev_exec:
                    break  # no forward progress: stop chasing
        self._maybe_snapshot()
        self._last_step = time.monotonic()
        self._c_ticks.inc(tick_inc)

    def _maybe_snapshot(self) -> None:
        """Snapshot + truncation policy (protocol thread, after the
        tick's dispatches): checkpoint once the on-disk log grew
        snap_every_bytes past the last snapshot, or snap_interval_s
        elapsed with new execution. Rate-limited to 4 Hz — the size
        probe is a stat() call, not per-tick material. Mencius is
        gated off: its recovery replays the full log (ownership
        cursors have no snapshot restore), so truncating under it
        would orphan its own restart."""
        fl = self.flags
        if (not fl.snapshots or self._snap_disabled or self._crashed
                or self.protocol == "mencius" or self.fatal is not None):
            return
        now = time.monotonic()
        if now < self._snap_check_s:
            return
        self._snap_check_s = now + 0.25
        exec_upto = int(self.snapshot.get("executed", -1))
        if exec_upto < 0 or exec_upto <= self.store.snap_frontier:
            return  # nothing newly applied to checkpoint
        size_due = (fl.snap_every_bytes > 0
                    and self.store.log_bytes() >= self._snap_goal_bytes)
        time_due = (fl.snap_interval_s > 0
                    and now - self._snap_last_s >= fl.snap_interval_s)
        if size_due or time_due:
            self._take_snapshot(exec_upto)

    def _take_snapshot(self, exec_upto: int) -> None:
        """Checkpoint the applied KV state at ``exec_upto`` into the
        stable store and truncate the redo log (one atomic segment
        swap, stable.py take_snapshot — two snapshots retained for the
        corruption-fallback ladder). Runs between dispatches, so
        ``self.state``'s buffers are alive and the published snapshot
        corresponds exactly to them; deferred host phases complete
        first so every record at/below exec_upto is in the store
        before the rewrite."""
        self._flush_inflight()
        kv = self.state.kv
        live = np.asarray(kv.slot) == LIVE
        keys = join_i64(np.asarray(kv.key_hi)[live],
                        np.asarray(kv.key_lo)[live])
        v = np.asarray(kv.val)
        vals = join_i64(v[live, 0], v[live, 1])
        freed = self.store.take_snapshot(keys, vals, exec_upto,
                                         wall_ns=time.time_ns())
        if freed == -1:
            # v1 store file (no CRC framing to protect a snapshot):
            # the policy can never succeed on this file — stop probing
            self._snap_disabled = True
            return
        lb = self.store.log_bytes()
        # EV_SNAPSHOT: value = checkpointed frontier, aux = log bytes
        # after; EV_TRUNCATE only when disk actually shrank (the first
        # snapshot truncates nothing): value = bytes freed
        self.journal.record(EV_SNAPSHOT, subject=self.me,
                            value=exec_upto, aux=lb)
        if freed > 0:
            self.journal.record(EV_TRUNCATE, subject=self.me,
                                value=freed, aux=lb)
        self._snap_goal_bytes = lb + max(self.flags.snap_every_bytes, 1)
        self._snap_last_s = time.monotonic()
        dlog(f"replica {self.me}: snapshot@{exec_upto} "
             f"({len(keys)} pairs, freed {freed} B, log {lb} B)")

    def _drain(self, timeout_s: float) -> bool:
        """Pull queued frames into the inbox buffer; returns whether a
        be_the_leader control event arrived."""
        elect = False
        t0 = time.perf_counter()
        try:
            item = self.queue.get(timeout=timeout_s)
        except queue.Empty:
            self._drain_wait_s = time.perf_counter() - t0
            return False
        self._drain_wait_s = time.perf_counter() - t0
        while True:
            src_kind, conn_id, kind, rows = item
            if src_kind == CONTROL:
                if kind == "be_the_leader":
                    # the BOOT self-election is a cold-start convenience
                    # (bareminpaxos.go:286-290), not an authority claim:
                    # if this replica's first tick was delayed (a long
                    # first jit compile on a loaded host) the cluster
                    # may already have an active leader + committed
                    # prefix — deposing it with an empty log wedged the
                    # cluster at the old leader's last catch-up chunk
                    # (round-5 wedge hunt). Defer the decision half a
                    # second of ticking (_tick settles it) so traffic
                    # racing the boot event can land first. Master
                    # promotions (rows is None) stay unconditional: the
                    # master knows more than we do.
                    if rows == "boot":
                        self._boot_pending = time.monotonic() + 0.5
                    else:
                        elect = True
                elif kind == "send_beacon":
                    rows = make_batch(MsgKind.BEACON, rid=self.me,
                                      timestamp=np.uint64(cputicks()))
                    for q in range(self.cfg.n_replicas):
                        if q != self.me:
                            self.transport.send_peer(q, MsgKind.BEACON,
                                                     rows)
            elif src_kind == CONN_LOST:
                pass  # peer redial is lazy (dispatch path)
            elif kind == MsgKind.BEACON:
                self.transport.send_peer(
                    int(rows["rid"][0]), MsgKind.BEACON_REPLY, rows)
            elif kind == MsgKind.BEACON_REPLY:
                rtt = cputicks() - int(rows["timestamp"][0])
                # the replier echoes the beacon unchanged, so rid is OUR
                # id; the peer is the connection the reply came in on
                q = conn_id if src_kind == FROM_PEER else int(rows["rid"][0])
                if q != self.me:
                    old = self.rtt_ewma[q]
                    self.rtt_ewma[q] = (rtt if np.isinf(old)
                                        else 0.99 * old + 0.01 * rtt)
            elif kind == MsgKind.READ:
                # linearizable read: goes through the log as a GET
                # (the reference parses-and-drops READ,
                # genericsmr.go:470-477; we serve it)
                n = len(rows)
                k_hi, k_lo = split_i64(rows["key"])
                self.inbox.append(
                    n, kind=int(MsgKind.PROPOSE), src=-1, op=int(Op.GET),
                    key_hi=k_hi, key_lo=k_lo, cmd_id=rows["cmd_id"],
                    client_id=conn_id)
                for c in rows["cmd_id"]:
                    self._pending[(conn_id, int(c))] = MsgKind.READ_REPLY
            elif kind == MsgKind.TRACE_CTX:
                # paxtrace context (host-path verb, never a device
                # row): echo the client's origin timestamp as the
                # chain's start span, RE-STAMPED into this replica's
                # monotonic domain (wall minus OUR wall-mono offset —
                # an exact identity when client and replica share a
                # host, the honest correction when they don't).
                # Filtered through OUR sampling exponent: a client
                # tracing more aggressively than the cluster must
                # degrade to the intersection, not flood the protocol
                # thread's ring with ORIGIN rows whose chains can
                # never complete.
                if self.trace_sink.enabled and len(rows):
                    m = self.trace_sink.sampled(rows["cmd_id"])
                    if m.any():
                        ring = self.trace_sink.ring()
                        my_off = time.time_ns() - monotonic_ns()
                        take = rows[m]
                        for cmd, tid, wall in zip(
                                take["cmd_id"].tolist(),
                                take["trace_id"].tolist(),
                                take["origin_wall_ns"].tolist()):
                            ring.record(tid, ST_ORIGIN, wall - my_off,
                                        wall - my_off, cmd)
            elif kind == MsgKind.SNAP_META:
                # snapshot catch-up announcement (host-path verb, like
                # TRACE_CTX — never a device row): open an assembly
                # buffer per announced frontier. Only transfers ahead
                # of our own executed frontier are worth assembling.
                for r in rows:
                    fr = int(r["frontier"])
                    if (fr > int(self.snapshot.get("executed", -1))
                            and fr not in self._snap_rx):
                        self._snap_rx[fr] = {"count": int(r["count"]),
                                             "src": int(r["leader_id"]),
                                             "rows": []}
                self._snap_rx_install()  # count=0 installs immediately
            elif kind == MsgKind.SNAP_ROWS:
                # pairs for an announced transfer; the per-row frontier
                # keys each row to ITS snapshot, so interleaved or
                # re-sent transfers can't splice
                for fr in np.unique(rows["frontier"]):
                    st = self._snap_rx.get(int(fr))
                    if st is not None:
                        st["rows"].append(rows[rows["frontier"] == fr])
                self._snap_rx_install()
            else:
                if src_kind == FROM_PEER and kind in (
                        MsgKind.PREPARE, MsgKind.ACCEPT, MsgKind.COMMIT,
                        MsgKind.COMMIT_SHORT):
                    # sticky: leader-originated traffic exists, so a
                    # still-queued boot self-election is stale even if
                    # the snapshot hasn't caught up yet (first drain
                    # runs before the first device tick)
                    self._seen_leader = True
                if src_kind == FROM_CLIENT and kind == MsgKind.PROPOSE:
                    # drop same-connection re-sends of still-pending
                    # commands: the client's retry driver re-proposes
                    # unacked ids after a timeout, and admitting the
                    # re-send would allocate a SECOND log slot (and a
                    # second reply) for a command that is merely slow —
                    # under load that amplifies into a retry storm
                    # (each re-proposal adds slots, slowing commits,
                    # causing more timeouts; Mencius's blocking
                    # frontier made this a death spiral, round 5). A
                    # failed-over client arrives on a NEW connection
                    # and is admitted as before.
                    fresh = np.fromiter(
                        ((conn_id, int(c)) not in self._pending
                         for c in rows["cmd_id"]), bool, len(rows))
                    if not fresh.all():
                        rows = rows[fresh]
                    # truncate to inbox room BEFORE registering: a row
                    # registered but dropped by ColumnBuffer overflow
                    # would make the dedup blackhole its retries (the
                    # reply that pops the pending entry never comes)
                    rows = rows[:max(self.inbox.room(), 0)]
                    for c in rows["cmd_id"]:
                        self._pending[(conn_id, int(c))] = MsgKind.PROPOSE_REPLY
                    self._c_proposals.inc(len(rows))
                    if self.trace_sink.enabled and len(rows):
                        # drain stamp for sampled commands; aux = the
                        # dispatch counter, so tail.py can say how many
                        # device rounds admission -> execution took
                        # (the flight-recorder row correlation)
                        t_dr = monotonic_ns()
                        self.trace_sink.stamp_batch(
                            ST_DRAIN, rows["cmd_id"], t_dr, t_dr,
                            aux=self._c_dispatches.value)
                    if DLOG:
                        dlog(f"replica {self.me}: drain PROPOSE "
                             f"n={len(rows)}")
                if kind == MsgKind.PREPARE_INST:
                    # beyond-retention heal, ALL protocols: a sweep
                    # (mencius takeover, or a re-elected laggard
                    # leader's phase-1 sweep) asks about slots we
                    # already slid out; the device can't answer (out of
                    # window) but the stable store's mirror can — serve
                    # the range as COMMIT rows. Without this, a leader
                    # elected with a stale log wedges forever once its
                    # sweep reaches slots beyond every follower's
                    # window (round-5 wedge hunt).
                    self._store_answer_sweep(rows)
                batches.frame_to_rows(self.inbox, kind, rows, conn_id)
            if self.inbox.room() <= 0:
                break
            try:
                item = self.queue.get_nowait()
            except queue.Empty:
                break
        return elect

    def _store_commit_frame(self, lo: int, hi: int, frontier: int):
        """A COMMIT wire frame of store-mirror records for [lo, hi],
        or None if no records exist — the building block of both
        store-served heal paths (_host_catchup, _mencius_store_answer)."""
        rec = self.store.read_range(lo, hi)
        if len(rec) == 0:
            return None
        return make_batch(
            MsgKind.COMMIT, leader_id=self.me, inst=rec["inst"],
            ballot=rec["ballot"], op=rec["op"], key=rec["key"],
            val=rec["val"], cmd_id=rec["cmd_id"],
            client_id=rec["client_id"], last_committed=frontier)

    def _store_answer_sweep(self, rows) -> None:
        """Serve a PREPARE_INST sweep that reaches below our window
        from the durable mirror: COMMIT rows for [lowest asked slot,
        committed prefix], chunked by catchup_rows. Not capped at the
        asked range — the laggard's crt_inst advances from the commits
        it applies, which is what lets its next sweep reach further
        (its own view of the log tip is stale by exactly the gap).
        Serves mencius takeover sweeps and minpaxos/classic new-leader
        phase-1 sweeps alike."""
        base = self.snapshot["window_base"]
        lo = int(rows["inst"].min())
        if lo >= base:
            return  # in-window: the device answers
        q = int(rows["leader_id"][0])
        if not (0 <= q < self.cfg.n_replicas) or q == self.me:
            return
        sb = self.store.base
        if sb >= 0 and lo <= sb:
            # the sweep reaches below our truncation frontier: those
            # redo records are gone — serve the snapshot (pull-path
            # mirror of _host_catchup's push), then commits above it
            self._send_snapshot(q)
            lo = sb + 1
        hi = min(lo + self.cfg.catchup_rows - 1, self.store.committed_prefix())
        if hi < lo:
            self.transport.flush_all()  # the snapshot frames, if any
            return
        frame = self._store_commit_frame(lo, hi, self.snapshot["frontier"])
        if frame is not None:
            self._send_or_redial(q, MsgKind.COMMIT, frame)
        self.transport.flush_all()

    def _become_leader(self) -> None:
        if self.protocol == "mencius":
            return  # no leaders; master be_the_leader promotions no-op
        # complete any deferred host phases first: the election's
        # PREPARE must not overtake the previous tick's still-buffered
        # accepts/commits on the wire
        self._flush_inflight()
        self.state, prep = become_leader(self.cfg, self.state)
        cols = {c: np.asarray(getattr(prep, c)) for c in batches.COLS
                if c != "kind"}
        cols["kind"] = np.asarray(prep.kind)
        frames = batches.rows_to_frames(cols, np.array([True]))
        for kind, frame in frames:
            for q in range(self.cfg.n_replicas):
                if q != self.me:
                    self._send_or_redial(q, kind, frame)
        self.transport.flush_all()
        self._c_elections.inc()
        self.journal.record(EV_ELECTION, subject=self.me,
                            value=self.snapshot["frontier"])
        dlog(f"replica {self.me}: running election")

    # message kinds whose rows address log slots (narrow-view gating
    # reads their slot ranges host-side; everything else only touches
    # scalars or is handled positionally)
    _ADDR_KINDS = (int(MsgKind.ACCEPT), int(MsgKind.COMMIT),
                   int(MsgKind.PREPARE_INST),
                   int(MsgKind.PREPARE_INST_REPLY))
    # kinds that can move crt_inst beyond any row's inst (election
    # traffic reporting peers' log tips) — always take the full step
    _FULL_STEP_KINDS = (int(MsgKind.PREPARE), int(MsgKind.PREPARE_REPLY))

    def _choose_fuse(self, n_rows: int) -> int:
        """Fused substeps for this dispatch: >1 only when the snapshot
        shows follow-up ticks are certainly coming — an exec backlog
        deeper than one exec_batch, or catch-up/broadcast/takeover
        cursors trailing the frontier by a RECOVERY-scale gap. The lag
        threshold is deliberately ~2 client batches (2 x inbox): under
        healthy closed-loop load a follower's reported frontier always
        trails the leader's by about one in-flight batch (it learns
        commitment from the NEXT accept's piggyback), and fusing on
        that steady-state pipeline lag paid 3x compute + duplicate
        catch-up rows per dispatch for follow-ups that had no work
        (measured: first bench attempt this round collapsed to ~2.8k
        ops/s). Blind fusion is a de-optimization; backlog/heal fusion
        is the win."""
        kf = max(1, self.flags.fuse_ticks)
        snap = self.snapshot
        if kf == 1 or "low" not in snap:
            return 1
        if not self.queue.empty():
            # traffic already queued: the next dispatch happens
            # immediately anyway, so its floor is paid regardless —
            # fusing here only delays draining the queue (a k=3 burst
            # blocks inbound acks for 2 extra substeps of compute,
            # which on a compute-bound host stalls the whole pipeline;
            # the first ON-leg A/B measured it as -20% closed-loop)
            return 1
        backlog = snap["frontier"] - snap["executed"]
        trail = snap["frontier"] + 1 - snap["low"]
        lag_floor = max(2 * self.cfg.inbox, self.cfg.catchup_rows)
        if trail > lag_floor:
            return kf  # recovery-scale heal: chunked follow-ups for sure
        if backlog > (kf - 1) * self.cfg.exec_batch:
            # every one of the kf substeps has a full exec_batch of
            # certain work. k is quantized to {1, kf} on purpose: a
            # trailing substep with no work costs a full step of
            # compute (worse than the dispatch it saves on a
            # compute-bound host), and every distinct k is a separate
            # compiled variant — intermediate k values bought little
            # and their first-compile stalls caused client-retry
            # duplicates mid-bench.
            return kf
        return 1

    def _choose_narrow(self, cols, n_rows: int) -> tuple[int, int]:
        """(narrow, off) for this dispatch, or (0, 0) for the full
        step. The narrow view is exact — not an approximation — only
        when every slot the substeps could read or write lands inside
        [window_base+off, window_base+off+narrow): the device-published
        low/high anchors bound the timer-driven paths (exec, retry,
        sweep, catch-up, commit broadcast), the inbox bound covers
        message-driven writes, and proposals extend the tip by at most
        n_rows slots (times R for Mencius's strided ownership)."""
        nw = self.flags.narrow_window
        snap = self.snapshot
        if not nw or nw >= self.cfg.window or "low" not in snap:
            return 0, 0
        if self._narrow_doubt:
            # a post-readback anchor validation failed: run ONE
            # full-width step to recount true anchors from the whole
            # window before trusting the narrow proof again
            self._narrow_doubt = False
            return 0, 0
        base = snap["window_base"]
        low = max(snap["low"], base)
        off = low - base
        if off > self.cfg.window - nw:
            return 0, 0  # view would run off the window; full step slides
        top = base + off + nw  # absolute, exclusive
        stride = self.cfg.n_replicas if self.protocol == "mencius" else 1
        if snap["high"] + n_rows * stride + 1 > top:
            return 0, 0
        if n_rows:
            k = cols["kind"][:n_rows]
            if np.isin(k, self._FULL_STEP_KINDS).any():
                return 0, 0
            inst = cols["inst"][:n_rows]
            lo_req, hi_req = top, low - 1  # empty bounds
            addr = np.isin(k, self._ADDR_KINDS)
            if addr.any():
                lo_req = min(lo_req, int(inst[addr].min()))
                hi_req = max(hi_req, int(inst[addr].max()))
            ar = k == int(MsgKind.ACCEPT_REPLY)
            if ar.any():
                lo_req = min(lo_req, int(inst[ar].min()))
                # run-length acks cover [inst, inst + (count-1)*stride]
                hi_req = max(hi_req, int(
                    (inst[ar] + (np.maximum(cols["cmd_id"][:n_rows][ar], 1)
                                 - 1) * stride).max()))
            sk = k == int(MsgKind.SKIP)
            if sk.any():
                lo_req = min(lo_req, int(
                    cols["last_committed"][:n_rows][sk].min()))
                hi_req = max(hi_req, int(inst[sk].max()))
            if self.protocol == "mencius":
                # COMMIT piggybacks advance crt_inst by the sender's
                # frontier too (models/mencius.py section 6)
                com = k == int(MsgKind.COMMIT)
                if com.any():
                    hi_req = max(hi_req, int(
                        cols["last_committed"][:n_rows][com].max()))
            if lo_req < low or hi_req >= top:
                return 0, 0
        return nw, off

    def _device_tick(self, buf: batches.ColumnBuffer,
                     persist: bool = True, dispatch: bool = True) -> None:
        """One dispatch, as a depth-2 software pipeline: drain this
        tick's inbox and ENQUEUE its jitted step without blocking
        (JAX async dispatch), run the PREVIOUS tick's deferred host
        phases while the device computes, and only then read this
        tick back. Fuse/narrow/idle decisions already consumed the
        previous tick's published snapshot in the serial order, so
        their inputs are unchanged; the step's state input is threaded
        device-side. Host phases are deferred for the NEXT call only
        when follow-up traffic is already queued (see _finish_host) —
        otherwise they complete here, preserving the serial order
        exactly (-nopipeline forces that always)."""
        if DLOG and buf.fill:
            dlog(f"replica {self.me}: tick start fill={buf.fill}")
        t0 = time.perf_counter()
        cols, n_rows = buf.drain()
        inbox = MsgBatch(**{c: np.asarray(cols[c]) for c in batches.COLS})
        k = self._choose_fuse(n_rows)
        narrow, off = self._choose_narrow(cols, n_rows)
        view_lo = self.snapshot.get("window_base", 0) + off
        # enqueue: on an async backend the call returns with the
        # outputs still in flight; everything until the np.asarray
        # below overlaps device compute
        self.state, out_mats_d, exec_mats_d, scals_d = self.step(
            self.state, inbox, k, narrow, off)
        t_enq = time.perf_counter()
        # the previous tick's host phases, hidden under this compute
        self._flush_inflight(overlapped=True)
        t_host = time.perf_counter()
        # THREE device reads per dispatch, covering ALL k substeps
        # (stacked outbox/exec/scalar matrices) — see _packed_step;
        # np.asarray blocks until the device finishes: the readback
        out_mats = np.asarray(out_mats_d)
        exec_mats = np.asarray(exec_mats_d)
        scals = np.asarray(scals_d)
        t_rb = time.perf_counter()
        t_rb_ns = monotonic_ns()  # trace anchor for the dispatch phases
        self._c_dispatches.inc()
        self._c_fused_substeps.inc(k)
        # regime classification, exactly one per dispatch (the flight
        # recorder's kind field uses the same precedence)
        if narrow:
            self._c_narrow_steps.inc()
        elif k > 1:
            self._c_fused_dispatches.inc()
        else:
            self._c_full_steps.inc()
        self._last_dispatch = time.monotonic()
        self._check_kv_load()
        if DLOG and n_rows:
            dlog(f"replica {self.me}: enqueue+readback k={k} "
                 f"narrow={narrow} {(t_rb - t0) * 1e3:.2f}ms")
        mencius = self.protocol == "mencius"
        last = scals[-1]
        self._last_scals = last  # STATS verb surfaces the full vector
        frontier_last = int(last[SCAL_FRONTIER])
        if frontier_last < self.snapshot["frontier"]:
            # the commit frontier is monotonic by construction; going
            # backward means device state was rebuilt/corrupted — make
            # that loudly visible (it presents as a silent wedge)
            dlog(f"replica {self.me}: FRONTIER WENT BACKWARD "
                 f"{self.snapshot['frontier']} -> {frontier_last}")
        # published at readback — strictly before the next tick's
        # fuse/narrow/idle decisions AND before this tick's
        # _host_catchup, exactly as in the serial order
        prev_leader = self.snapshot["leader"]
        self.snapshot = {
            "frontier": frontier_last,
            "window_base": int(last[SCAL_WINDOW_BASE]),
            "crt_inst": int(last[SCAL_CRT_INST]),
            # mencius is leaderless: leader=-1 hints clients any
            # replica serves; prepared=True keeps the re-prepare
            # wedge-guard inert
            "leader": -1 if mencius else int(last[SCAL_LEADER]),
            "prepared": True if mencius else bool(last[SCAL_PREPARED]),
            "executed": int(last[SCAL_EXECUTED]),
            "low": int(last[SCAL_LOW_ANCHOR]),
            "high": int(last[SCAL_HIGH_ANCHOR]),
            "work_pending": bool(last[SCAL_WORK_PENDING]),
        }
        if self.snapshot["leader"] != prev_leader:
            # the device-published leader view moved: an election
            # landed (ours or a peer's) — the journal's leader-change
            # timeline is what the churn detector's evidence joins to
            self.journal.record(EV_LEADER_CHANGE,
                                subject=self.snapshot["leader"],
                                value=frontier_last, aux=prev_leader)
        if narrow:
            # post-readback anchor validation (defense in depth for
            # the pipeline): the choose-time proof said every slot the
            # substeps could touch lies in [view_lo, view_lo+narrow);
            # the device-published post-substep anchors must agree.
            # The low anchor is clamped to each substep's window_base
            # first — a peer lagging BELOW the window legitimately
            # drags low_anchor under the view, but those slots are
            # host-served (_host_catchup), not step-touched, exactly
            # as _choose_narrow's own max(low, base). A violation
            # means a containment assumption broke — count it and
            # recount anchors through one full-width step before
            # trusting the narrow proof again.
            lows = np.maximum(scals[:, SCAL_LOW_ANCHOR],
                              scals[:, SCAL_WINDOW_BASE])
            if (int(lows.min()) < view_lo
                    or int(scals[:, SCAL_HIGH_ANCHOR].max())
                    > view_lo + narrow):
                self._c_narrow_fallbacks.inc()
                self._narrow_doubt = True
                self.journal.record(
                    EV_NARROW_FALLBACK, subject=self.me,
                    value=self._c_narrow_fallbacks.value)
                dlog(f"replica {self.me}: narrow anchor validation "
                     f"FAILED (view [{view_lo}, {view_lo + narrow}), "
                     f"anchors [{int(scals[:, SCAL_LOW_ANCHOR].min())}, "
                     f"{int(scals[:, SCAL_HIGH_ANCHOR].max())}]); next "
                     f"dispatch recounts full-width")
        # read the [R] peer-commit vector NOW, while this state's
        # buffers are still alive (the next enqueue donates them):
        # deferred _host_catchup must see THIS tick's values, and a
        # lazy read later would block on — and read — the next step
        pc = None if mencius else np.asarray(self.state.peer_commits)
        rows_out = int((out_mats[:, 0, :] != 0).sum())  # col 0 = kind
        exec_total = int(scals[:, SCAL_EXEC_COUNT].sum())
        self._idle = (n_rows == 0 and rows_out == 0 and exec_total == 0)
        # KV saturation is a correctness failure, not a statistic: a
        # dropped insert belongs to a command that was (or will be)
        # acked, so the state machine silently diverges from the log.
        # The reference's Go map grows without limit (state.go:33-36);
        # a fixed-capacity table must fail-stop instead of serving
        # wrong data. Checked every dispatch, BEFORE this tick's host
        # phases can queue: a fatal tick's replies must never leave.
        dropped = int(last[SCAL_KV_DROPPED])
        if dropped and self.fatal is None:
            self.fatal = (
                f"replica {self.me}: KV table saturated — {dropped} "
                f"write(s) dropped (kv_pow2={self.cfg.kv_pow2} is too "
                f"small for the live key space); failing stop")
            self.journal.record(EV_FATAL, subject=self.me,
                                value=dropped)
            raise FatalReplicaError(self.fatal)
        drain_s, self._drain_work_s = self._drain_work_s, 0.0
        # coalescer telemetry for the recorder row (schema v7): the
        # rows the ingress front batched into this tick's drain, and
        # the cumulative wakeup kicks. A chased dispatch (overlap_exec)
        # reads 0 — its inbox came from no drain.
        coal = self.coalescer
        coal_occ = coal_wake = 0
        if coal is not None:
            coal_occ, coal.last_occupancy = coal.last_occupancy, 0
            coal_wake = coal._c_wakeups.value
        rec = _InflightTick(
            cols=cols, n_rows=n_rows, out_mats=out_mats,
            exec_mats=exec_mats, scals=scals, k=k,
            kind=(KIND_NARROW if narrow
                  else KIND_FUSED if k > 1 else KIND_FULL),
            persist=persist, dispatch=dispatch, frontier=frontier_last,
            backlog=frontier_last - int(last[SCAL_EXECUTED]),
            rows_out=rows_out, peer_commits=pc, snap=self.snapshot,
            drain_us=int(drain_s * 1e6),
            enqueue_us=int((t_enq - t0) * 1e6),
            readback_us=int((t_rb - t_host) * 1e6),
            t_rb_ns=t_rb_ns, coal_occ=coal_occ, coal_wake=coal_wake)
        self._inflight = rec
        # defer only when the next dispatch is imminent (traffic
        # already queued): its enqueue is what the host phases hide
        # under. With an empty queue the next wakeup may be a full
        # idle interval away — a serial op's reply must not wait for
        # it, so complete in place (this IS the pre-pipeline order).
        if not (self.flags.pipeline and persist and dispatch
                and not self.queue.empty()):
            self._flush_inflight()

    def _flush_inflight(self, overlapped: bool = False) -> None:
        """Complete the deferred tick's host phases, if any.
        ``overlapped`` marks the stage-2 call between the next tick's
        enqueue and readback — the wall spent there is device-hidden
        and recorded as the row's ``overlap_us``."""
        rec, self._inflight = self._inflight, None
        if rec is not None:
            self._finish_host(rec, overlapped)

    def _finish_host(self, rec: _InflightTick, overlapped: bool) -> None:
        """The host side of one dispatched tick: persist -> dispatch ->
        reply -> catch-up, each as ONE vectorized pass over the stacked
        [k, ...] substep matrices (the old per-substep Python replay
        paid k iterations of mask/extract work per dispatch). Ordering
        contract preserved: the store flush (fsync under -durable)
        happens before any buffered reply frame reaches a socket
        (flush_all is last)."""
        t_f0 = time.perf_counter()
        cols, n_rows, k = rec.cols, rec.n_rows, rec.k
        out_mats, exec_mats, scals = rec.out_mats, rec.exec_mats, rec.scals
        ncols = len(batches.COLS)
        if self.trace_sink.enabled:
            self._trace_commits(rec)
        persist_s = dispatch_s = reply_s = 0.0
        if rec.persist:
            # always maintained (in-memory mirror feeds beyond-window
            # catch-up); -durable additionally fsyncs before replies
            tp = time.perf_counter()
            out0 = {c: out_mats[0][j] for j, c in enumerate(batches.COLS)}
            acked0 = out_mats[0][ncols + 1].astype(bool)
            wrote = self._persist(cols, n_rows, out0, acked0,
                                  int(scals[0][SCAL_FRONTIER]))
            if k > 1:
                # substeps 1..k-1 ran empty inboxes, so every
                # persistable row of theirs is an outbox tail row
                # (retry/noop/catch-up ACCEPTs + mencius SKIPs): one
                # concatenated pass over all of them at once,
                # substep-major order preserved by the reshape
                big = {c: out_mats[1:, j, :].reshape(-1)
                       for j, c in enumerate(batches.COLS)}
                wrote |= self._persist(cols, 0, big,
                                       np.zeros(0, bool), rec.frontier)
            if wrote:
                # ONE store flush (fsync under -durable) covers all k
                # substeps: outbound frames only hit the sockets at
                # flush_all below (FrameWriter buffers, wire/codec.py),
                # so the fsync-before-acks-leave ordering holds without
                # paying k fsyncs per fused dispatch
                self.store.flush()
            persist_s = time.perf_counter() - tp
        if rec.dispatch:
            td = time.perf_counter()
            if rec.rows_out:
                # the reshapes COPY (strided slices), so build them
                # only when there are live rows to scatter — backlog-
                # drain ticks execute commands without emitting any
                flat = {c: out_mats[:, j, :].reshape(-1)
                        for j, c in enumerate(batches.COLS)}
                self._dispatch(flat, out_mats[:, ncols, :].reshape(-1))
            tr = time.perf_counter()
            self._reply_stacked(exec_mats, scals, k, rec.frontier)
            t_cu = time.perf_counter()
            self._host_catchup(rec.peer_commits, rec.snap)
            self.transport.flush_all()
            t_de = time.perf_counter()
            dispatch_s = (tr - td) + (t_de - t_cu)
            reply_s = t_cu - tr
        # flight-recorder row + latency histograms: the per-phase wall
        # decomposition for THIS dispatch, wall-honest under fusion
        # (one row per dispatch, carrying k — a fused burst is one
        # wall tick; consumers divide by k for per-substep cost).
        # overlap_us = this tick's host-phase wall executed while the
        # NEXT dispatch was in flight on the device (0 when serial).
        host_s = time.perf_counter() - t_f0
        if overlapped:
            self._c_pipelined.inc()
        step_s = (rec.enqueue_us + rec.readback_us) / 1e6
        self._h_tick.observe((rec.drain_us / 1e6 + step_s + host_s) * 1e3)
        self._h_step.observe(step_s * 1e3)
        if self.recorder is not None:
            self.recorder.record(
                monotonic_ns(), rec.kind, k, n_rows, rec.rows_out,
                rec.frontier, rec.backlog, rec.drain_us, rec.enqueue_us,
                rec.readback_us, int(host_s * 1e6) if overlapped else 0,
                int(persist_s * 1e6), int(dispatch_s * 1e6),
                int(reply_s * 1e6), rec.t_rb_ns,
                chaos_faults=self.transport.chaos_faults_total(),
                coal_occ=rec.coal_occ, coal_wake=rec.coal_wake)

    # -- paxtrace: slot assignment + commit stamps (protocol thread) --

    def _trace_commits(self, rec: _InflightTick) -> None:
        """Two paxtrace duties per dispatch, both O(sampled):

        1. learn the log slot of every SAMPLED proposal this tick
           admitted — the kernel's ACCEPT broadcast at outbox row i
           carries the slot it assigned to inbox PROPOSE row i (the
           same row alignment ``_persist`` relies on);
        2. stamp ST_COMMIT for tracked slots the tick's frontier just
           covered, at the tick's readback time (``t_rb_ns`` — the
           moment the host LEARNED the commit; the device rounds in
           between are the span).

        The tracked set is a min-heap keyed on slot, NOT a dict: slots
        can sit above the contiguous frontier for many dispatches
        (out-of-order exec, re-proposals), and a full per-dispatch
        scan of every tracked slot is protocol-thread time the
        blocking-frontier protocols cannot spare under load.
        """
        sink = self.trace_sink
        n = rec.n_rows
        if n:
            ik = rec.cols["kind"][:n]
            pm = ik == int(MsgKind.PROPOSE)
            if pm.any():
                ids = rec.cols["cmd_id"][:n]
                out_kind = rec.out_mats[0, 0, :n]  # col 0 = kind
                sm = pm & sink.sampled(ids) \
                    & (out_kind == int(MsgKind.ACCEPT))
                if sm.any():
                    out_inst = rec.out_mats[0, 3, :n]  # col 3 = inst
                    ccol = rec.cols["client_id"][:n]
                    for i in np.nonzero(sm)[0]:
                        # linearizable READs ride the log as PROPOSE
                        # rows too — their chains never complete (no
                        # drain/exec spans by design), so a commit
                        # stamp would only churn the ring
                        if self._pending.get(
                                (int(ccol[i]), int(ids[i]))) \
                                == MsgKind.READ_REPLY:
                            continue
                        heapq.heappush(self._trace_slots,
                                       (int(out_inst[i]), int(ids[i])))
        if self._trace_slots and self._trace_slots[0][0] <= rec.frontier:
            ring = sink.ring()
            while self._trace_slots and \
                    self._trace_slots[0][0] <= rec.frontier:
                s, cmd = heapq.heappop(self._trace_slots)
                ring.record(trace_id_for(cmd), ST_COMMIT,
                            rec.t_rb_ns, rec.t_rb_ns, s)

    # -- durability: reconstruct accepted slots from (inbox, outbox) --

    def _persist(self, in_cols, n_rows, out_cols, acked,
                 frontier: int) -> bool:
        """Accepted slots are reconstructed host-side from the inbox
        plus the kernel's outputs (``frontier`` is this substep's
        committed_upto, read from the packed scalar vector instead of
        a fresh per-tick device read). Returns whether anything was
        appended; the CALLER flushes the store once per dispatch,
        before any buffered ack/reply frame reaches a socket:

        * follower acks: the kernel's per-inbox-row ``acked`` mask
          (Outbox.acked — outbox ACCEPT_REPLY rows are run-length
          compressed and no longer align 1:1 with inbox rows) -> slot
          from inbox ACCEPT row i
        * leader self-accepts: out ACCEPT broadcast at i -> cmd from
          inbox PROPOSE row i (command rows stay row-aligned)
        * commits applied: inbox COMMIT rows
        * retry/noop rows (appended tail segments): out ACCEPT rows
          beyond the inbox range carry full commands
        """
        n = n_rows
        ik = in_cols["kind"][:n]
        ok_acc = acked[:n] & (ik == int(MsgKind.ACCEPT))
        lead_acc = out_cols["kind"][:n] == int(MsgKind.ACCEPT)
        com = ik == int(MsgKind.COMMIT)
        recs = []
        if ok_acc.any() or com.any():
            m = ok_acc | com
            # dedup persists of already-committed slots: a heal sweep
            # delivers R-1 copies of every slot (each peer answers
            # PREPARE_INST with the same COMMIT row, often all in one
            # tick's inbox), and re-ACCEPTs of committed slots re-ack;
            # commitment is final, so re-appending only amplifies log
            # growth + fsync volume. Drop (a) rows the store already
            # holds committed (frontier or explicit record, vectorized),
            # (b) all but the first COMMIT row per inst in this batch.
            idx = np.nonzero(m)[0]
            dup = self.store.is_committed(in_cols["inst"][:n][idx])
            m[idx[dup]] = False
            com = com & m
            cidx = np.nonzero(com)[0]
            if len(cidx) > 1:
                _, first = np.unique(in_cols["inst"][:n][cidx],
                                     return_index=True)
                drop = np.ones(len(cidx), bool)
                drop[first] = False
                m[cidx[drop]] = False
                com = com & m
            recs.append((in_cols["inst"][:n][m], in_cols["ballot"][:n][m],
                         np.where(com[m], COMMITTED, ACCEPTED),
                         in_cols["op"][:n][m],
                         join_i64(in_cols["key_hi"][:n][m], in_cols["key_lo"][:n][m]),
                         join_i64(in_cols["val_hi"][:n][m], in_cols["val_lo"][:n][m]),
                         in_cols["cmd_id"][:n][m], in_cols["client_id"][:n][m]))
        if lead_acc.any():
            m = lead_acc
            recs.append((out_cols["inst"][:n][m], out_cols["ballot"][:n][m],
                         np.full(m.sum(), ACCEPTED),
                         out_cols["op"][:n][m],
                         join_i64(out_cols["key_hi"][:n][m], out_cols["key_lo"][:n][m]),
                         join_i64(out_cols["val_hi"][:n][m], out_cols["val_lo"][:n][m]),
                         out_cols["cmd_id"][:n][m], out_cols["client_id"][:n][m]))
        # appended tail segments (recovery/frontier/catchup/retry rows).
        # Catch-up rows (7c) re-ship slots this leader already holds
        # committed-durable — skip re-appending those (same dedup as
        # above, leader-side); retry rows for uncommitted slots still
        # persist.
        t = slice(n, None)
        tail_acc = (out_cols["kind"][t] == int(MsgKind.ACCEPT)) \
            & ~self.store.is_committed(out_cols["inst"][t])
        if tail_acc.any():
            m = tail_acc
            recs.append((out_cols["inst"][t][m], out_cols["ballot"][t][m],
                         np.full(m.sum(), ACCEPTED),
                         out_cols["op"][t][m],
                         join_i64(out_cols["key_hi"][t][m], out_cols["key_lo"][t][m]),
                         join_i64(out_cols["val_hi"][t][m], out_cols["val_lo"][t][m]),
                         out_cols["cmd_id"][t][m], out_cols["client_id"][t][m]))
        if self.protocol == "mencius":
            # SKIP ranges commit no-ops for the ceder's owned slots
            # (models/mencius.py steps 3-4); without records for them
            # the committed prefix would have permanent holes on replay
            from minpaxos_tpu.wire.messages import Op as _Op

            for cols_, hi in ((in_cols, n), (out_cols, None)):
                ks = cols_["kind"][:hi]
                for j in np.nonzero(ks == int(MsgKind.SKIP))[0]:
                    owner = int(cols_["src"][:hi][j])
                    start = int(cols_["last_committed"][:hi][j])
                    end = int(cols_["inst"][:hi][j])
                    if end < start:
                        continue
                    slots = np.arange(start, end + 1, dtype=np.int64)
                    slots = slots[slots % self.cfg.n_replicas == owner]
                    slots = slots[~self.store.is_committed(slots)]
                    if len(slots):
                        z = np.zeros(len(slots), np.int64)
                        recs.append((slots.astype(np.int32),
                                     z.astype(np.int32),
                                     np.full(len(slots), COMMITTED),
                                     np.full(len(slots), int(_Op.NONE)),
                                     z, z, z.astype(np.int32),
                                     np.full(len(slots), -1, np.int32)))
        wrote = False
        for inst, ballot, status, op, key, val, cmd, cli in recs:
            if len(inst):
                self.store.append_slots(inst, ballot, status, op, key, val,
                                        cmd, cli)
                wrote = True
        if frontier > self.store.frontier:
            self.store.append_frontier(frontier)
            wrote = True
        return wrote

    # -- outbox dispatch --

    def _quorum_targets(self) -> list[int]:
        """Thrifty: accepts go to floor(N/2) peers only
        (paxos.go:278-281); with beacons on, the lowest-RTT peers
        (UpdatePreferredPeerOrder, genericsmr.go:554-580)."""
        peers = [q for q in range(self.cfg.n_replicas) if q != self.me]
        if self.flags.beacon:
            peers.sort(key=lambda q: self.rtt_ewma[q])
        return peers[: self.cfg.n_replicas // 2]

    def _send_or_redial(self, q, kind, frame) -> None:
        if not self.transport.send_peer(q, kind, frame):
            if self.transport.dial_peer(q):
                self.transport.send_peer(q, kind, frame)

    def _dispatch(self, out_cols, dst) -> None:
        kinds = out_cols["kind"]
        live = kinds != 0
        if not live.any():
            return
        if DLOG:
            dlog(f"replica {self.me}: dispatch "
                 f"{np.bincount(kinds[live]).nonzero()[0].tolist()}")
        thrifty_q = self._quorum_targets() if self.flags.thrifty else None
        for q in range(self.cfg.n_replicas):
            if q == self.me:
                continue
            mask = live & ((dst == q) | (dst == -1))
            if thrifty_q is not None and q not in thrifty_q:
                # thrifty drops broadcast ACCEPTs for non-quorum peers;
                # unicast rows (their catch-up) still flow
                mask = mask & ~((dst == -1) & (kinds == int(MsgKind.ACCEPT)))
            if not mask.any():
                continue
            for kind, frame in batches.rows_to_frames(out_cols, mask):
                self._send_or_redial(q, kind, frame)
        # client-bound rejections (dst == -2): ProposeReplyTS{FALSE,
        # Leader} so clients re-route (bareminpaxos.go:618-625)
        rej = live & (dst == -2) & (kinds == int(MsgKind.PROPOSE_REPLY))
        if rej.any():
            self._c_rejected.inc(int(rej.sum()))
            leader_hint = out_cols["ballot"][rej]
            cids = out_cols["client_id"][rej]
            cmds = out_cols["cmd_id"][rej]
            for cid in np.unique(cids):
                m = cids == cid
                frame = make_batch(MsgKind.PROPOSE_REPLY, ok=0,
                                   cmd_id=cmds[m], val=0,
                                   timestamp=monotonic_ns(),
                                   leader=leader_hint[m].astype(np.int8))
                self.transport.send_client(int(cid), MsgKind.PROPOSE_REPLY,
                                           frame)
                for c in cmds[m]:
                    self._pending.pop((int(cid), int(c)), None)

    # -- execution replies (ReplyProposeTS, genericsmr.go:529) --

    def _reply_stacked(self, exec_mats: np.ndarray, scals: np.ndarray,
                       k: int, frontier: int) -> None:
        """Execution replies for ALL k substeps in one pass: the
        stacked [k, 6, E] exec matrices concatenate (substep-major, so
        per-connection reply order matches the k-iteration replay this
        replaces) and the grouping/pending bookkeeping runs once."""
        counts = scals[:, SCAL_EXEC_COUNT]
        total = int(counts.sum())
        self._c_executed.inc(total)
        self._g_committed.set(frontier + 1)
        if total == 0 or not self.flags.dreply:
            return
        if DLOG:
            dlog(f"replica {self.me}: reply n={total}")
        live = [i for i in range(k) if counts[i] > 0]
        cids = np.concatenate(
            [exec_mats[i][5][:int(counts[i])] for i in live])
        cmds = np.concatenate(
            [exec_mats[i][4][:int(counts[i])] for i in live])
        vals = join_i64(
            np.concatenate([exec_mats[i][0][:int(counts[i])]
                            for i in live]),
            np.concatenate([exec_mats[i][1][:int(counts[i])]
                            for i in live]))
        # group-by client connection: ONE frame (and one socket write)
        # per (conn, kind) instead of a frame per executed command —
        # the reply path must stay invisible next to the device step
        # at bench load. No-op fills (cid < 0) are dropped vectorized.
        writes: dict[int, tuple[list, list]] = {}
        reads: dict[int, tuple[list, list]] = {}
        sink = self.trace_sink
        tracing = sink.enabled
        traced: list[int] = []
        t_x0 = monotonic_ns() if tracing else 0
        # ONE vectorized sampling hash for the whole pass (the drain-
        # path discipline): a scalar per-command hash here measured
        # ~18x slower per 512-command batch, paid on the protocol
        # thread for every write regardless of sample rate
        smask = sink.sampled(cmds) if tracing else None
        for i in np.nonzero(cids >= 0)[0]:
            key = (int(cids[i]), int(cmds[i]))
            want = self._pending.pop(key, None)
            if want is None:
                continue  # not proposed on this conn (or already replied)
            if tracing and want != MsgKind.READ_REPLY and smask[i]:
                # writes only: reads never get DRAIN/COMMIT spans, so
                # an exec/reply stamp for them could never complete a
                # chain — it would just churn the fixed rings
                traced.append(key[1])
            book = reads if want == MsgKind.READ_REPLY else writes
            cs_, vs_ = book.setdefault(key[0], ([], []))
            cs_.append(key[1])
            vs_.append(int(vals[i]))
        ts = monotonic_ns()
        for conn, (cs_, vs_) in writes.items():
            frame = make_batch(MsgKind.PROPOSE_REPLY, ok=1,
                               cmd_id=np.asarray(cs_, np.int32),
                               val=np.asarray(vs_, np.int64),
                               timestamp=ts, leader=np.int8(self.me))
            self.transport.send_client(conn, MsgKind.PROPOSE_REPLY, frame)
        for conn, (cs_, vs_) in reads.items():
            frame = make_batch(MsgKind.READ_REPLY,
                               cmd_id=np.asarray(cs_, np.int32),
                               val=np.asarray(vs_, np.int64))
            self.transport.send_client(conn, MsgKind.READ_REPLY, frame)
        if traced:
            # one exec stamp (when the reply pass picked the command
            # up — commit -> here is the exec-backlog wait; aux = the
            # dispatch count, closing the drain-aux round correlation)
            # and one reply-serialization span per sampled command.
            # The span ends at ``ts`` — taken BEFORE the send loop: a
            # same-host client can receive a frame before this code
            # runs again, and a reply_ser end stamped after the sends
            # would put reply_recv BEFORE it (negative transport_out,
            # chain dropped as impossible under exactly the load the
            # tail table exists to explain).
            ring = sink.ring()
            disp = self._c_dispatches.value
            for cmd in traced:
                tid = trace_id_for(cmd)
                ring.record(tid, ST_EXEC, t_x0, t_x0, disp)
                ring.record(tid, ST_REPLY_SER, t_x0, ts, cmd)

    # -- beyond-window catch-up from the durable log --

    def _host_catchup(self, pc: np.ndarray | None, snap: dict) -> None:
        """A peer lagging behind window_base can't be healed by device
        catch-up rows (they slid out); serve it from the stable store's
        in-memory mirror instead — the runtime's replacement for the
        reference replaying its whole file to the new process.

        ``pc``/``snap`` are the tick's OWN peer-commit vector and
        published snapshot, captured at its readback: under the
        pipeline this runs after the next step was enqueued, when
        ``self.state``'s buffers are already donated — a live read
        here would block on (and read) the wrong tick."""
        if self.protocol == "mencius" or pc is None:
            # leaderless: there is no leader to push catch-up. Healing
            # is PULL-based instead — the laggard's takeover sweep
            # (kernel) plus peers' store-served COMMIT answers to
            # beyond-window PREPARE_INSTs (_mencius_store_answer).
            return
        if not snap["prepared"] or snap["leader"] != self.me:
            return
        base = snap["window_base"]
        fr = snap["frontier"]
        sb = self.store.base
        for q in range(self.cfg.n_replicas):
            if q == self.me or pc[q] + 1 >= base:
                continue
            if sb >= 0 and pc[q] < sb:
                # the peer needs slots BELOW our truncation frontier —
                # those redo records no longer exist anywhere on this
                # replica's disk. Ship the retained snapshot instead
                # (SNAP_META + SNAP_ROWS, paced); the live suffix
                # above it follows through this same path once the
                # peer's reported frontier clears the snapshot.
                self._send_snapshot(q)
                continue
            frame = self._store_commit_frame(
                int(pc[q]) + 1, min(int(pc[q]) + 256, base - 1), fr)
            if frame is not None:
                self._send_or_redial(q, MsgKind.COMMIT, frame)

    # minimum seconds between snapshot re-pushes to one peer: a
    # transfer already in flight must not be re-sent every tick, and a
    # peer that installed it advances its reported frontier well
    # before this expires
    _SNAP_RESEND_S = 2.0

    def _send_snapshot(self, q: int) -> None:
        """Push the newest retained snapshot to peer q: one SNAP_META
        announcement, then its live pairs as SNAP_ROWS frames. Every
        row repeats the snapshot frontier, so the receiver can never
        splice two transfers; completeness is count-checked before
        install (_snap_rx_install)."""
        now = time.monotonic()
        if now - self._snap_sent_s.get(q, -1e9) < self._SNAP_RESEND_S:
            return
        fr = self.store.snap_frontier
        pairs = self.store.snapshot_pairs
        if fr < 0:
            return
        self._snap_sent_s[q] = now
        self._snap_seq += 1
        meta = make_batch(MsgKind.SNAP_META, leader_id=self.me,
                          frontier=fr, count=len(pairs),
                          seq=self._snap_seq)
        self._send_or_redial(q, MsgKind.SNAP_META, meta)
        for lo in range(0, len(pairs), 4096):
            ch = pairs[lo:lo + 4096]
            rows = make_batch(MsgKind.SNAP_ROWS, frontier=fr,
                              key=np.ascontiguousarray(ch["key"]),
                              val=np.ascontiguousarray(ch["val"]))
            self._send_or_redial(q, MsgKind.SNAP_ROWS, rows)
        dlog(f"replica {self.me}: pushed snapshot@{fr} "
             f"({len(pairs)} pairs) to replica {q}")

    def _snap_rx_install(self) -> None:
        """Install a COMPLETE received snapshot that is ahead of our
        own executed frontier (protocol thread, called from _drain).
        Install = the KV pairs into the device table + every cursor to
        frontier+1 (_install_snapshot_pairs), then the snapshot into
        OUR OWN stable store — a restart of this replica must replay
        from it, not from slot 0 of a log it never held."""
        for fr in sorted(self._snap_rx):
            st = self._snap_rx[fr]
            if sum(len(r) for r in st["rows"]) < st["count"]:
                continue
            del self._snap_rx[fr]
            if fr <= int(self.snapshot.get("executed", -1)):
                continue  # stale by the time it completed
            t0 = time.perf_counter()
            self._flush_inflight()
            pairs = (np.concatenate(st["rows"]) if st["rows"]
                     else empty_batch(MsgKind.SNAP_ROWS))
            self._install_snapshot_pairs(pairs, fr)
            self.store.take_snapshot(
                np.ascontiguousarray(pairs["key"]),
                np.ascontiguousarray(pairs["val"]), fr,
                wall_ns=time.time_ns())
            # publish before the next dispatch: fuse/narrow/idle
            # decisions and the catch-up sender must see the new
            # frontier, exactly as a readback would publish it
            self.snapshot = dict(
                self.snapshot, frontier=fr, executed=fr,
                window_base=fr + 1,
                crt_inst=max(int(self.snapshot.get("crt_inst", 0)),
                             fr + 1),
                work_pending=True)
            self.journal.record(
                EV_RECOVERY, subject=self.me, value=fr,
                aux=int((time.perf_counter() - t0) * 1e3))
            dlog(f"replica {self.me}: installed snapshot@{fr} "
                 f"({len(pairs)} pairs) from replica {st['src']}")
        # drop buffers that can no longer install (at/below our own
        # frontier): a dead transfer must not pin its rows forever
        done = int(self.snapshot.get("executed", -1))
        for fr in [f for f in self._snap_rx if f <= done]:
            del self._snap_rx[fr]
