"""Unit tests for the shared ack-run compression kernels
(ops/ackruns.py): emission/consumption must stay in lockstep, for both
the MinPaxos consecutive-slot stride and Mencius's owner stride R."""

import jax.numpy as jnp
import numpy as np
import pytest

from minpaxos_tpu.ops.ackruns import compress_ack_runs, range_vote_coverage


def _naive_coverage(valid, src, inst, count, wb, window, r, stride):
    cov = np.zeros((window, r), bool)
    for v, sr, i0, c in zip(valid, src, inst, count):
        if not v:
            continue
        for j in range(max(int(c), 1)):
            rel = i0 + j * stride - wb
            if 0 <= rel < window:
                cov[rel, sr] = True
    return cov


@pytest.mark.parametrize("stride", [1, 3, 5])
def test_compress_runs_form_at_protocol_stride(stride):
    # one sender acks 6 slots spaced `stride` apart: ONE run of 6
    m = 8
    is_acc = jnp.asarray([True] * 6 + [False] * 2)
    src = jnp.full(m, 1, jnp.int32)
    inst = jnp.asarray([10 + stride * i for i in range(6)] + [0, 0],
                       jnp.int32)
    ok = jnp.asarray([True] * 6 + [False] * 2)
    start, length = compress_ack_runs(is_acc, src, inst, ok,
                                      stride=stride)
    assert np.asarray(start)[:6].tolist() == [True] + [False] * 5
    assert int(np.asarray(length)[0]) == 6


def test_compress_breaks_on_wrong_stride():
    # consecutive insts under stride 3 never form runs
    is_acc = jnp.ones(4, bool)
    src = jnp.zeros(4, jnp.int32)
    inst = jnp.asarray([7, 8, 9, 10], jnp.int32)
    ok = jnp.ones(4, bool)
    start, length = compress_ack_runs(is_acc, src, inst, ok, stride=3)
    assert np.asarray(start).all()
    assert np.asarray(length).tolist() == [1, 1, 1, 1]


def test_compress_breaks_on_sender_ok_ballot():
    is_acc = jnp.ones(6, bool)
    src = jnp.asarray([0, 0, 1, 1, 1, 1], jnp.int32)
    inst = jnp.asarray([0, 3, 6, 9, 12, 15], jnp.int32)
    ok = jnp.asarray([True, True, True, True, False, False])
    bal = jnp.asarray([5, 5, 5, 5, 5, 6], jnp.int32)
    start, length = compress_ack_runs(is_acc, src, inst, ok,
                                      ballot=bal, stride=3)
    # runs: [0,3] by 0; [6,9] by 1 ok; [12] nack bal5; [15] nack bal6
    assert np.asarray(start).tolist() == [True, False, True, False,
                                          True, True]
    assert np.asarray(length).tolist() == [2, 2, 2, 2, 1, 1]


@pytest.mark.parametrize("stride", [1, 3])
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_range_coverage_matches_naive(stride, seed):
    rng = np.random.default_rng(seed)
    window, r, m = 64, 3, 40
    wb = int(rng.integers(0, 1000))
    valid = rng.random(m) < 0.8
    src = rng.integers(0, r, m)
    # starts straddling both window edges, ranges of varied length
    inst = wb + rng.integers(-30, window + 10, m)
    count = rng.integers(0, 12, m)  # 0 = pre-compression padding row
    got = np.asarray(range_vote_coverage(
        jnp.asarray(valid), jnp.asarray(src, jnp.int32),
        jnp.asarray(inst, jnp.int32), jnp.asarray(count, jnp.int32),
        jnp.int32(wb), window, r, stride=stride))
    want = _naive_coverage(valid, src, inst, count, wb, window, r,
                           stride)
    np.testing.assert_array_equal(got, want)


def test_emit_consume_lockstep_stride_r():
    """End-to-end: rows an owner would ack (its foreign-owner accepts,
    stride R) compress to one row whose (inst, count) reproduces the
    original coverage exactly at the driving owner."""
    r, window, wb = 3, 32, 99
    # owner 1's accepts for its slots 100, 103, ..., 118 (7 slots)
    insts = np.array([100 + 3 * i for i in range(7)], np.int32)
    m = len(insts)
    start, length = compress_ack_runs(
        jnp.ones(m, bool), jnp.full(m, 2, jnp.int32),
        jnp.asarray(insts), jnp.ones(m, bool), stride=3)
    # emitter publishes (inst, count) on start rows only
    valid = np.asarray(start)
    count = np.asarray(length)
    cov = np.asarray(range_vote_coverage(
        jnp.asarray(valid), jnp.full(m, 2, jnp.int32),
        jnp.asarray(insts), jnp.asarray(count, jnp.int32),
        jnp.int32(wb), window, r, stride=3))
    want = _naive_coverage(np.ones(m, bool), np.full(m, 2),
                           insts, np.ones(m, np.int32), wb, window, r,
                           stride=3)
    np.testing.assert_array_equal(cov, want)
