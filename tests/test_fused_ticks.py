"""Fused multi-tick dispatches, the idle fast path, and the narrow
resident view (runtime/replica.py + ops/substeps.py).

The fused path's claim is exactness, not approximation: k substeps
inside one ``lax.scan`` dispatch must produce the same commits,
replies and outbox rows as k sequential dispatches fed the same
trace — with the one DELIBERATE difference that wall-tick counters
(tick / stall_ticks) advance once per dispatch, not once per substep
(tick_inc). These tests pin both halves of that contract, for both
protocol kernels, against a realistic recorded exchange (propose ->
accept -> ack -> commit), plus the narrow view's
full-state-equivalence and the idle fast path's no-dispatch guarantee
on a live server.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minpaxos_tpu.models.mencius import init_mencius, mencius_step_impl
from minpaxos_tpu.models.minpaxos import (
    MinPaxosConfig,
    MsgBatch,
    become_leader,
    init_replica,
    replica_step_impl,
)
from minpaxos_tpu.ops.substeps import (
    SCAL_EXEC_COUNT,
    SCAL_FRONTIER,
    SCAL_WINDOW_BASE,
    pack_outputs,
)
from minpaxos_tpu.runtime.replica import _packed_step
from minpaxos_tpu.wire.messages import MsgKind, Op

CFG = MinPaxosConfig(n_replicas=3, window=128, inbox=32, exec_batch=16,
                     kv_pow2=8, catchup_rows=8, recovery_rows=8,
                     gossip_ticks=1)


def _mk(cols) -> MsgBatch:
    return MsgBatch(**{c: jnp.asarray(cols[c]) for c in MsgBatch._fields})


def _empty_cols(m: int):
    return {c: np.zeros(m, np.int32) for c in MsgBatch._fields}


def _copy(st):
    return jax.tree_util.tree_map(lambda x: x.copy(), st)


def _propose_cols(cfg, n: int, base_cmd: int = 0):
    cols = _empty_cols(cfg.inbox)
    cols["kind"][:n] = int(MsgKind.PROPOSE)
    cols["src"][:n] = -1
    cols["op"][:n] = int(Op.PUT)
    cols["key_lo"][:n] = 100 + np.arange(n)
    cols["val_lo"][:n] = 500 + np.arange(n)
    cols["cmd_id"][:n] = base_cmd + np.arange(n)
    cols["client_id"][:n] = 7
    return cols


def _rows_of_kind(outbox, kind: MsgKind, m: int):
    """Extract one kind's live rows from a kernel outbox into inbox
    columns — the array analogue of the wire round trip."""
    msgs, k = outbox.msgs, int(kind)
    mask = np.asarray(msgs.kind) == k
    cols = _empty_cols(m)
    n = int(mask.sum())
    assert n <= m
    for c in MsgBatch._fields:
        cols[c][:n] = np.asarray(getattr(msgs, c))[mask]
    return cols, n


def _prepared_leader(cfg, init_fn=init_replica, step=replica_step_impl):
    st = init_fn(cfg, 0)
    st, prep = become_leader(cfg, st)
    cols = _empty_cols(cfg.inbox)
    for i, src in enumerate(range(1, cfg.n_replicas)):
        cols["kind"][i] = int(MsgKind.PREPARE_REPLY)
        cols["src"][i] = src
        cols["ballot"][i] = int(prep.ballot[0])
        cols["op"][i] = 1  # ok
        cols["last_committed"][i] = -1
    st, _, _ = step(cfg, st, _mk(cols))
    assert bool(st.prepared)
    return _copy(st)


def _leader_trace(cfg):
    """A recorded minpaxos exchange: the leader's inboxes for (1) a
    propose batch, (2) the follower acks those accepts generated."""
    lead = _prepared_leader(cfg)
    fol = _copy(init_replica(cfg, 1))
    b_prop = _propose_cols(cfg, 4)
    lead2, out, _ = replica_step_impl(cfg, _copy(lead), _mk(b_prop))
    acc, n_acc = _rows_of_kind(out, MsgKind.ACCEPT, cfg.inbox)
    assert n_acc >= 4
    _, fol_out, _ = replica_step_impl(cfg, fol, _mk(acc))
    acks, n_ack = _rows_of_kind(fol_out, MsgKind.ACCEPT_REPLY, cfg.inbox)
    assert n_ack >= 1
    return lead, [b_prop, acks]


def _mencius_trace(cfg):
    """Same shape of exchange for the mencius kernel (owner 0 drives
    its slots; replica 1 acks)."""
    own = _copy(init_mencius(cfg, 0))
    fol = _copy(init_mencius(cfg, 1))
    b_prop = _propose_cols(cfg, 4)
    _, out, _ = mencius_step_impl(cfg, _copy(own), _mk(b_prop))
    acc, n_acc = _rows_of_kind(out, MsgKind.ACCEPT, cfg.inbox)
    assert n_acc >= 4
    _, fol_out, _ = mencius_step_impl(cfg, fol, _mk(acc))
    acks, n_ack = _rows_of_kind(fol_out, MsgKind.ACCEPT_REPLY, cfg.inbox)
    assert n_ack >= 1
    return own, [b_prop, acks]


def _seq_substeps(cfg, st, inbox, step_impl, k):
    """Reference semantics: k sequential steps, real inbox first, the
    rest empty, tick credited once — exactly what the fused scan
    claims to compute."""
    outs = []
    empty = jax.tree_util.tree_map(jnp.zeros_like, inbox)
    for i in range(k):
        st, ob, ex = step_impl(cfg, st, inbox if i == 0 else empty,
                               1 if i == 0 else 0)
        outs.append(pack_outputs(st, ob, ex))
    return st, outs


def _assert_trees_equal(a, b, context: str):
    for pa, pb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        assert np.array_equal(np.asarray(pa), np.asarray(pb)), context


@pytest.mark.parametrize("proto,trace_fn,step_impl,min_frontier", [
    ("minpaxos", _leader_trace, replica_step_impl, 3),
    # mencius: only owner 0's slots (0, 3, 6, 9) commit here; the
    # GLOBAL blocking frontier stops at slot 0 until the other owners
    # cede their interleaved slots, which this two-party trace never
    # triggers — slot-status commitment is asserted instead
    ("mencius", _mencius_trace, mencius_step_impl, 0),
])
def test_fused_equals_sequential(proto, trace_fn, step_impl, min_frontier):
    """k fused substeps == k sequential substeps, EXACTLY (state and
    every packed output), along a recorded propose/ack trace."""
    st, trace = trace_fn(CFG)
    st_f, st_s = _copy(st), _copy(st)
    for batch in trace:
        inbox = _mk(batch)
        st_f, om, em, sc = _packed_step(CFG, st_f, inbox, step_impl, 3)
        st_s, outs = _seq_substeps(CFG, st_s, inbox, step_impl, 3)
        for i, (o, e, s) in enumerate(outs):
            assert np.array_equal(np.asarray(om)[i], np.asarray(o)), (
                proto, i)
            assert np.array_equal(np.asarray(em)[i], np.asarray(e)), (
                proto, i)
            assert np.array_equal(np.asarray(sc)[i], np.asarray(s)), (
                proto, i)
        _assert_trees_equal(st_f, st_s, proto)
    # the trace ends with the driver holding commits: the fused run
    # must have actually committed and executed (not just matched a
    # do-nothing reference)
    assert int(st_f.committed_upto) >= min_frontier
    assert int(st_f.executed_upto) >= min_frontier
    assert int((np.asarray(st_f.status) >= 4).sum()) >= 4  # COMMITTED+


@pytest.mark.parametrize("proto,trace_fn,step_impl,min_execs", [
    ("minpaxos", _leader_trace, replica_step_impl, 4),
    ("mencius", _mencius_trace, mencius_step_impl, 1),
])
def test_fused_commits_match_unfused_ticks(proto, trace_fn, step_impl,
                                           min_execs):
    """The wall-honest form of equivalence: the same trace driven as
    plain k=1 dispatches (each a full wall tick) reaches the same
    commits and produces the same executed commands — tick counters
    are the ONLY intended difference."""
    st, trace = trace_fn(CFG)
    t0 = int(st.tick)
    st_f, st_u = _copy(st), _copy(st)
    exec_f, exec_u = [], []

    def run(st, fused: bool, sink):
        for batch in trace:
            k = 3 if fused else 1
            st, om, em, sc = _packed_step(CFG, st, _mk(batch),
                                          step_impl, k)
            sc = np.asarray(sc)
            for i in range(k):
                n = int(sc[i][SCAL_EXEC_COUNT])
                sink.extend(np.asarray(em)[i][4][:n].tolist())  # cmd_id
            if not fused:  # give the unfused run its follow-up ticks
                for _ in range(2):
                    st, om, em, sc2 = _packed_step(
                        CFG, st, _mk(_empty_cols(CFG.inbox)), step_impl, 1)
                    n = int(np.asarray(sc2)[0][SCAL_EXEC_COUNT])
                    sink.extend(np.asarray(em)[0][4][:n].tolist())
        return st

    st_f = run(st_f, True, exec_f)
    st_u = run(st_u, False, exec_u)
    assert int(st_f.committed_upto) == int(st_u.committed_upto)
    assert exec_f == exec_u and len(exec_f) >= min_execs
    # counters: fused credited 1 tick per dispatch, unfused 3
    assert int(st_f.tick) - t0 == len(trace)
    assert int(st_u.tick) - t0 == 3 * len(trace)


def test_tick_inc_zero_freezes_stall_counter():
    """A trailing fused substep (tick_inc=0) must not age the stall
    counter — the retry/no-op-fill thresholds are wall-time contracts
    (PERF.md round-5: a threshold reached early rebroadcasts accepts
    that are merely in flight)."""
    lead = _prepared_leader(CFG)
    # one in-flight proposal, never acked -> stalling
    st, _, _ = replica_step_impl(CFG, _copy(lead), _mk(_propose_cols(CFG, 1)))
    empty = _mk(_empty_cols(CFG.inbox))
    s0 = int(st.stall_ticks)
    st, _, _ = replica_step_impl(CFG, st, empty, 0)
    st, _, _ = replica_step_impl(CFG, st, empty, 0)
    assert int(st.stall_ticks) == s0
    st, _, _ = replica_step_impl(CFG, st, empty, 1)
    assert int(st.stall_ticks) == s0 + 1


def _committed_leader(cfg):
    """A leader with a few committed+executed slots and peers reported
    up to date — the state shape the narrow view targets."""
    lead = _prepared_leader(cfg)
    lead, out, _ = replica_step_impl(cfg, lead, _mk(_propose_cols(cfg, 4)))
    acc, _ = _rows_of_kind(out, MsgKind.ACCEPT, cfg.inbox)
    fol = _copy(init_replica(cfg, 1))
    _, fol_out, _ = replica_step_impl(cfg, fol, _mk(acc))
    acks, _ = _rows_of_kind(fol_out, MsgKind.ACCEPT_REPLY, cfg.inbox)
    lead, _, _ = replica_step_impl(cfg, lead, _mk(acks))
    assert int(lead.committed_upto) >= 3
    fr = int(lead.committed_upto)
    return _copy(lead._replace(
        peer_commits=jnp.full(cfg.n_replicas, fr, jnp.int32)))


def test_narrow_view_matches_full_step():
    """The small-window specialized step is exact when the live span
    fits the view: full-window step vs narrow view at both a zero and
    a mid-window offset, state and outputs compared leaf-for-leaf."""
    cfg = CFG._replace(window=256)
    lead = _committed_leader(cfg)
    exec_edge = int(lead.executed_upto) + 1
    assert exec_edge >= 4
    follow_up = _propose_cols(cfg, 3, base_cmd=50)
    for off in (0, exec_edge):
        full_st, fo, fe, fs = _packed_step(
            cfg, _copy(lead), _mk(follow_up), replica_step_impl, 1, 0, 0)
        nar_st, no, ne, ns = _packed_step(
            cfg, _copy(lead), _mk(follow_up), replica_step_impl, 1, 64,
            jnp.int32(off))
        _assert_trees_equal(full_st, nar_st, f"state off={off}")
        assert np.array_equal(np.asarray(fo), np.asarray(no)), off
        assert np.array_equal(np.asarray(fe), np.asarray(ne)), off
        assert np.array_equal(np.asarray(fs), np.asarray(ns)), off
        assert int(np.asarray(ns)[0][SCAL_WINDOW_BASE]) == 0
        # the step did real work: new proposals accepted
        assert int(nar_st.crt_inst) == int(lead.crt_inst) + 3


def test_narrow_view_fused_commits():
    """narrow x fused compose: a k=2 burst inside a 64-slot view
    commits + executes the backlog exactly like the full-window run."""
    cfg = CFG._replace(window=256, exec_batch=2)
    lead = _committed_leader(cfg)
    # exec_batch=2 but 4+ commits: the backlog needs multiple substeps
    lead = lead._replace(executed_upto=jnp.int32(-1),
                         status=jnp.where(lead.status > 0,
                                          jnp.uint8(4), lead.status))
    empty = _mk(_empty_cols(cfg.inbox))
    full_st, _, _, fs = _packed_step(
        cfg, _copy(lead), empty, replica_step_impl, 2, 0, 0)
    nar_st, _, _, ns = _packed_step(
        cfg, _copy(lead), empty, replica_step_impl, 2, 64, jnp.int32(0))
    _assert_trees_equal(full_st, nar_st, "fused narrow")
    assert int(np.asarray(ns)[-1][SCAL_FRONTIER]) == int(
        full_st.committed_upto)
    assert int(full_st.executed_upto) >= 3  # two substeps x batch 2


def test_idle_fastpath_skips_device_dispatch():
    """A quiet prepared replica must answer idle polls WITHOUT device
    dispatches (stats['dispatches'] frozen, stats['idle_skips']
    counting) until a message arrives — the round-6 idle fast path."""
    from minpaxos_tpu.runtime.replica import ReplicaServer, RuntimeFlags
    from minpaxos_tpu.utils.netutil import CONTROL_OFFSET, free_ports
    from minpaxos_tpu.wire.messages import make_batch

    port = free_ports(1, sibling_offset=CONTROL_OFFSET)[0]
    cfg = MinPaxosConfig(n_replicas=1, window=64, inbox=16, exec_batch=8,
                         kv_pow2=6, catchup_rows=4, recovery_rows=4)
    flags = RuntimeFlags(idle_skip_max_s=30.0, idle_s=0.01,
                         store_dir="/tmp")
    srv = ReplicaServer(0, [("127.0.0.1", port)], cfg, flags)
    srv.start()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if (srv.snapshot["prepared"]
                    and not srv.snapshot.get("work_pending", True)):
                break
            time.sleep(0.05)
        assert srv.snapshot["prepared"], srv.snapshot
        assert not srv.snapshot["work_pending"], srv.snapshot
        before = dict(srv.stats)
        time.sleep(0.5)  # ~50 idle polls at idle_s=0.01
        after = dict(srv.stats)
        assert after["dispatches"] == before["dispatches"], (before, after)
        assert after["idle_skips"] > before["idle_skips"] + 5
        # a message still forces a dispatch immediately
        rows = make_batch(MsgKind.PROPOSE, cmd_id=np.asarray([1]),
                          op=int(Op.PUT), key=np.asarray([11]),
                          val=np.asarray([22]), timestamp=0)
        from minpaxos_tpu.runtime.transport import FROM_CLIENT
        srv.queue.put((FROM_CLIENT, 999, MsgKind.PROPOSE, rows))
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if srv.stats["dispatches"] > after["dispatches"]:
                break
            time.sleep(0.05)
        assert srv.stats["dispatches"] > after["dispatches"]
        # and the command committed (single-replica majority = 1)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if srv.snapshot["frontier"] >= 0:
                break
            time.sleep(0.05)
        assert srv.snapshot["frontier"] >= 0
    finally:
        srv.stop()


def test_kv_sizing_startup_line_and_saturation_warning(tmp_path, capsys):
    """-kvpow2 footgun mitigation: the startup line states capacity vs
    the workload hint, and the periodic load check warns before the
    fail-stop can trigger."""
    from minpaxos_tpu.runtime.replica import ReplicaServer, RuntimeFlags
    from minpaxos_tpu.utils.netutil import CONTROL_OFFSET, free_ports

    port = free_ports(1, sibling_offset=CONTROL_OFFSET)[0]
    cfg = MinPaxosConfig(n_replicas=1, window=64, inbox=16, exec_batch=8,
                         kv_pow2=6, catchup_rows=4, recovery_rows=4)
    flags = RuntimeFlags(store_dir=str(tmp_path), key_hint=60)
    srv = ReplicaServer(0, [("127.0.0.1", port)], cfg, flags)
    srv._log_kv_sizing()
    err = capsys.readouterr().err
    assert "KV table capacity 64" in err
    assert "projected load 0.94" in err and "OVER" in err
    # saturation warning: force a near-full table + a check-due tick
    # (stats is a snapshot property now — set the live counter)
    srv.metrics.counter("dispatches").value = 1024
    srv.state = srv.state._replace(
        kv=srv.state.kv._replace(slot=jnp.ones_like(srv.state.kv.slot)))
    srv._check_kv_load()
    err = capsys.readouterr().err
    assert "NEAR SATURATION" in err
    assert srv._kv_warned
    srv.store.close()
