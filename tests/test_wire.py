"""Codec round-trip tests (reference *marsh.go equivalents)."""

import numpy as np
import pytest

from minpaxos_tpu.wire import (
    MsgKind,
    StreamDecoder,
    decode_frame,
    empty_batch,
    encode_frame,
    make_batch,
)
from minpaxos_tpu.wire.messages import SCHEMAS, Op


@pytest.mark.parametrize("kind", list(SCHEMAS))
def test_roundtrip_random(kind):
    rng = np.random.default_rng(int(kind))
    rows = empty_batch(kind, 17)
    for name, (dt, _) in rows.dtype.fields.items():
        info = np.iinfo(dt)
        rows[name] = rng.integers(info.min, info.max, size=17, dtype=dt)
    wire = encode_frame(kind, rows)
    k2, rows2, used = decode_frame(wire)
    assert k2 == kind and used == len(wire)
    assert (rows2 == rows).all()


def test_roundtrip_empty():
    wire = encode_frame(MsgKind.COMMIT_SHORT, empty_batch(MsgKind.COMMIT_SHORT, 0))
    k, rows, used = decode_frame(wire)
    assert k == MsgKind.COMMIT_SHORT and len(rows) == 0 and used == len(wire)


def test_make_batch_broadcast():
    b = make_batch(
        MsgKind.ACCEPT,
        inst=np.arange(8, dtype=np.int32),
        ballot=3,
        op=Op.PUT,
        key=np.arange(8),
        val=7,
        cmd_id=0,
        client_id=1,
        leader_id=0,
        last_committed=-1,
    )
    assert len(b) == 8
    assert (b["ballot"] == 3).all()
    assert (b["inst"] == np.arange(8)).all()


def test_stream_decoder_fragmentation():
    frames = [
        (MsgKind.PREPARE, make_batch(MsgKind.PREPARE, leader_id=0, ballot=16, last_committed=-1)),
        (MsgKind.ACCEPT, make_batch(
            MsgKind.ACCEPT, inst=np.arange(100, dtype=np.int32), ballot=16,
            op=Op.PUT, key=np.arange(100), val=np.arange(100) * 2,
            cmd_id=np.arange(100), client_id=5, leader_id=0, last_committed=-1)),
        (MsgKind.ACCEPT_REPLY, make_batch(
            MsgKind.ACCEPT_REPLY, id=1, ok=1, inst=0, count=100, ballot=16,
            last_committed=-1)),
    ]
    wire = b"".join(encode_frame(k, r) for k, r in frames)
    # feed in awkward chunk sizes
    dec = StreamDecoder()
    got = []
    for i in range(0, len(wire), 7):
        got.extend(dec.feed(wire[i : i + 7]))
    assert dec.pending_bytes() == 0
    assert len(got) == len(frames)
    for (k1, r1), (k2, r2) in zip(frames, got):
        assert k1 == k2 and (r1 == r2).all()


def test_decoder_rejects_bad_opcode():
    with pytest.raises(ValueError):
        decode_frame(bytes([255, 1, 0, 0, 0]) + b"x" * 64)


def test_handshake_kinds_have_no_schema_but_latch_cleanly():
    import struct

    good = encode_frame(MsgKind.READ, make_batch(MsgKind.READ, cmd_id=1, key=2))
    dec = StreamDecoder()
    out = dec.feed(good + struct.pack("<BI", int(MsgKind.HANDSHAKE_CLIENT), 0) + good)
    assert len(out) == 1 and dec.error is not None


def test_encode_frame_rejects_oversized_batch():
    from minpaxos_tpu.wire.codec import MAX_FRAME_ROWS

    rows = np.zeros(MAX_FRAME_ROWS + 1, dtype=np.dtype([("cmd_id", "<i4"), ("key", "<i8")]))
    with pytest.raises(ValueError):
        encode_frame(MsgKind.READ, rows)


def test_stream_decoder_corruption_latches():
    good = encode_frame(MsgKind.READ, make_batch(MsgKind.READ, cmd_id=1, key=2))
    dec = StreamDecoder()
    out = dec.feed(good + bytes([200, 1, 0, 0, 0]) + good)
    # frames before the corruption are preserved, error is latched
    assert len(out) == 1 and out[0][0] == MsgKind.READ
    assert dec.error is not None
    with pytest.raises(ValueError):
        dec.feed(b"")

