"""KV engine vs a sequential dict oracle (reference state.Execute
semantics, state/state.go:86-103)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minpaxos_tpu.ops.kvstore import KVState, kv_apply_batch, kv_init, kv_lookup
from minpaxos_tpu.ops.packed import join_i64, split_i64
from minpaxos_tpu.wire.messages import Op


def _apply_np(kv, ops, keys, vals, valid=None):
    ops = np.asarray(ops, dtype=np.int32)
    k_hi, k_lo = split_i64(np.asarray(keys))
    v_hi, v_lo = split_i64(np.asarray(vals))
    if valid is None:
        valid = np.ones(len(ops), dtype=bool)
    kv, o_hi, o_lo, found = jax.jit(kv_apply_batch)(
        kv, jnp.asarray(ops), jnp.asarray(k_hi), jnp.asarray(k_lo),
        jnp.asarray(v_hi), jnp.asarray(v_lo), jnp.asarray(valid))
    return kv, join_i64(np.asarray(o_hi), np.asarray(o_lo)), np.asarray(found)


class DictOracle:
    def __init__(self):
        self.d = {}

    def apply(self, ops, keys, vals, valid=None):
        outs, founds = [], []
        if valid is None:
            valid = [True] * len(ops)
        for op, k, v, ok in zip(ops, keys, vals, valid):
            if not ok:
                outs.append(0); founds.append(False); continue
            if op == Op.PUT:
                self.d[k] = v; outs.append(v); founds.append(True)
            elif op == Op.GET:
                outs.append(self.d.get(k, 0)); founds.append(k in self.d)
            elif op == Op.DELETE:
                self.d.pop(k, None); outs.append(0); founds.append(False)
            else:
                outs.append(0); founds.append(False)
        return np.array(outs, dtype=np.int64), np.array(founds)


def test_put_then_get_same_batch():
    kv = kv_init(8)
    ops = [Op.PUT, Op.GET, Op.PUT, Op.GET, Op.GET]
    keys = [7, 7, 7, 7, 99]
    vals = [10, 0, 20, 0, 0]
    kv, out, found = _apply_np(kv, ops, keys, vals)
    assert out.tolist() == [10, 10, 20, 20, 0]
    assert found.tolist() == [True, True, True, True, False]


def test_cross_batch_persistence():
    kv = kv_init(8)
    kv, _, _ = _apply_np(kv, [Op.PUT], [5], [55])
    kv, out, found = _apply_np(kv, [Op.GET], [5], [0])
    assert out[0] == 55 and found[0]


def test_delete_semantics():
    kv = kv_init(8)
    kv, _, _ = _apply_np(kv, [Op.PUT, Op.DELETE, Op.GET], [1, 1, 1], [9, 0, 0])
    kv, out, found = _apply_np(kv, [Op.GET], [1], [0])
    assert not found[0] and out[0] == 0


def test_64bit_keys_and_values():
    kv = kv_init(8)
    k = 0x1234_5678_9ABC_DEF0 - 2**63  # negative i64
    v = 2**62 + 12345
    kv, out, found = _apply_np(kv, [Op.PUT, Op.GET], [k, k], [v, 0])
    assert out[1] == v and found[1]


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_random_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    kv = kv_init(12)  # 4096 slots
    oracle = DictOracle()
    for _ in range(5):
        b = int(rng.integers(1, 300))
        ops = rng.choice([Op.PUT, Op.GET, Op.DELETE], size=b, p=[0.5, 0.4, 0.1])
        keys = rng.integers(-50, 50, size=b).astype(np.int64)
        vals = rng.integers(-(2**60), 2**60, size=b).astype(np.int64)
        valid = rng.random(b) < 0.9
        kv, out, found = _apply_np(kv, ops, keys, vals, valid)
        want_out, want_found = oracle.apply(ops, keys, vals, valid)
        np.testing.assert_array_equal(out, want_out)
        np.testing.assert_array_equal(found, want_found)
        assert int(np.asarray(kv.dropped)) == 0
    # final table state agrees with the oracle
    ks = np.array(sorted(oracle.d), dtype=np.int64)
    if len(ks):
        k_hi, k_lo = split_i64(ks)
        f, v_hi, v_lo = jax.jit(kv_lookup)(kv, jnp.asarray(k_hi), jnp.asarray(k_lo))
        assert np.asarray(f).all()
        np.testing.assert_array_equal(
            join_i64(np.asarray(v_hi), np.asarray(v_lo)),
            np.array([oracle.d[k] for k in ks]))


@pytest.mark.parametrize("lanes", [4, 256])
def test_wide_value_lanes_vs_oracle(lanes):
    """The engine is generic over the value-lane axis: lanes=256 is the
    reference's 1KB build variant (state.go.1k:15, Value [128]int64 =
    256 i32 lanes). Same sequential semantics, oracle-checked on whole
    lane vectors including in-batch PUT->GET forwarding."""
    from minpaxos_tpu.ops.kvstore import kv_apply_batch_lanes, kv_lookup_lanes

    rng = np.random.default_rng(99)
    kv = kv_init(6, val_lanes=lanes)  # 64 slots
    oracle = {}
    for _ in range(3):
        b = 40
        ops = rng.choice([Op.PUT, Op.GET, Op.DELETE], size=b,
                         p=[0.5, 0.4, 0.1]).astype(np.int32)
        keys = rng.integers(0, 20, size=b).astype(np.int64)
        k_hi, k_lo = split_i64(keys)
        vals = rng.integers(-(2**31), 2**31, size=(b, lanes)).astype(np.int32)
        want_out = np.zeros((b, lanes), np.int32)
        want_found = np.zeros(b, bool)
        for i, (op, k) in enumerate(zip(ops, keys)):
            if op == Op.PUT:
                oracle[k] = vals[i].copy()
                want_out[i], want_found[i] = vals[i], True
            elif op == Op.GET:
                if k in oracle:
                    want_out[i], want_found[i] = oracle[k], True
            elif op == Op.DELETE:
                oracle.pop(k, None)
        kv, out, found = jax.jit(kv_apply_batch_lanes)(
            kv, jnp.asarray(ops), jnp.asarray(k_hi), jnp.asarray(k_lo),
            jnp.asarray(vals), jnp.ones(b, bool))
        np.testing.assert_array_equal(np.asarray(out), want_out)
        np.testing.assert_array_equal(np.asarray(found), want_found)
        assert int(np.asarray(kv.dropped)) == 0
    # final table state: every surviving key holds its full lane vector
    ks = np.array(sorted(oracle), dtype=np.int64)
    if len(ks):
        k_hi, k_lo = split_i64(ks)
        f, v = jax.jit(kv_lookup_lanes)(kv, jnp.asarray(k_hi),
                                        jnp.asarray(k_lo))
        assert np.asarray(f).all()
        np.testing.assert_array_equal(
            np.asarray(v), np.stack([oracle[k] for k in ks]))


def test_put_delete_churn_reuses_capacity():
    # delete-in-place: churn on one key must not consume table slots
    kv = kv_init(4)  # 16 slots
    for i in range(40):
        kv, _, _ = _apply_np(kv, [Op.PUT, Op.DELETE], [7, 7], [i, 0])
    kv, out, found = _apply_np(kv, [Op.PUT, Op.GET], [7, 7], [99, 0])
    assert found[1] and out[1] == 99
    assert int(np.asarray(kv.dropped)) == 0


def test_probe_chain_with_collisions():
    # tiny table (16 slots) + more distinct keys than half capacity
    kv = kv_init(4)
    keys = np.arange(12, dtype=np.int64) * 1000
    kv, out, found = _apply_np(kv, [Op.PUT] * 12, keys, keys + 1)
    kv, out, found = _apply_np(kv, [Op.GET] * 12, keys, np.zeros(12))
    assert found.all()
    assert (out == keys + 1).all()
