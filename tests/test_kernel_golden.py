"""Golden byte-equality pin of the consensus kernels + routing fabric.

The PR-11 hot-path rewrite (segmented routing fabric, fused per-kind
slot writes) must be BYTE-IDENTICAL to the kernels it replaces: the
fixtures here were generated from the pre-rewrite tree (PR 9 HEAD,
``python tests/test_kernel_golden.py`` regenerates) and record a
blake2b digest of the FULL cluster state — stacked replica states,
routed pending inboxes, alive mask — after every step of a scenario
that drives all three protocols through elections, mixed
broadcast/unicast/client-bound traffic, inbox overflow, majority loss
(kill 3 of 5), stalled-frontier retries, revival and a mid-run leader
change. Any semantic drift in the step kernels OR the routing fabric
changes a digest; the test names the first divergent step.

This is deliberately stronger than output-level checks: the pending
inboxes pin the fabric's exact row ORDER (ack-run compression and
winner tie-breaks depend on it), and the per-step digests localize a
divergence to the step that introduced it.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys

import numpy as np

if __name__ == "__main__":  # direct regen run: mirror conftest's env
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

import pytest

from minpaxos_tpu.models.cluster import Cluster
from minpaxos_tpu.models.mencius import MenciusCluster
from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.models.paxos import classic_config
from minpaxos_tpu.wire.messages import Op

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "kernel_golden.json")

_KW = dict(n_replicas=5, window=64, inbox=32, exec_batch=16, kv_pow2=8,
           catchup_rows=8, recovery_rows=8)


def _digest(cs) -> str:
    import jax

    h = hashlib.blake2b(digest_size=16)
    for leaf in jax.tree_util.tree_leaves((cs.states, cs.pending, cs.alive)):
        h.update(np.asarray(leaf).tobytes())
    return h.hexdigest()


def _drive(protocol: str, extra_cfg: dict | None = None) -> list[str]:
    """Deterministic mixed-traffic scenario; one digest per step.

    ``extra_cfg`` merges extra MinPaxosConfig fields into the golden
    shape — test_flexible_quorum.py uses it to pin that an EXPLICIT
    (q1, q2) = (majority, majority) compiles byte-identically to the
    0-sentinel default recorded in the fixture."""
    kw = dict(_KW, **(extra_cfg or {}))
    if protocol == "mencius":
        cl = MenciusCluster(MinPaxosConfig(**kw), ext_rows=8)
    else:
        cfg = (classic_config(**kw) if protocol == "classic"
               else MinPaxosConfig(**kw))
        cl = Cluster(cfg, ext_rows=8)
    rng = np.random.default_rng(7)
    digests = []

    def step(n=1):
        for _ in range(n):
            cl.step()
            digests.append(_digest(cl.cs))

    def propose(n, client, to):
        keys = rng.integers(0, 40, n)
        vals = rng.integers(0, 1 << 16, n)
        ops = np.where(rng.random(n) < 0.7, int(Op.PUT), int(Op.GET))
        mids = np.arange(n) + len(digests) * 100 + client * 10_000
        cl.propose(ops, keys, vals, mids, client_id=client, to=to)

    if protocol != "mencius":
        cl.elect(0)
        step(2)  # deliver PREPAREs + replies -> prepared
        propose(20, client=1, to=0)  # chunked: 8+8+4 ext rows
        propose(5, client=2, to=0)
        step(6)
        cl.kill(2)
        propose(6, client=1, to=0)
        step(4)
        cl.kill(1)
        cl.kill(3)  # majority lost: frontier stalls, retries fire
        propose(4, client=2, to=0)
        step(8)
        cl.revive(1)
        cl.revive(2)
        cl.revive(3)
        step(6)
        cl.elect(1)  # leader change: PIR sweep over the old tenure
        step(3)
        propose(6, client=1, to=1)
        step(8)
    else:
        propose(10, client=1, to=0)
        propose(7, client=2, to=1)
        step(6)
        cl.kill(2)
        propose(6, client=1, to=3)
        step(6)
        cl.kill(1)
        cl.kill(3)
        propose(4, client=2, to=0)
        step(8)
        cl.revive(1)
        cl.revive(2)
        cl.revive(3)
        step(10)
        propose(5, client=1, to=2)
        step(8)
    return digests


PROTOCOLS = ("minpaxos", "classic", "mencius")


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_kernel_golden(protocol):
    with open(FIXTURE) as f:
        golden = json.load(f)
    got = _drive(protocol)
    want = golden[protocol]
    assert len(got) == len(want), (
        f"{protocol}: scenario length changed ({len(got)} vs {len(want)}) "
        f"— the golden scenario must not be edited without regenerating")
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, (
            f"{protocol}: state digest diverged at step {i} "
            f"(first {sum(a == b for a, b in zip(got, want))}/{len(want)} "
            f"match) — the rewritten kernel/fabric is no longer "
            f"byte-identical to the pre-rewrite tree")


if __name__ == "__main__":
    out = {p: _drive(p) for p in PROTOCOLS}
    os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
    with open(FIXTURE, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {FIXTURE}: " + ", ".join(
        f"{p}={len(d)} steps" for p, d in out.items()))
