"""MinPaxos protocol tests over the pod-mode cluster.

Programmatic equivalents of the reference's shell matrix (SURVEY.md
section 4): simpletest.sh smoke, exactly-once -check semantics
(client.go:279-284), leader kill + election
(leaderelectiontestmaster.sh), and the agreement invariant the TLA+
spec states (EgalitarianPaxos.tla:708 Consistency).
"""

import numpy as np
import pytest

from minpaxos_tpu.models.cluster import Cluster, tree_slice
from minpaxos_tpu.models.minpaxos import COMMITTED, MinPaxosConfig
from minpaxos_tpu.wire.messages import MsgKind, Op

CFG = MinPaxosConfig(n_replicas=3, window=256, inbox=512, exec_batch=128,
                     kv_pow2=10)


def boot(cfg=CFG) -> Cluster:
    c = Cluster(cfg, ext_rows=256)
    c.elect(0)
    c.run(3)
    return c


def test_boot_elects_leader():
    c = boot()
    st0 = tree_slice(c.cs.states, 0)
    assert bool(np.asarray(st0.prepared))
    assert c.leader == 0
    for r in range(3):
        assert int(np.asarray(tree_slice(c.cs.states, r).leader_id)) == 0


def test_basic_put_get_commit():
    c = boot()
    c.propose(ops=[Op.PUT, Op.PUT, Op.GET], keys=[1, 2, 1], vals=[10, 20, 0],
              cmd_ids=[0, 1, 2], client_id=7)
    c.run(4)
    assert c.replies[(7, 0)]["value"] == 10
    assert c.replies[(7, 1)]["value"] == 20
    assert c.replies[(7, 2)]["value"] == 10 and c.replies[(7, 2)]["found"]
    # all replicas converge on the same committed frontier
    for r in range(3):
        st = tree_slice(c.cs.states, r)
        assert int(np.asarray(st.committed_upto)) == 2


def test_follower_acks_are_run_length_compressed():
    """A follower receiving p contiguous ACCEPTs must emit ONE live
    ACCEPT_REPLY row covering the run (cmd_id = run length), not p rows
    — the round-3 ack-row explosion fix. The per-inbox-row ``acked``
    mask still reports every accepted row for the durability path."""
    import jax.numpy as jnp

    from minpaxos_tpu.models.minpaxos import (
        MsgBatch,
        init_replica,
        replica_step_impl,
    )

    cfg = CFG
    st = init_replica(cfg, me=1)
    # adopt leader 0's ballot via a PREPARE first
    prep = MsgBatch.empty(64)._replace(
        kind=jnp.zeros(64, jnp.int32).at[0].set(int(MsgKind.PREPARE)),
        ballot=jnp.zeros(64, jnp.int32).at[0].set(16),
        last_committed=jnp.full(64, -1, jnp.int32))
    st, _, _ = replica_step_impl(cfg, st, prep)
    p = 40
    rows = jnp.arange(64)
    acc = MsgBatch.empty(64)._replace(
        kind=jnp.where(rows < p, int(MsgKind.ACCEPT), 0).astype(jnp.int32),
        src=jnp.zeros(64, jnp.int32),
        ballot=jnp.full(64, 16, jnp.int32),
        inst=rows.astype(jnp.int32),
        last_committed=jnp.full(64, -1, jnp.int32),
        op=jnp.full(64, int(Op.PUT), jnp.int32),
        key_lo=rows.astype(jnp.int32),
        val_lo=rows.astype(jnp.int32))
    st, outbox, _ = replica_step_impl(cfg, st, acc)
    kinds = np.asarray(outbox.msgs.kind)
    ar = kinds == int(MsgKind.ACCEPT_REPLY)
    # exactly one live compressed ack for the whole contiguous run
    # (plus possibly the appended frontier-gossip row, which carries
    # op=0 and lives outside the first-64 inbox-aligned segment)
    assert ar[:64].sum() == 1
    i = int(np.nonzero(ar[:64])[0][0])
    assert int(np.asarray(outbox.msgs.inst)[i]) == 0
    assert int(np.asarray(outbox.msgs.cmd_id)[i]) == p
    assert int(np.asarray(outbox.msgs.op)[i]) == 1
    np.testing.assert_array_equal(np.asarray(outbox.acked)[:p], True)
    np.testing.assert_array_equal(np.asarray(outbox.acked)[p:], False)


def test_exactly_once_large_batch():
    c = boot()
    n = 200
    c.propose(ops=[Op.PUT] * n, keys=list(range(n)), vals=[k * 3 for k in range(n)],
              cmd_ids=list(range(n)), client_id=1)
    c.run(5)
    assert len(c.replies) == n
    dups = [e for e in c.reply_log if e.get("duplicate")]
    assert not dups
    for i in range(n):
        assert c.replies[(1, i)]["value"] == i * 3


def test_agreement_across_replicas():
    c = boot()
    rng = np.random.default_rng(0)
    for batch in range(3):
        n = 50
        c.propose(ops=rng.choice([Op.PUT, Op.GET], n), keys=rng.integers(0, 20, n),
                  vals=rng.integers(0, 100, n), cmd_ids=np.arange(n) + batch * n,
                  client_id=2)
        c.run(4)
    frontiers, bases, logs, kvs = [], [], [], []
    for r in range(3):
        st = tree_slice(c.cs.states, r)
        f = int(np.asarray(st.committed_upto))
        frontiers.append(f)
        bases.append(int(np.asarray(st.window_base)))
        logs.append((np.asarray(st.op), np.asarray(st.key_lo),
                     np.asarray(st.val_lo), np.asarray(st.cmd_id)))
        live = np.asarray(st.kv.slot) == 1
        kvs.append(dict(zip(np.asarray(st.kv.key_lo)[live].tolist(),
                            np.asarray(st.kv.val[:, 1])[live].tolist())))
    assert min(frontiers) == max(frontiers) >= 149
    # committed slots still resident in every window agree slot-by-slot
    # (Consistency; every replica retains `retention` executed slots,
    # so the overlap is non-empty by construction)
    lo, hi = max(bases), min(frontiers) + 1
    assert hi - lo > 0, "no co-resident committed slots — vacuous check"
    for r in range(1, 3):
        for a, b in zip(logs[0], logs[r]):
            np.testing.assert_array_equal(
                a[lo - bases[0] : hi - bases[0]],
                b[lo - bases[r] : hi - bases[r]])
    # executed state machines agree exactly (end-to-end Consistency:
    # same committed log => same KV contents)
    assert kvs[0] == kvs[1] == kvs[2] and kvs[0]


def test_leader_failover():
    c = boot()
    c.propose(ops=[Op.PUT], keys=[5], vals=[50], cmd_ids=[0], client_id=3)
    c.run(4)
    assert c.replies[(3, 0)]["value"] == 50
    # kill the leader; master promotes replica 1 (real Prepare round)
    c.kill(0)
    c.elect(1)
    c.run(3)
    st1 = tree_slice(c.cs.states, 1)
    assert bool(np.asarray(st1.prepared))
    c.propose(ops=[Op.GET], keys=[5], vals=[0], cmd_ids=[1], client_id=3, to=1)
    c.run(4)
    assert c.replies[(3, 1)]["value"] == 50 and c.replies[(3, 1)]["found"]
    # replica 2 followed the new leader
    st2 = tree_slice(c.cs.states, 2)
    assert int(np.asarray(st2.leader_id)) == 1


def test_propose_to_follower_rejected_with_leader_hint():
    c = boot()
    c.propose(ops=[Op.PUT], keys=[9], vals=[90], cmd_ids=[0], client_id=4, to=2)
    c.run(3)
    rej = [e for e in c.reply_log if e.get("ok") is False]
    assert rej and rej[0]["leader"] == 0  # ProposeReplyTS.Leader re-routing
    assert (4, 0) not in c.replies


def test_dead_replica_stalls_then_recovers():
    cfg = CFG
    c = boot(cfg)
    c.kill(2)
    # majority (2 of 3) still commits
    c.propose(ops=[Op.PUT], keys=[1], vals=[11], cmd_ids=[0], client_id=5)
    c.run(4)
    assert c.replies[(5, 0)]["value"] == 11
    # revive: catches up via the next accept's piggybacked frontier
    c.revive(2)
    c.propose(ops=[Op.PUT], keys=[2], vals=[22], cmd_ids=[1], client_id=5)
    c.run(4)
    st2 = tree_slice(c.cs.states, 2)
    assert int(np.asarray(st2.committed_upto)) >= 0


def test_adopted_value_not_redriven_before_phase1_majority():
    """Safety regression (round-3 review): a new leader that adopted a
    slot value from a SINGLE phase-1 answer must not re-drive it until
    a per-slot majority has answered — an early re-drive could push a
    superseded value over one committed under a higher ballot
    (classic Paxos phase-2 precondition). Drives replica_step_impl
    directly to stage the async race pod-mode routing can't produce."""
    import jax
    import jax.numpy as jnp

    from minpaxos_tpu.models.minpaxos import (
        ACCEPTED, MsgBatch, become_leader, init_replica, replica_step_impl)
    from minpaxos_tpu.wire.messages import MsgKind

    cfg = MinPaxosConfig(n_replicas=5, window=64, inbox=64, exec_batch=16,
                         kv_pow2=8, catchup_rows=8, recovery_rows=8)
    st = init_replica(cfg, me=1)
    st, _ = become_leader(cfg, st)
    bal = int(np.asarray(st.default_ballot))
    # prepare majority so the leader serves; a 3-slot in-flight span
    st = st._replace(
        prepared=jnp.asarray(True),
        prepare_oks=jnp.ones(5, dtype=bool),
        crt_inst=jnp.int32(3),
    )
    # one early phase-1 answer from replica 0 reporting v_old at an
    # old ballot for slot 0 (context tag = current ballot)
    pir = MsgBatch.empty(cfg.inbox)
    pir = pir._replace(
        kind=pir.kind.at[0].set(int(MsgKind.PREPARE_INST_REPLY)),
        src=pir.src.at[0].set(0),
        inst=pir.inst.at[0].set(0),
        ballot=pir.ballot.at[0].set(2 * 16 + 0),  # v_old's low ballot
        last_committed=pir.last_committed.at[0].set(bal),
        op=pir.op.at[0].set(int(Op.PUT)),
        key_lo=pir.key_lo.at[0].set(11),
        val_lo=pir.val_lo.at[0].set(99),
    )
    st, out, _ = replica_step_impl(cfg, st, pir)
    assert int(np.asarray(st.status)[0]) == ACCEPTED  # adopted
    # stall a few steps: only 2/5 answered (self + replica 0) -> the
    # retry path must NOT broadcast an ACCEPT for slot 0 yet
    for _ in range(4):
        st, out, _ = replica_step_impl(cfg, st, MsgBatch.empty(cfg.inbox))
        acc = (np.asarray(out.msgs.kind) == int(MsgKind.ACCEPT)) & (
            np.asarray(out.msgs.inst) == 0)
        assert not acc.any(), "re-drove adopted value before majority"
    # two more answers (replicas 2, 3 report empty) -> majority of 5
    pir2 = MsgBatch.empty(cfg.inbox)
    pir2 = pir2._replace(
        kind=pir2.kind.at[:2].set(int(MsgKind.PREPARE_INST_REPLY)),
        src=pir2.src.at[0].set(2).at[1].set(3),
        inst=pir2.inst.at[:2].set(0),
        ballot=pir2.ballot.at[:2].set(-1),  # empty answers
        last_committed=pir2.last_committed.at[:2].set(bal),
    )
    st, out, _ = replica_step_impl(cfg, st, pir2)
    st, out, _ = replica_step_impl(cfg, st, MsgBatch.empty(cfg.inbox))
    acc = (np.asarray(out.msgs.kind) == int(MsgKind.ACCEPT)) & (
        np.asarray(out.msgs.inst) == 0) & (np.asarray(out.msgs.ballot) == bal)
    assert acc.any(), "majority reached but adopted value never re-driven"
