"""paxtrace: per-command distributed tracing (obs/trace.py).

Unit half: context frame round-trip + v1 wire compat, deterministic
cross-process sampling agreement, span-ring wraparound, schema-v5
validator pins in both directions, clock-anchor monotonicity and the
stage-decomposition math. Integration half: a live 3-replica cluster
traced end to end — TRACESPANS replica verb + master fan-out + a
complete client -> replica -> commit -> reply span chain whose stage
sum equals the measured end-to-end latency, and tools/tail.py as a
real subprocess.
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import numpy as np
import pytest

from minpaxos_tpu.obs import trace as T
from minpaxos_tpu.obs.recorder import (
    DEVICE_PID,
    SCHEMA_VERSION,
    TRACE_PID,
    chrome_trace,
    validate_chrome_trace,
)
from minpaxos_tpu.wire.codec import StreamDecoder, decode_frame, encode_frame
from minpaxos_tpu.wire.messages import MsgKind, make_batch

REPO = Path(__file__).resolve().parents[1]


# ------------------------------------------------------- wire context


def test_trace_ctx_frame_roundtrip():
    ids = np.arange(5, dtype=np.int32) * 7
    frame = make_batch(MsgKind.TRACE_CTX, cmd_id=ids,
                       trace_id=T.trace_id_for(ids.astype(np.int64)),
                       origin_wall_ns=987_654_321_000)
    buf = encode_frame(MsgKind.TRACE_CTX, frame)
    kind, rows, end = decode_frame(buf)
    assert kind == MsgKind.TRACE_CTX and end == len(buf)
    np.testing.assert_array_equal(rows["cmd_id"], ids)
    np.testing.assert_array_equal(rows["trace_id"], frame["trace_id"])
    assert (rows["origin_wall_ns"] == 987_654_321_000).all()
    # the ledger entry matches the live schema (append-only contract)
    from minpaxos_tpu.analysis.wire_golden import GOLDEN_KINDS

    val, size = GOLDEN_KINDS["TRACE_CTX"]
    assert val == int(MsgKind.TRACE_CTX)
    assert size == rows.dtype.itemsize == 20


def test_v1_frames_still_parse_and_disabled_tracing_is_transparent():
    """Old peers: a stream WITHOUT ctx frames (v1 client, or tracing
    off) decodes exactly as before; a v2 stream interleaving ctx
    frames decodes both kinds in order. A decoder that doesn't know
    TRACE_CTX (a v1 peer) never sees one when tracing is off — pinned
    by byte equality of the tracing-off propose path."""
    prop = make_batch(MsgKind.PROPOSE, cmd_id=np.arange(3, dtype=np.int32),
                      op=1, key=np.arange(3), val=7, timestamp=9)
    v1_stream = encode_frame(MsgKind.PROPOSE, prop)
    dec = StreamDecoder()
    frames = dec.feed(v1_stream)
    assert [k for k, _ in frames] == [MsgKind.PROPOSE]

    # v2 stream: ctx frame ahead of the propose, same connection
    ctx = make_batch(MsgKind.TRACE_CTX, cmd_id=np.int32(1),
                     trace_id=T.trace_id_for(1), origin_wall_ns=5)
    dec2 = StreamDecoder()
    frames2 = dec2.feed(encode_frame(MsgKind.TRACE_CTX, ctx) + v1_stream)
    assert [k for k, _ in frames2] == [MsgKind.TRACE_CTX, MsgKind.PROPOSE]

    # tracing disabled writes ONLY the propose frame (byte-transparent)
    from minpaxos_tpu.runtime.client import Client

    class _CapSock:
        def __init__(self):
            self.data = b""

        def sendall(self, b):
            self.data += b

    cli = Client.__new__(Client)  # no network: exercise propose() only
    cli.trace = None
    cli.metrics = None
    from minpaxos_tpu.obs.metrics import MetricsRegistry
    from minpaxos_tpu.wire.codec import FrameWriter

    cli._c_proposed = MetricsRegistry("t").counter("proposed_rows")
    off_sock = _CapSock()
    cli.writer = FrameWriter(off_sock)
    cli.propose([1], [1], [42], [7])
    k0, rows0, _ = decode_frame(off_sock.data)
    assert k0 == MsgKind.PROPOSE and len(off_sock.data) == \
        5 + rows0.dtype.itemsize  # header + one row, nothing else

    # tracing on (pow2=0): ctx frame precedes the propose
    cli.trace = T.TraceSink(enabled=True, sample_pow2=0)
    on_sock = _CapSock()
    cli.writer = FrameWriter(on_sock)
    cli.propose([1], [1], [42], [7])
    k1, rows1, end = decode_frame(on_sock.data)
    assert k1 == MsgKind.TRACE_CTX
    assert int(rows1["trace_id"][0]) == T.trace_id_for(1)
    k2, _, _ = decode_frame(on_sock.data, end)
    assert k2 == MsgKind.PROPOSE


# ---------------------------------------------------------- sampling


def test_sampling_deterministic_and_scalar_vector_agree():
    ids = np.arange(-512, 4096, dtype=np.int64)
    for pow2 in (0, 1, 4, 8):
        m = T.sampled_mask(ids, pow2)
        scal = np.array([T.is_sampled(int(i), pow2) for i in ids])
        np.testing.assert_array_equal(m, scal)
        # rate is roughly 1-in-2^k (deterministic, not random — just
        # sanity that the hash spreads)
        if pow2:
            assert 0.3 / 2 ** pow2 < m.mean() < 3.0 / 2 ** pow2
        else:
            assert m.all()
    # trace ids: nonzero, scalar == vectorized
    tids = T.trace_id_for(ids)
    assert (tids != 0).all()
    assert int(tids[0]) == T.trace_id_for(int(ids[0]))
    assert T.mix64_scalar(12345) == int(T.mix64(12345))


def test_sampling_agreement_across_processes():
    """The distributed contract: a SEPARATE python process computes the
    identical sample set and trace ids for the same command ids — no
    coordination, no shared state."""
    code = textwrap.dedent("""
        import sys, json, numpy as np
        sys.path.insert(0, %r)
        from minpaxos_tpu.obs import trace as T
        ids = np.arange(2000, dtype=np.int64)
        m = T.sampled_mask(ids, 4)
        print(json.dumps({
            "sampled": np.nonzero(m)[0].tolist(),
            "tids": T.trace_id_for(ids[m]).tolist()}))
    """) % str(REPO)
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    got = json.loads(out.stdout)
    ids = np.arange(2000, dtype=np.int64)
    m = T.sampled_mask(ids, 4)
    assert got["sampled"] == np.nonzero(m)[0].tolist()
    assert got["tids"] == T.trace_id_for(ids[m]).tolist()


# ---------------------------------------------------------- span rings


def test_span_ring_wraparound_keeps_newest():
    r = T.SpanRing(8)
    for i in range(20):
        r.record(100 + i, T.ST_DRAIN, 1000 * i, 1000 * i + 1, i)
    assert r.total == 20 and r.dropped == 12
    snap = r.snapshot()
    assert snap.shape == (8, T.N_SPAN_FIELDS)
    np.testing.assert_array_equal(snap[:, T.SP_TRACE],
                                  [100 + i for i in range(12, 20)])
    assert (np.diff(snap[:, T.SP_T0]) > 0).all()
    with pytest.raises(ValueError):
        T.SpanRing(0)


def test_sink_per_thread_rings_and_collect():
    import threading

    sink = T.TraceSink(enabled=True, sample_pow2=0, ring_capacity=16)
    sink.stamp(T.ST_DRAIN, 1, 10, 10)

    def other():
        sink.stamp(T.ST_EXEC, 1, 20, 20)

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert len(sink._rings) == 2  # one ring per writer thread
    # a NEW thread adopts the dead thread's ring instead of leaking a
    # fresh one (transport churns a reader thread per client
    # connection — an append-only registry would grow forever)
    t2 = threading.Thread(target=lambda: sink.stamp(T.ST_EXEC, 2, 30, 30))
    t2.start()
    t2.join()
    assert len(sink._rings) == 2
    c = sink.collect()
    assert c["total"] == 3 and c["dropped"] == 0
    assert {row[T.SP_STAGE] for row in c["spans"]} == {T.ST_DRAIN,
                                                       T.ST_EXEC}
    assert c["anchor"]["mono_ns"] > 0 and c["anchor"]["wall_ns"] > 0
    json.dumps(c)  # the TRACESPANS verb ships this as JSON


def test_clock_anchor_monotonicity_and_alignment():
    a1 = T.clock_anchor()
    time.sleep(0.002)
    a2 = T.clock_anchor()
    assert a2["mono_ns"] > a1["mono_ns"]
    assert a2["wall_ns"] >= a1["wall_ns"]
    # alignment: a collection whose clock runs 5 s "behind" (smaller
    # mono for the same wall) lands its spans 5 s later in the
    # reference domain — the wall anchors are the bridge
    ref = {"mono_ns": 1_000, "wall_ns": 10_000}
    skew = {"mono_ns": 1_000 - 5_000_000_000,
            "wall_ns": 10_000}
    spans = [[7, T.ST_DRAIN, 100 - 5_000_000_000,
              100 - 5_000_000_000, 0]]
    out = T.align_collections(
        [{"anchor": skew, "spans": spans}], ref_anchor=ref)
    assert out[0][T.SP_T0] == 100
    # empty collections survive
    assert len(T.align_collections([{"anchor": ref, "spans": []}])) == 0


# ------------------------------------------------- decomposition math


def _chain(cmd, t0, commit_ms=2.0, exec_ms=0.5, out_ms=1.0):
    tid = T.trace_id_for(cmd)
    ns = lambda ms: int(ms * 1e6)  # noqa: E731
    return [
        (tid, T.ST_SEND, t0, t0 + ns(0.1), cmd),
        (tid, T.ST_DECODE, t0 + ns(0.3), t0 + ns(0.4), cmd),
        (tid, T.ST_DRAIN, t0 + ns(0.9), t0 + ns(0.9), 10),
        (tid, T.ST_COMMIT, t0 + ns(0.9 + commit_ms),
         t0 + ns(0.9 + commit_ms), 5),
        (tid, T.ST_EXEC, t0 + ns(0.9 + commit_ms + exec_ms),
         t0 + ns(0.9 + commit_ms + exec_ms), 12),
        (tid, T.ST_REPLY_SER, t0 + ns(0.9 + commit_ms + exec_ms),
         t0 + ns(1.0 + commit_ms + exec_ms), cmd),
        (tid, T.ST_REPLY_RECV, t0 + ns(1.0 + commit_ms + exec_ms + out_ms),
         t0 + ns(1.0 + commit_ms + exec_ms + out_ms), cmd),
    ]


def test_stage_decomposition_sums_to_end_to_end():
    spans = np.array(_chain(1, 10**9) + _chain(2, 2 * 10**9, commit_ms=40.0),
                     np.int64)
    chains = T.span_chains(spans)
    decomp = T.stage_decomposition(chains)
    assert len(decomp) == 2
    for d in decomp:
        assert abs(sum(d["stages"].values()) - d["total_ms"]) < 1e-9
    tab = T.stage_table(decomp)
    assert tab["n_traced"] == 2
    assert tab["tail"]["worst_stage"] == "commit"
    assert "commit" in T.format_stage_table(tab)
    # round correlation: exec aux - drain aux = dispatches to commit
    assert all(d["commit_dispatches"] == 2 for d in decomp)
    # incomplete chains (no commit) are excluded, not crashed on
    partial = np.array(_chain(3, 10**9)[:2], np.int64)
    assert T.stage_decomposition(T.span_chains(partial)) == []
    # duplicate-stage resolution: a commit span from a NEWER life of a
    # reused cmd_id (43 ms, after this chain's exec at 3.4 ms) must
    # not splice into an impossible chain — the backwards walk keeps
    # the consistent 2.0 ms-commit life and the table stays sane
    rows = _chain(4, 10**9)
    tid4 = T.trace_id_for(4)
    ns = lambda ms: int(ms * 1e6)  # noqa: E731
    rows.append((tid4, T.ST_COMMIT, 10**9 + ns(43.0), 10**9 + ns(43.0), 5))
    mixed = T.stage_decomposition(T.span_chains(np.array(rows, np.int64)))
    assert len(mixed) == 1
    assert abs(mixed[0]["stages"]["commit"] - 2.0) < 1e-9
    # a deduped retry: the client re-stamps SEND/DECODE 3 s later but
    # the server admitted the FIRST attempt — the walk recovers the
    # first-attempt start, so the slow command keeps its true latency
    rows2 = _chain(5, 10**9)
    tid5 = T.trace_id_for(5)
    rows2.append((tid5, T.ST_SEND, 10**9 + ns(3000.0),
                  10**9 + ns(3000.1), 5))
    rows2.append((tid5, T.ST_DECODE, 10**9 + ns(3000.3),
                  10**9 + ns(3000.4), 5))
    retry = T.stage_decomposition(T.span_chains(np.array(rows2, np.int64)))
    assert len(retry) == 1
    assert abs(retry[0]["total_ms"] - 4.5) < 1e-9  # first-send anchored


def test_schema_v5_pins_both_directions():
    """Current-schema readers reject older-stamped traces; paxtrace
    events must ride the reserved pid (and nothing else may squat on
    it). (v6 bumped the stamp for paxwatch event tracks; the paxtrace
    pid reservation is unchanged.)"""
    assert SCHEMA_VERSION == 7
    spans = np.array(_chain(1, 10**9), np.int64)
    chains = T.span_chains(spans)
    decomp = T.stage_decomposition(chains)
    events = T.span_events(decomp, chains)
    assert events and all(e["pid"] == TRACE_PID for e in events)
    assert all(e["args"]["trace_id"] == decomp[0]["trace_id"]
               for e in events)
    tr = chrome_trace(events)
    assert validate_chrome_trace(tr) == []
    # older-stamped file fails against the current reader
    stale = chrome_trace(events)
    stale["otherData"]["paxmonSchemaVersion"] = 4
    errs = validate_chrome_trace(stale)
    assert errs and "mismatch" in errs[0]
    # a paxtrace event off the reserved pid fails
    bad = chrome_trace([dict(events[0], pid=3)])
    assert any("reserved pid" in e for e in validate_chrome_trace(bad))
    # a non-paxtrace event squatting on TRACE_PID fails
    squat = chrome_trace([{"name": "tick:full", "cat": "tick", "ph": "X",
                           "ts": 1.0, "dur": 1, "pid": TRACE_PID,
                           "tid": 0}])
    assert any("reserved for paxtrace" in e
               for e in validate_chrome_trace(squat))
    # device-pid reservation from v4 still enforced alongside
    dev_bad = chrome_trace([{"name": "device_frontier", "ph": "C",
                             "ts": 1.0, "pid": 1, "tid": 0,
                             "args": {"device_frontier": 1}}])
    assert any(str(DEVICE_PID) in e for e in validate_chrome_trace(dev_bad))


# ----------------------------------------------- cluster integration


def _ctl(addr, req):
    from minpaxos_tpu.utils.netutil import CONTROL_OFFSET

    host, port = addr
    with socket.create_connection((host, port + CONTROL_OFFSET),
                                  timeout=10) as s:
        f = s.makefile("rw")
        f.write(json.dumps(req) + "\n")
        f.flush()
        return json.loads(f.readline())


@pytest.mark.slow  # ~13 s cluster boot; tier-1's 870 s budget is
# within noise of the suite wall (PR 8 precedent) — the stage math,
# wire compat and v5 pins above stay tier-1, and obs_smoke gates the
# tail/TRACESPANS path against a control-plane stub every build
def test_live_cluster_tracespans_and_end_to_end_chain(tmp_path):
    """The tentpole, end to end: every op traced (pow2=0) on a live
    3-replica cluster; the TRACESPANS verb + master fan-out collect
    span rings cluster-wide; merged with the client's own spans, at
    least one command has a COMPLETE chain (send -> decode -> drain ->
    commit -> exec -> reply_ser -> reply_recv) whose stage sum equals
    its end-to-end latency; and tools/tail.py (a real subprocess, no
    JAX) prints the stage table from the same cluster."""
    from test_distributed import Harness

    from minpaxos_tpu.runtime.client import Client, gen_workload
    from minpaxos_tpu.runtime.master import cluster_tracespans

    h = Harness(tmp_path,
                flags_overrides={i: {"trace_pow2": 0} for i in range(3)})
    try:
        cli = Client(("127.0.0.1", h.mport), check=True, trace_pow2=0)
        ops, keys, vals = gen_workload(120, seed=11)
        stats = cli.run_workload(ops, keys, vals, timeout_s=60)
        assert stats["acked"] == 120, stats

        # replica-level verb
        r = _ctl(h.addrs[0], {"m": "tracespans"})
        assert r["ok"] and r["trace"]["enabled"]
        assert r["trace"]["sample_pow2"] == 0
        assert r["trace"]["total"] > 0
        assert r["trace"]["anchor"]["mono_ns"] > 0

        # trace counters ride the stats snapshot (paxtop TRACE
        # column); the gauge is read later than the verb's snapshot,
        # so it may only have grown
        st = _ctl(h.addrs[0], {"m": "stats"})
        assert st["metrics"]["gauges"]["trace_spans"] >= r["trace"]["total"]

        # master fan-out + client merge -> complete chains
        resp = cluster_tracespans(("127.0.0.1", h.mport))
        assert resp["ok"] and len(resp["replicas"]) == 3
        colls = [rr["trace"] for rr in resp["replicas"] if rr.get("ok")]
        assert len(colls) == 3
        colls.append(cli.trace_collect())
        chains = T.span_chains(T.align_collections(colls))
        decomp = T.stage_decomposition(chains)
        assert len(decomp) >= 100, len(decomp)  # nearly all 120 traced
        for d in decomp:
            assert abs(sum(d["stages"].values()) - d["total_ms"]) < 1e-9
            assert d["total_ms"] > 0
            # client-side receipt present => transport_out measured
            assert d["stages"]["transport_out"] >= 0
        tab = T.stage_table(decomp)
        assert tab["n_traced"] == len(decomp)
        assert tab["tail"]["worst_stage"] in T.DECOMP_STAGES

        # the shipped tool against the live cluster (no client spans:
        # chains still complete via the ctx ORIGIN echo)
        out = subprocess.run(
            [sys.executable, str(REPO / "tools/tail.py"),
             "-mport", str(h.mport), "--once", "--json"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        payload = json.loads(out.stdout)
        assert payload["stage_table"]["n_traced"] >= 100
        # cluster-only chains end at reply serialization
        assert all(d["stages"]["transport_out"] == 0
                   for d in payload["per_trace"])

        # tail -dump-trace merges a valid v5 file: recorder ticks from
        # replica pids + command spans on the reserved pid
        tf = tmp_path / "tail_trace.json"
        out = subprocess.run(
            [sys.executable, str(REPO / "tools/tail.py"),
             "-mport", str(h.mport), "-dump-trace", str(tf),
             "-last", "256"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        merged = json.loads(tf.read_text())
        assert validate_chrome_trace(merged) == []
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert TRACE_PID in pids and {0, 1, 2} <= pids
        cli.close_conn()
    finally:
        h.stop()


@pytest.mark.slow  # see the budget note above
def test_notrace_flag_is_silent_and_cheap(tmp_path):
    """trace=False: no spans collected, TRACESPANS answers empty-but-
    ok, and the client sends no ctx frames (wire transparency at the
    server: proposals are admitted exactly as before)."""
    from test_distributed import Harness

    from minpaxos_tpu.runtime.client import gen_workload

    h = Harness(tmp_path, n=1, flags_overrides={0: {"trace": False}})
    try:
        cli = h.client()
        ops, keys, vals = gen_workload(40, seed=2)
        assert cli.run_workload(ops, keys, vals,
                                timeout_s=60)["acked"] == 40
        cli.close_conn()
        r = _ctl(h.addrs[0], {"m": "tracespans"})
        assert r["ok"] and r["trace"]["enabled"] is False
        assert r["trace"]["total"] == 0
        assert h.servers[0].stats["trace_spans"] == 0
    finally:
        h.stop()
