"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices (the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The container's sitecustomize force-registers the TPU tunnel backend
# ("axon") and pins jax_platforms; override before any backend init so
# the suite runs on the virtual 8-device CPU mesh, not through the
# (slow-compile) tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# shared persistent compile cache (repo-local .jax_cache): the suite
# boots many real server processes that would otherwise each re-jit
# identical kernels for seconds on the 1-core CI host
from minpaxos_tpu.utils.backend import enable_compile_cache  # noqa: E402

enable_compile_cache()
