"""paxlint analyzer suite: every rule fires on its seeded violation,
stays quiet on the clean idiom, and the real tree is clean.

Fixtures are in-memory Projects (minpaxos_tpu/analysis/core.py), so a
seeded violation and a real one travel exactly the same code path the
CLI uses; one subprocess test pins the tools/lint.py exit-code and
--json contract that tools/run_tier1.sh and future benches rely on.
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

from minpaxos_tpu.analysis import Project, run_passes
from minpaxos_tpu.analysis import wire_contract as wc
from minpaxos_tpu.analysis.wire_golden import (
    GOLDEN_HEADER_FMT,
    GOLDEN_KINDS,
    GOLDEN_MAX_FRAME_ROWS,
)

REPO = Path(__file__).resolve().parents[1]


def rules_of(violations):
    return {v.rule for v in violations}


def lint_src(path: str, src: str, rule: str):
    return run_passes(Project({path: src}), (rule,))


# ---------------------------------------------------------------- trace


TRACE_BAD = '''
import jax
import numpy as np

@jax.jit
def step(state):
    if state > 0:                 # traced branch
        pass
    n = int(state)                # host coercion
    m = state.sum().item()        # host sync
    a = np.asarray(state)         # device -> host pull
    for i in range(state):        # traced iteration
        pass
    return n, m, a
'''

TRACE_CLEAN = '''
import jax
import jax.numpy as jnp

@jax.jit
def step(cfg, state):
    if cfg.explicit_commit:        # static config branch
        state = state + 1
    if getattr(state, "leader_id", None) is not None:  # structural
        pass
    w = state.shape[0]             # structural read
    if w > 4:                      # branch on a python int
        state = state * 2
    for name in state._asdict().items():  # container of tracers
        pass
    return jnp.where(state > 0, state, -state)
'''


def test_trace_hazard_fires_on_seeded_violations():
    vs = lint_src("minpaxos_tpu/models/fix.py", TRACE_BAD, "trace-hazard")
    msgs = "\n".join(v.msg for v in vs)
    assert len(vs) == 5, vs
    for needle in ("`if`", "`int()`", "`.item()`", "`np.asarray`", "`for`"):
        assert needle in msgs, f"missing {needle}: {msgs}"


def test_trace_hazard_quiet_on_clean_idiom():
    assert lint_src("minpaxos_tpu/models/ok.py", TRACE_CLEAN,
                    "trace-hazard") == []


def test_trace_hazard_follows_calls_across_modules():
    helper = '''
def helper(v):
    return v.item()
'''
    entry = '''
import jax
from minpaxos_tpu.ops.helper import helper

@jax.jit
def entry(x):
    return helper(x)
'''
    vs = run_passes(Project({
        "minpaxos_tpu/ops/helper.py": helper,
        "minpaxos_tpu/models/entry.py": entry,
    }), ("trace-hazard",))
    assert any(v.path.endswith("helper.py") for v in vs), vs


def test_trace_hazard_ops_package_numpy_needs_suppression():
    src = '''
import numpy as np

def host_helper(x):
    return np.asarray(x)
'''
    vs = lint_src("minpaxos_tpu/ops/h.py", src, "trace-hazard")
    assert len(vs) == 1 and "device-kernel package" in vs[0].msg
    # models/ has host harnesses (cluster.py): no package-wide rule
    assert lint_src("minpaxos_tpu/models/h.py", src, "trace-hazard") == []
    # the suppression syntax clears it
    sup = src.replace(
        "return np.asarray(x)",
        "return np.asarray(x)  # paxlint: disable=trace-hazard -- host")
    assert lint_src("minpaxos_tpu/ops/h.py", sup, "trace-hazard") == []


# ------------------------------------------------------------ recompile


def test_recompile_hazard_fires():
    src = '''
import jax, functools

_REGISTRY = {}

def f(x, buf=[]):
    return x

@functools.partial(jax.jit, static_argnums=(1,))
def g(x, opts={}):
    return _REGISTRY and x
'''
    vs = lint_src("minpaxos_tpu/ops/r.py", src, "recompile-hazard")
    msgs = "\n".join(v.msg for v in vs)
    assert "mutable default for `buf`" in msgs
    # `opts` trips both the mutable-default and the unhashable-static
    # checks on one line; violations dedup per (path, line, rule), so
    # exactly one of the two messages survives
    assert "`opts`" in msgs
    assert "mutable module global `_REGISTRY`" in msgs


def test_recompile_hazard_quiet_on_clean_idiom():
    src = '''
import jax, functools
import jax.numpy as jnp

_BIG = jnp.int32(2 ** 30)          # immutable device constant: fine

@functools.partial(jax.jit, static_argnums=0)
def g(cfg, x, k=1, extra=None):
    return x + _BIG
'''
    assert lint_src("minpaxos_tpu/ops/ok.py", src, "recompile-hazard") == []


def test_recompile_hazard_static_argnums_out_of_range():
    src = '''
import jax

def f(x):
    return x

g = jax.jit(f, static_argnums=(3,))
'''
    vs = lint_src("minpaxos_tpu/ops/r2.py", src, "recompile-hazard")
    assert any("out of range" in v.msg for v in vs), vs


# ----------------------------------------------------------------- wire


def _real_wire():
    msgs = (REPO / "minpaxos_tpu/wire/messages.py").read_text()
    codec = (REPO / "minpaxos_tpu/wire/codec.py").read_text()
    return msgs, codec


def test_wire_contract_clean_on_real_tree():
    msgs, codec = _real_wire()
    assert wc.check(msgs, codec, GOLDEN_KINDS, GOLDEN_HEADER_FMT,
                    GOLDEN_MAX_FRAME_ROWS) == []


def test_wire_contract_collision_and_renumber():
    msgs, codec = _real_wire()
    drift = msgs.replace("SKIP = 28", "SKIP = 24")  # collides PREPARE_INST
    vs = wc.check(drift, codec, GOLDEN_KINDS, GOLDEN_HEADER_FMT,
                  GOLDEN_MAX_FRAME_ROWS)
    assert any("collision" in v.msg for v in vs), vs
    assert any("renumbered" in v.msg for v in vs), vs


def test_wire_contract_removed_kind_and_width_drift():
    msgs, codec = _real_wire()
    vs = wc.check(msgs.replace("SKIP = 28", "SKIPPED = 28"), codec,
                  GOLDEN_KINDS, GOLDEN_HEADER_FMT, GOLDEN_MAX_FRAME_ROWS)
    assert any("removed" in v.msg for v in vs), vs
    # widen READ's cmd_id: packed row width drifts 12 -> 16 bytes
    wide = msgs.replace('np.dtype([("cmd_id", "<i4"), ("key", "<i8")])',
                        'np.dtype([("cmd_id", "<i8"), ("key", "<i8")])')
    assert wide != msgs
    vs = wc.check(wide, codec, GOLDEN_KINDS, GOLDEN_HEADER_FMT,
                  GOLDEN_MAX_FRAME_ROWS)
    assert any("width drift" in v.msg for v in vs), vs


def test_wire_contract_codec_header_and_bound():
    msgs, codec = _real_wire()
    vs = wc.check(msgs, codec.replace('"<BI"', '"<BH"'), GOLDEN_KINDS,
                  GOLDEN_HEADER_FMT, GOLDEN_MAX_FRAME_ROWS)
    assert any("header format" in v.msg for v in vs), vs
    vs = wc.check(msgs, codec.replace("1 << 22", "1 << 20"), GOLDEN_KINDS,
                  GOLDEN_HEADER_FMT, GOLDEN_MAX_FRAME_ROWS)
    assert any("MAX_FRAME_ROWS" in v.msg for v in vs), vs


def test_wire_contract_new_kind_appends_cleanly():
    msgs, codec = _real_wire()
    grown = msgs.replace("    SKIP = 28",
                         "    SKIP = 28\n    SNAPSHOT = 29")
    vs = wc.check(grown, codec, GOLDEN_KINDS, GOLDEN_HEADER_FMT,
                  GOLDEN_MAX_FRAME_ROWS)
    # appending with a fresh value breaks no append-only/collision
    # rule, but the new kind is nudged to finish the job in the same
    # PR: add a SCHEMAS entry (decodability) and record it in the
    # ledger (drift protection) — without the latter a later renumber
    # of SNAPSHOT would go unnoticed
    assert all("no SCHEMAS entry" in v.msg or "not recorded" in v.msg
               for v in vs), vs
    assert any("not recorded in the wire ledger" in v.msg for v in vs), vs
    reuse = msgs.replace("    SKIP = 28",
                         "    SKIP = 28\n    SNAPSHOT = 20")
    vs = wc.check(reuse, codec, GOLDEN_KINDS, GOLDEN_HEADER_FMT,
                  GOLDEN_MAX_FRAME_ROWS)
    assert any("reuses recorded opcode" in v.msg for v in vs), vs


# ---------------------------------------------------------- concurrency


CONC_BAD = '''
import threading, socket, time

class Transport:
    def __init__(self):
        self._lock = threading.Lock()
        self.peers = {}

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        self.peers[1] = object()       # unlocked write
        with self._lock:
            sock = socket.create_connection(("h", 1))  # blocking w/ lock

    def alive(self, q):
        with self._lock:               # peers IS lock-guarded elsewhere
            return q in self.peers
'''

CONC_CLEAN = '''
import threading

class Transport:
    def __init__(self):
        self._lock = threading.Lock()
        self.peers = {}

    def start(self):
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        with self._lock:
            self.peers[1] = object()   # locked write
        conns = None
        with self._lock:
            conns = list(self.peers.values())
        for c in conns:
            c.flush()                  # blocking work outside the lock
'''


def test_concurrency_fires():
    vs = lint_src("minpaxos_tpu/runtime/transport.py", CONC_BAD,
                  "concurrency")
    msgs = "\n".join(v.msg for v in vs)
    assert "without holding the lock" in msgs
    assert "blocking call `create_connection`" in msgs


def test_concurrency_quiet_on_clean_idiom():
    assert lint_src("minpaxos_tpu/runtime/transport.py", CONC_CLEAN,
                    "concurrency") == []


def test_concurrency_constructor_exempt():
    # __init__ writes before any thread exists: not a race
    src = CONC_BAD.replace("self.peers[1] = object()       # unlocked write",
                           "pass")
    vs = lint_src("minpaxos_tpu/runtime/transport.py", src, "concurrency")
    assert all("without holding the lock" not in v.msg for v in vs), vs


def test_concurrency_out_of_scope_file_ignored():
    # replica.py is single-owner by design; the lock-discipline checks
    # scope to transport/master/cli (replica.py gets the donated-state
    # check instead — below)
    assert lint_src("minpaxos_tpu/runtime/replica.py", CONC_BAD,
                    "concurrency") == []


# coalescer cv discipline (ISSUE 15): the ingress coalescer's wakeup
# condition variable counts as a lock for the blocking-under-lock rule
# — a socket read while holding self._cv would stall every client
# reader's enqueue behind one peer's TCP timeout. cv.wait itself is
# exempt (it releases the lock while parked).

CV_BAD = '''
import threading, socket

class IngressCoalescer:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def get(self, sock):
        with self._cv:
            data = sock.recv(4096)     # blocking read under the cv
            self._items.append(data)
            return self._items.pop(0)
'''

CV_CLEAN = '''
import threading, socket

class IngressCoalescer:
    def __init__(self):
        self._cv = threading.Condition()
        self._items = []

    def put(self, item):
        with self._cv:
            self._items.append(item)
            self._cv.notify()          # kick: O(1) under the cv

    def get(self, sock):
        with self._cv:
            while not self._items:
                self._cv.wait(0.05)    # releases the cv while parked
            item = self._items.pop(0)
        data = sock.recv(4096)         # blocking work outside the cv
        return item, data
'''


def test_concurrency_cv_blocking_read_fires():
    vs = lint_src("minpaxos_tpu/runtime/batches.py", CV_BAD,
                  "concurrency")
    msgs = "\n".join(v.msg for v in vs)
    assert "blocking call `recv` while holding a lock" in msgs, vs


def test_concurrency_cv_clean_coalescer_quiet():
    assert lint_src("minpaxos_tpu/runtime/batches.py", CV_CLEAN,
                    "concurrency") == []


def test_concurrency_real_coalescer_clean():
    # the shipped coalescer must satisfy its own lint: nothing
    # blocking under self._cv in runtime/batches.py
    src = (Path(__file__).resolve().parents[1]
           / "minpaxos_tpu/runtime/batches.py").read_text()
    vs = lint_src("minpaxos_tpu/runtime/batches.py", src, "concurrency")
    assert vs == [], vs


# donated-state: self.state's buffers are donated into the jitted step;
# only the protocol thread (_run and what it calls) may touch them —
# the pipelined tick loop doubles the in-flight references, so the
# single-owner convention is machine-checked, not just documented.

STATE_BAD = '''
import threading

class ReplicaServer:
    def start(self):
        threading.Thread(target=self._run, daemon=True).start()
        threading.Thread(target=self._control_loop, daemon=True).start()

    def _run(self):
        while True:
            self._tick()

    def _tick(self):
        self.state = self.step(self.state)   # owner thread: fine

    def _control_loop(self):
        self._answer()

    def _answer(self):
        return int(self.state.committed_upto)  # foreign-thread read
'''


def test_concurrency_donated_state_read_fires():
    vs = lint_src("minpaxos_tpu/runtime/replica.py", STATE_BAD,
                  "concurrency")
    assert len(vs) == 1, vs
    assert "`self.state` touched in `_answer`" in vs[0].msg
    assert "donated" in vs[0].msg


def test_concurrency_donated_state_owner_thread_ok():
    # the same access pattern minus the control-thread read is clean:
    # _run/_tick own the state (and methods no thread reaches, like a
    # stop() on the main thread, are exempt)
    src = STATE_BAD.replace(
        "        return int(self.state.committed_upto)"
        "  # foreign-thread read",
        "        return dict(self.snapshot)")
    assert lint_src("minpaxos_tpu/runtime/replica.py", src,
                    "concurrency") == []


def test_concurrency_donated_state_scoped_to_replica():
    # the check keys on the replica runtime's donation contract; the
    # same shape elsewhere (no donated buffers) must stay quiet
    assert lint_src("minpaxos_tpu/runtime/transport.py", STATE_BAD,
                    "concurrency") == []


# --------------------------------------------------------- wall-honesty


def test_wall_honesty_fires():
    src = '''
def step(cfg, state, inbox, tick_inc=1):
    return state._replace(stall_ticks=state.stall_ticks + 1)
'''
    vs = lint_src("minpaxos_tpu/models/m.py", src, "wall-honesty")
    assert len(vs) == 1 and "stall_ticks" in vs[0].msg


def test_wall_honesty_quiet_on_clean_idiom():
    src = '''
import jax.numpy as jnp

def step(cfg, state, inbox, tick_inc=1):
    return state._replace(
        tick=state.tick + tick_inc,
        stall_ticks=jnp.where(state.crt_inst > 0,
                              state.stall_ticks + tick_inc, 0))

def thresholds(cfg, state):
    # reads and config comparisons are not updates
    return (state.stall_ticks >= cfg.noop_delay,
            (4 + 2) * cfg.noop_delay)
'''
    assert lint_src("minpaxos_tpu/models/m.py", src, "wall-honesty") == []


def test_wall_honesty_scoped_to_models():
    src = "x = state.stall_ticks + 1\n"
    assert lint_src("minpaxos_tpu/runtime/r.py", src, "wall-honesty") == []


def test_wall_honesty_registry_advance_fires_in_runtime():
    """The paxmon extension: a tick-named registry counter advanced by
    a literal in runtime/ counts fused device substeps as wall ticks —
    must carry tick_inc (obs/metrics.py wall-honesty contract)."""
    src = '''
class R:
    def _tick(self, k):
        self._c_ticks.inc(1)
'''
    vs = lint_src("minpaxos_tpu/runtime/rep.py", src, "wall-honesty")
    assert len(vs) == 1 and "registry counter" in vs[0].msg, vs
    assert "_c_ticks" in vs[0].msg


def test_wall_honesty_registry_metric_name_string_fires():
    # the counter-ish identity can live in the metric NAME string
    src = 'def f(reg, n):\n    reg.counter("stall_ticks").inc(n)\n'
    vs = lint_src("minpaxos_tpu/models/m2.py", src, "wall-honesty")
    assert len(vs) == 1 and "stall_ticks" in vs[0].msg, vs


def test_wall_honesty_registry_advance_clean_idioms():
    """tick_inc-spelled advances and event counters (not tick-named)
    advance freely; suppression clears a deliberate site."""
    src = '''
class R:
    def _tick(self, k, n_rows):
        tick_inc = 1
        self._c_ticks.inc(tick_inc)
        self._c_fused_substeps.inc(k)       # substeps, not wall ticks
        self._c_proposals.inc(n_rows)
        self.metrics.counter("idle_skips").inc(1)
        self._pending.add((1, 2))           # a set, not a counter
'''
    assert lint_src("minpaxos_tpu/runtime/rep.py", src,
                    "wall-honesty") == []
    sup = ('def f(reg):\n'
           '    reg.counter("stall_ticks").inc(2)'
           '  # paxlint: disable=wall-honesty -- replay\n')
    assert lint_src("minpaxos_tpu/models/m2.py", sup,
                    "wall-honesty") == []


# --------------------------------------------------------- broad-except


def test_broad_except_fires_and_reraise_exempt():
    src = '''
def f():
    try:
        g()
    except Exception:
        pass

def h():
    try:
        g()
    except Exception as e:
        raise RuntimeError("wrapped") from e
'''
    vs = lint_src("minpaxos_tpu/runtime/x.py", src, "broad-except")
    assert len(vs) == 1 and vs[0].line == 5, vs


def test_broad_except_quiet_on_narrow_handlers():
    src = '''
def f():
    try:
        g()
    except (OSError, ValueError):
        pass
'''
    assert lint_src("minpaxos_tpu/runtime/x.py", src, "broad-except") == []


# ---------------------------------------------------- quorum-certificate


QUORUM_BAD = '''
class FlexCfg:
    @property
    def q1(self):
        return (self.n_replicas + 1) // 2

    @property
    def q2(self):
        return (self.n_replicas + 1) // 2
'''

QUORUM_CLEAN = '''
class Cfg:
    @property
    def majority(self):
        return self.n_replicas // 2 + 1


def step(cfg, state, n_votes):
    majority = cfg.majority          # delegation: certified at source
    return n_votes >= majority
'''


def test_quorum_certificate_rejects_non_intersecting_pair():
    vs = lint_src("minpaxos_tpu/models/flex.py", QUORUM_BAD,
                  "quorum-certificate")
    assert any("NON-INTERSECTING" in v.msg for v in vs), vs
    # the refutation names a concrete disjoint witness pair
    assert any("disjoint quorums" in v.msg for v in vs), vs


def test_quorum_certificate_quiet_on_certified_majority():
    assert lint_src("minpaxos_tpu/models/ok.py", QUORUM_CLEAN,
                    "quorum-certificate") == []


def test_quorum_certificate_flags_uncovered_and_literal():
    # intersecting but absent from the ledger: must be appended.
    # (q = n itself became a certified formula when quorum_fast landed,
    # so probe with ceil(3n/4) — fast-paxos-ish, intersects with
    # itself, but (4, 4) at n=5 is not a ledger row)
    src = ("class C:\n    @property\n    def quorum(self):\n"
           "        return (self.n_replicas * 3 + 3) // 4\n")
    vs = lint_src("minpaxos_tpu/models/u.py", src, "quorum-certificate")
    assert any("not covered by a certified entry" in v.msg for v in vs), vs
    # fixed literal compared against a vote count
    lit = "def f(state):\n    return state.n_votes >= 1\n"
    vs = lint_src("minpaxos_tpu/ops/l.py", lit, "quorum-certificate")
    assert any("fixed literal" in v.msg for v in vs), vs


def test_quorum_certificate_unrecognizable_formula_flagged():
    src = ("class C:\n    @property\n    def majority(self):\n"
           "        return mystery()\n")
    vs = lint_src("minpaxos_tpu/models/m.py", src, "quorum-certificate")
    assert any("cannot certify" in v.msg for v in vs), vs


def test_quorum_certificate_scoped_to_device_packages():
    # the same bad pair outside ops//models/ is out of scope
    assert lint_src("minpaxos_tpu/runtime/flex.py", QUORUM_BAD,
                    "quorum-certificate") == []


# ------------------------------------------------------------ lock-order


LOCK_CYCLE = '''
import threading

class Transport:
    def __init__(self):
        self._peers_lock = threading.Lock()
        self._stats_lock = threading.Lock()

    def send(self):
        with self._peers_lock:
            with self._stats_lock:
                pass

    def report(self):
        with self._stats_lock:
            self._count()

    def _count(self):
        with self._peers_lock:
            pass
'''

LOCK_ORDERED = '''
import threading

class Transport:
    def __init__(self):
        self._peers_lock = threading.Lock()
        self._stats_lock = threading.Lock()

    def send(self):
        with self._peers_lock:
            with self._stats_lock:
                pass

    def report(self):
        with self._peers_lock:          # same order everywhere
            with self._stats_lock:
                self._count()

    def _count(self):
        pass
'''

LOCK_CROSS = '''
import threading

class Transport:
    def __init__(self, master):
        self._lock = threading.Lock()
        self.master = Master()

    def send(self):
        with self._lock:
            pass

    def deliver(self):
        with self._lock:
            self.master.on_frame()

class Master:
    def __init__(self):
        self._lock = threading.Lock()
        self.transport = Transport(self)

    def on_frame(self):
        with self._lock:
            pass

    def fanout(self):
        with self._lock:
            self.transport.send()
'''


def test_lock_order_cycle_fires():
    vs = lint_src("minpaxos_tpu/runtime/transport.py", LOCK_CYCLE,
                  "lock-order")
    assert len(vs) == 1 and "lock-order cycle" in vs[0].msg, vs
    assert "_peers_lock" in vs[0].msg and "_stats_lock" in vs[0].msg


def test_lock_order_quiet_on_consistent_order():
    assert lint_src("minpaxos_tpu/runtime/transport.py", LOCK_ORDERED,
                    "lock-order") == []


def test_lock_order_cross_class_cycle_fires():
    """The production shape: master holds its lock fanning out through
    transport methods that take the transport lock, while a transport
    read loop holds its lock calling back into the master."""
    vs = lint_src("minpaxos_tpu/runtime/master.py", LOCK_CROSS,
                  "lock-order")
    assert len(vs) == 1, vs
    assert "Transport._lock" in vs[0].msg and "Master._lock" in vs[0].msg


def test_lock_order_nested_inside_branches_tracked():
    # the with->if->with nesting must still build the edge
    src = LOCK_CYCLE.replace(
        "        with self._stats_lock:\n            self._count()",
        "        with self._stats_lock:\n"
        "            if True:\n                self._count()")
    vs = lint_src("minpaxos_tpu/runtime/transport.py", src, "lock-order")
    assert len(vs) == 1, vs


def test_lock_order_scoped_to_runtime():
    assert lint_src("minpaxos_tpu/cli/x.py", LOCK_CYCLE, "lock-order") == []


def test_lock_order_sees_through_match_statements():
    """Code-review regression: locks taken inside `match` case arms
    (whose bodies live in match_case objects, not plain stmt bodies)
    still build graph edges."""
    src = LOCK_CYCLE.replace(
        "    def report(self):\n        with self._stats_lock:\n"
        "            self._count()",
        "    def report(self, kind):\n        match kind:\n"
        "            case 1:\n                with self._stats_lock:\n"
        "                    self._count()")
    vs = lint_src("minpaxos_tpu/runtime/transport.py", src, "lock-order")
    assert len(vs) == 1 and "lock-order cycle" in vs[0].msg, vs


def test_quorum_certificate_zero_literal_is_emptiness_not_quorum():
    # `> 0` / `>= 0` against a vote count is an emptiness guard; a
    # quorum size is always >= 1, so zero never flags
    src = ("def f(state):\n"
           "    a = state.n_votes > 0\n"
           "    b = 0 < state.pv_cnt\n"
           "    return a and b\n")
    assert lint_src("minpaxos_tpu/ops/z.py", src,
                    "quorum-certificate") == []


def test_lock_order_duplicate_class_names_both_analyzed():
    """Code-review regression: two runtime/ files each defining a class
    with the SAME name must not shadow each other — a cycle inside
    either one still fires, and the report qualifies the node names so
    the two classes' locks don't merge into phantom edges."""
    clean = LOCK_ORDERED  # class Transport, consistent order
    vs = run_passes(Project({
        "minpaxos_tpu/runtime/a.py": clean,
        "minpaxos_tpu/runtime/b.py": LOCK_CYCLE,  # also class Transport
    }), ("lock-order",))
    assert len(vs) == 1 and vs[0].path.endswith("b.py"), vs
    assert "b:Transport" in vs[0].msg, vs  # stem-qualified node label


# --------------------------------------------- single-parse / shared graph


def test_single_parse_and_one_graph_build_across_all_passes():
    """The lint perf contract: one ast.parse per file, one structural
    module walk per device file, ONE jit call-graph fixed point per
    invocation — no matter how many passes consult it (trace-hazard
    and recompile-hazard both do)."""
    from minpaxos_tpu.analysis.jitgraph import DEVICE_PREFIXES

    project = Project.from_root(REPO)
    run_passes(project)  # every registered pass
    n_device = sum(1 for p in project.files if p.startswith(DEVICE_PREFIXES))
    assert project.stats["ast_parses"] == len(project.files)
    assert project.stats["module_walks"] == n_device
    assert project.stats["graph_builds"] == 1, project.stats
    # a second full run re-uses everything — no new parses, no rebuild
    run_passes(project)
    assert project.stats["ast_parses"] == len(project.files)
    assert project.stats["module_walks"] == n_device
    assert project.stats["graph_builds"] == 1


def test_passes_share_one_prefix_scope():
    from minpaxos_tpu.analysis import recompile_hazard, trace_hazard
    from minpaxos_tpu.analysis.jitgraph import DEVICE_PREFIXES

    assert trace_hazard.GRAPH_PREFIXES is DEVICE_PREFIXES
    assert recompile_hazard.PREFIXES is DEVICE_PREFIXES


# ----------------------------------------------------- framework pieces


def test_suppression_comment_line_covers_next_code_line():
    src = '''
def f():
    try:
        g()
    # paxlint: disable=broad-except -- best-effort by design
    except Exception:
        pass
'''
    assert lint_src("minpaxos_tpu/runtime/x.py", src, "broad-except") == []


def test_suppression_comment_line_skips_blank_lines():
    src = '''
import numpy as np

def f(x):
    # paxlint: disable=trace-hazard -- host helper

    return np.asarray(x)
'''
    assert lint_src("minpaxos_tpu/ops/h.py", src, "trace-hazard") == []


def test_suppression_disable_file_works_anywhere():
    src = ("def f():\n    pass\n" * 8
           + "# paxlint: disable-file=broad-except\n"
           + "def g():\n    try:\n        f()\n"
             "    except Exception:\n        pass\n")
    assert lint_src("minpaxos_tpu/runtime/x.py", src, "broad-except") == []


def test_trace_hazard_item_on_static_config_ok():
    src = '''
import jax

@jax.jit
def step(cfg, state):
    n = cfg.table.item()     # static config read: trace-time, fine
    return state + n
'''
    assert lint_src("minpaxos_tpu/models/ok2.py", src, "trace-hazard") == []


def test_concurrency_manual_acquire_release_not_a_race():
    src = CONC_BAD.replace(
        "        self.peers[1] = object()       # unlocked write",
        "        self._lock.acquire(timeout=1.0)\n"
        "        try:\n"
        "            self.peers[1] = object()\n"
        "        finally:\n"
        "            self._lock.release()")
    vs = lint_src("minpaxos_tpu/runtime/transport.py", src, "concurrency")
    assert all("without holding the lock" not in v.msg for v in vs), vs


def test_parse_error_is_a_violation():
    vs = run_passes(Project({"minpaxos_tpu/ops/bad.py": "def f(:\n"}))
    assert any(v.rule == "parse" for v in vs), vs


def test_unknown_rule_raises():
    try:
        run_passes(Project({}), ("no-such-rule",))
    except KeyError as e:
        assert "no-such-rule" in str(e)
    else:
        raise AssertionError("expected KeyError")


# ------------------------------------------------------- the real tree


def test_whole_repo_is_clean():
    """The acceptance gate: the shipped tree has zero violations (true
    positives were fixed; deliberate host-side/best-effort sites carry
    visible suppressions)."""
    project = Project.from_root(REPO)
    assert run_passes(project) == []


def test_cli_exit_codes_and_json(tmp_path):
    """tools/lint.py: exit 0 + --json on the clean tree; nonzero on a
    tree with a seeded violation (the run_tier1.sh contract)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "tools/lint.py"), "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["clean"] is True and payload["violations"] == []

    bad = tmp_path / "minpaxos_tpu" / "models"
    bad.mkdir(parents=True)
    (bad / "seeded.py").write_text(
        "def step(state, tick_inc):\n"
        "    return state.stall_ticks + 1\n")
    out = subprocess.run(
        [sys.executable, str(REPO / "tools/lint.py"),
         "--root", str(tmp_path), "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["counts"].get("wall-honesty") == 1, payload


# ------------------------------------------------------- resident-loop


RESIDENT_BAD = '''
import numpy as np
import jax

def helper(state):
    return np.asarray(state)       # device -> host pull, one hop away

# paxlint: resident-loop
def run_resident_dispatch(state):
    y = helper(state)              # transitive: flagged in helper
    jax.block_until_ready(state)   # blocks the measured loop
    n = state.sum().item()         # host sync
    return y, n
'''

RESIDENT_CLEAN = '''
import functools

import jax
import jax.numpy as jnp

def kernel(state):
    k = int(7)                     # literal coercion: not a readback
    return jnp.where(state > 0, state, -state) + k

# paxlint: resident-loop
def run_resident_dispatch(state):
    step = functools.partial(kernel)
    out = jax.vmap(step)(state)    # bare-reference edge, still clean
    return out

def host_tool(x):
    import numpy as np
    return np.asarray(x)           # unmarked host code may sync freely
'''


def test_resident_loop_fires_on_seeded_violations():
    vs = lint_src("minpaxos_tpu/parallel/fx.py", RESIDENT_BAD,
                  "resident-loop")
    msgs = "\n".join(v.msg for v in vs)
    assert len(vs) == 3, vs
    assert any(v.path.endswith("fx.py") and v.line == 6 for v in vs), \
        "np.asarray must be flagged in the REACHED helper, not the root"
    for needle in ("np.asarray", "block_until_ready", ".item()"):
        assert needle in msgs, f"missing {needle}: {msgs}"


def test_resident_loop_quiet_on_clean_idiom_and_unmarked_host_code():
    assert lint_src("minpaxos_tpu/parallel/ok.py", RESIDENT_CLEAN,
                    "resident-loop") == []


def test_resident_loop_scalar_readback_needs_suppression():
    """int()/float() in a MARKED dispatch wrapper is a scalar readback
    and must carry the sanctioning suppression; with it, clean."""
    src = '''
# paxlint: resident-loop
def run_resident_dispatch(committed):
    return int(committed)
'''
    vs = lint_src("minpaxos_tpu/parallel/rb.py", src, "resident-loop")
    assert len(vs) == 1 and "scalar readback" in vs[0].msg
    ok = src.replace(
        "return int(committed)",
        "return int(committed)  # paxlint: disable=resident-loop -- ok")
    assert lint_src("minpaxos_tpu/parallel/rb.py", ok,
                    "resident-loop") == []


def test_resident_loop_follows_cross_module_and_method_edges():
    """The real topology: a marked METHOD calling a jitted module
    function in another module that hides the sync."""
    kernel = '''
import numpy as np

def fused_dispatch(state):
    return np.asarray(state)
'''
    wrapper = '''
from minpaxos_tpu.ops.fused import fused_dispatch

class Cluster:
    # paxlint: resident-loop
    def run_resident(self, k):
        return fused_dispatch(self.ss)
'''
    vs = run_passes(Project({
        "minpaxos_tpu/ops/fused.py": kernel,
        "minpaxos_tpu/parallel/wrap.py": wrapper,
    }), ("resident-loop",))
    assert len(vs) == 1 and vs[0].path.endswith("fused.py"), vs
    assert "run_resident" in vs[0].msg  # names the responsible root


def test_resident_loop_flags_mid_window_telemetry_readback():
    """The paxray discipline (ISSUE 9): the telemetry ring's readback
    (np.asarray of the device buffer) is post-window host code — a
    call of it FROM the marked dispatch root ("just peeking" at the
    ring between measured dispatches) must be flagged through the
    self-method edge; the unmarked post-window reader alone is
    clean."""
    peeking = '''
import numpy as np

class Cluster:
    # paxlint: resident-loop
    def run_resident(self, k):
        rows = self.resident_telemetry()   # mid-window peek: a sync
        return rows

    def resident_telemetry(self):
        return np.asarray(self._telemetry)
'''
    vs = lint_src("minpaxos_tpu/parallel/peek.py", peeking,
                  "resident-loop")
    assert len(vs) == 1 and "np.asarray" in vs[0].msg, vs
    assert "run_resident" in vs[0].msg  # names the responsible root
    disciplined = peeking.replace(
        "        rows = self.resident_telemetry()   # mid-window peek: a sync\n"
        "        return rows", "        return 0")
    assert lint_src("minpaxos_tpu/parallel/peek.py", disciplined,
                    "resident-loop") == []


def test_resident_loop_real_suppression_is_load_bearing():
    """The ONE sanctioned per-dispatch scalar readback in the real
    tree (ShardedCluster.run_resident) is actually guarded: stripping
    its suppression must produce exactly the int() readback
    violations, nothing else."""
    files = {p: (REPO / p).read_text() for p in (
        "minpaxos_tpu/parallel/sharded.py",
        "minpaxos_tpu/ops/workload.py",
        "minpaxos_tpu/models/cluster.py",
        "minpaxos_tpu/models/minpaxos.py",
    )}
    marker = "# paxlint: disable=resident-loop -- sanctioned scalar readback"
    assert marker in files["minpaxos_tpu/parallel/sharded.py"]
    assert run_passes(Project(files), ("resident-loop",)) == []
    files["minpaxos_tpu/parallel/sharded.py"] = files[
        "minpaxos_tpu/parallel/sharded.py"].replace(marker, "#")
    vs = run_passes(Project(files), ("resident-loop",))
    assert vs and all(v.rule == "resident-loop"
                      and "scalar readback" in v.msg for v in vs), vs


# ----------------------------------------------------------- spec-sync


SPEC_MINI = '''
ABSTRACT_ACTIONS = ("Phase1a", "Phase1b", "Phase2a", "Phase2b",
                    "Commit", "Skip", "Stutter")
MSGKIND_ACTIONS = {
    "PREPARE": ("Phase1a", "Phase1b"),
    "ACCEPT": ("Phase2a", "Phase2b"),
}
'''

SPEC_KERNEL = '''
def step(kind, MsgKind):
    p = kind == int(MsgKind.PREPARE)
    a = kind == int(MsgKind.ACCEPT)
    return p, a
'''

SPEC_SYNC_BAD = '''
from minpaxos_tpu.wire.messages import MsgKind

def step(kind):
    return kind == int(MsgKind.RECONF)
'''


def lint_spec_pair(kernel_src, spec_src=SPEC_MINI):
    return run_passes(Project({
        "minpaxos_tpu/verify/spec.py": spec_src,
        "minpaxos_tpu/models/kernel.py": kernel_src,
    }), ("spec-sync",))


def test_spec_sync_quiet_when_table_matches_kernel():
    assert lint_spec_pair(SPEC_KERNEL) == []


def test_spec_sync_flags_unmapped_kernel_kind():
    src = SPEC_KERNEL.replace(
        "return p, a",
        "r = kind == int(MsgKind.RECONF)\n"
        "    r2 = kind == int(MsgKind.RECONF)  # same kind: one report\n"
        "    return p, a, r, r2")
    vs = lint_spec_pair(src)
    assert len(vs) == 1 and vs[0].rule == "spec-sync", vs
    assert vs[0].path.endswith("kernel.py")
    assert "MsgKind.RECONF" in vs[0].msg and "MSGKIND_ACTIONS" in vs[0].msg


def test_spec_sync_flags_stale_table_entry():
    vs = lint_spec_pair("def step(kind, MsgKind):\n"
                        "    return kind == int(MsgKind.PREPARE)\n")
    assert len(vs) == 1 and "stale" in vs[0].msg, vs
    assert "'ACCEPT'" in vs[0].msg and vs[0].path.endswith("spec.py")


def test_spec_sync_flags_unknown_abstract_action():
    spec = SPEC_MINI.replace('"ACCEPT": ("Phase2a", "Phase2b"),',
                             '"ACCEPT": ("Teleport",),')
    vs = lint_spec_pair(SPEC_KERNEL, spec)
    assert len(vs) == 1 and "Teleport" in vs[0].msg, vs
    assert vs[0].path.endswith("spec.py")


def test_spec_sync_table_must_stay_pure_literal():
    spec = ('ABSTRACT_ACTIONS = ("Phase1a",)\n'
            'MSGKIND_ACTIONS = dict(PREPARE=("Phase1a",))\n')
    vs = lint_spec_pair("def step(kind, MsgKind):\n"
                        "    return kind == int(MsgKind.PREPARE)\n", spec)
    assert len(vs) == 1 and "pure" in vs[0].msg and "literal" in vs[0].msg


def test_spec_sync_missing_table_is_a_violation():
    vs = lint_spec_pair(SPEC_KERNEL, 'ABSTRACT_ACTIONS = ("Phase1a",)\n')
    assert len(vs) == 1 and "MSGKIND_ACTIONS" in vs[0].msg, vs


def test_spec_sync_host_side_cluster_exempt():
    """models/cluster.py routes client replies (environment outputs,
    not consensus transitions) — its MsgKind compares are out of
    scope by design."""
    vs = run_passes(Project({
        "minpaxos_tpu/verify/spec.py": SPEC_MINI,
        "minpaxos_tpu/models/kernel.py": SPEC_KERNEL,
        "minpaxos_tpu/models/cluster.py":
            "def route(kind, MsgKind):\n"
            "    return kind == int(MsgKind.PROPOSE_REPLY)\n",
    }), ("spec-sync",))
    assert vs == []


def test_spec_sync_silent_without_both_sides():
    """Fixture projects that carry only kernels or only the spec have
    nothing to sync (keeps every OTHER rule's fixtures quiet)."""
    assert run_passes(Project(
        {"minpaxos_tpu/models/kernel.py": SPEC_SYNC_BAD},
        ), ("spec-sync",)) == []
    assert run_passes(Project(
        {"minpaxos_tpu/verify/spec.py": SPEC_MINI},
        ), ("spec-sync",)) == []


def test_spec_sync_real_table_is_load_bearing():
    """The real tree is clean, and deleting one real table entry fires
    exactly the unmapped-kind violation for that kind — the pass is
    reading the actual correspondence, not rubber-stamping."""
    files = {p: (REPO / p).read_text() for p in (
        "minpaxos_tpu/verify/spec.py",
        "minpaxos_tpu/models/minpaxos.py",
        "minpaxos_tpu/models/mencius.py",
        "minpaxos_tpu/models/cluster.py",
    )}
    assert run_passes(Project(files), ("spec-sync",)) == []
    files["minpaxos_tpu/verify/spec.py"] = files[
        "minpaxos_tpu/verify/spec.py"].replace('    "SKIP": ("Skip",),\n',
                                               "")
    vs = run_passes(Project(files), ("spec-sync",))
    assert vs and all(v.rule == "spec-sync" for v in vs), vs
    assert any("MsgKind.SKIP" in v.msg
               and v.path.endswith("mencius.py") for v in vs), vs


_CLI_SEEDS = {
    "trace-hazard": ("minpaxos_tpu/models/seed.py", TRACE_BAD),
    "recompile-hazard": ("minpaxos_tpu/ops/seed.py",
                         "def f(x, buf=[]):\n    return buf\n"),
    "wire-contract": ("minpaxos_tpu/wire/messages.py", None),  # drifted
    "concurrency": ("minpaxos_tpu/runtime/transport.py", CONC_BAD),
    "wall-honesty": ("minpaxos_tpu/models/seed.py",
                     "def step(state, tick_inc):\n"
                     "    return state.stall_ticks + 1\n"),
    "broad-except": ("minpaxos_tpu/utils/seed.py",
                     "def f():\n    try:\n        g()\n"
                     "    except Exception:\n        pass\n"),
    "quorum-certificate": ("minpaxos_tpu/models/flex.py", QUORUM_BAD),
    "lock-order": ("minpaxos_tpu/runtime/transport.py", LOCK_CYCLE),
    "resident-loop": ("minpaxos_tpu/parallel/seed.py", RESIDENT_BAD),
    "spec-sync": ("minpaxos_tpu/models/seed.py", SPEC_SYNC_BAD),
}


@pytest.mark.parametrize("rule", sorted(_CLI_SEEDS))
def test_cli_nonzero_on_each_seeded_rule(tmp_path, rule):
    """Acceptance: tools/lint.py exits nonzero on a seeded violation
    of EVERY rule, and attributes it to that rule."""
    rel, src = _CLI_SEEDS[rule]
    if src is None:  # wire drift: real registry with SKIP renumbered
        src = (REPO / rel).read_text().replace("SKIP = 28", "SKIP = 24")
    dst = tmp_path / rel
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(src)
    if rule == "spec-sync":  # needs the real table alongside the seed
        spec_rel = "minpaxos_tpu/verify/spec.py"
        spec_dst = tmp_path / spec_rel
        spec_dst.parent.mkdir(parents=True, exist_ok=True)
        spec_dst.write_text((REPO / spec_rel).read_text())
    out = subprocess.run(
        [sys.executable, str(REPO / "tools/lint.py"), "--root",
         str(tmp_path), "--rules", rule, "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 1, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["counts"].get(rule, 0) >= 1, payload
