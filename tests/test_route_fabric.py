"""Byte-equality pin of the segmented routing fabric (PR 11).

``_route_segmented`` (one segment-prefix-sum + searchsorted winner,
ops/segscatter.py) must reproduce the original dense fabric
(``_route``) BYTE-FOR-BYTE: same rows, same per-destination order,
same overflow-drop semantics — ack-run compression and winner
tie-breaks read row order, so "equivalent but reordered" is not good
enough. The old fabric stays in-tree behind
``route_fabric="dense"`` exactly so this pin owns the rewrite; the
golden kernel fixtures (tests/test_kernel_golden.py) extend the pin
through whole multi-protocol cluster scenarios.

Also here: the inbox-compaction step (``compact_inbox``) — NOT
byte-equal at the frame level by design (padding gaps vanish, ack
runs may merge) — must leave the protocol STATE byte-identical when
capacity covers occupancy, across all three protocols.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minpaxos_tpu.models.cluster import (
    Cluster,
    _route,
    _route_segmented,
)
from minpaxos_tpu.models.minpaxos import MinPaxosConfig, MsgBatch
from minpaxos_tpu.wire.messages import MsgKind, Op

R = 5


def _mk_outboxes(m, n_live, seed, bc_frac=0.5, uni_frac=0.3):
    """Random [R, m] outboxes: n_live live rows each, dst mixing
    broadcast (-1), unicast (0..R-1, self included), client (-2)."""
    rng = np.random.default_rng(seed)
    cols = {f: np.zeros((R, m), np.int32) for f in MsgBatch._fields}
    dst = np.full((R, m), -1, np.int32)
    for r in range(R):
        # scatter live rows across positions, not only a prefix: the
        # fabric must compact arbitrary gap patterns
        pos = np.sort(rng.choice(m, size=n_live, replace=False))
        cols["kind"][r, pos] = rng.integers(1, 10, n_live)
        for f in MsgBatch._fields:
            if f != "kind":
                cols[f][r, pos] = rng.integers(-5, 1 << 20, n_live)
        u = rng.random(n_live)
        dst[r, pos] = np.where(
            u < bc_frac, -1,
            np.where(u < bc_frac + uni_frac, rng.integers(0, R, n_live), -2))
    msgs = MsgBatch(**{f: jnp.asarray(v) for f, v in cols.items()})
    return msgs, jnp.asarray(dst)


def _assert_tree_equal(a, b, ctx=""):
    for la, lb in zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb),
                                      err_msg=ctx)


@pytest.mark.parametrize("m,n_live,capacity", [
    (32, 16, 32),    # ordinary mix
    (32, 32, 16),    # heavy overflow: fan-out far beyond capacity
    (64, 3, 64),     # sparse
    (16, 16, 128),   # capacity beyond pool: all rows land, tail empty
])
def test_segmented_matches_dense(m, n_live, capacity):
    cfg = MinPaxosConfig(n_replicas=R, window=64, inbox=capacity)
    for seed in range(4):
        msgs, dst = _mk_outboxes(m, n_live, seed)
        alive = jnp.ones(R, bool)
        _assert_tree_equal(
            _route(cfg, msgs, dst, alive, capacity),
            _route_segmented(cfg, msgs, dst, alive, capacity),
            ctx=f"seed={seed}")


def test_segmented_matches_dense_dead_replicas():
    """Dead sources' rows drop; dead destinations receive zeroed
    inboxes — every alive-mask combination at N=5 (jitted once,
    alive as a runtime arg: 32 masks, 2 compiles)."""
    import jax as _jax

    cfg = MinPaxosConfig(n_replicas=R, window=64, inbox=24)
    msgs, dst = _mk_outboxes(24, 18, seed=3)
    dense = _jax.jit(lambda a: _route(cfg, msgs, dst, a, 24))
    seg = _jax.jit(lambda a: _route_segmented(cfg, msgs, dst, a, 24))
    for mask in range(1 << R):
        alive = jnp.asarray([(mask >> i) & 1 == 1 for i in range(R)])
        _assert_tree_equal(dense(alive), seg(alive),
                           ctx=f"alive={mask:05b}")


def test_broadcast_unicast_client_semantics():
    """Hand-built outbox: broadcast reaches all OTHER live replicas,
    unicast exactly its target, client-bound (-2) rows never route,
    and per-destination order is pooled-row order."""
    cfg = MinPaxosConfig(n_replicas=R, window=64, inbox=8)
    cols = {f: np.zeros((R, 4), np.int32) for f in MsgBatch._fields}
    dst = np.full((R, 4), -2, np.int32)
    # replica 0: row0 broadcast, row1 unicast->3, row2 client, row3 pad
    cols["kind"][0, :3] = [int(MsgKind.ACCEPT), int(MsgKind.PREPARE_REPLY),
                           int(MsgKind.PROPOSE_REPLY)]
    cols["cmd_id"][0, :3] = [100, 101, 102]
    dst[0, :3] = [-1, 3, -2]
    # replica 2: row0 unicast->3 (lands AFTER replica 0's rows), row1
    # unicast->2 (self: dropped)
    cols["kind"][2, :2] = [int(MsgKind.ACCEPT_REPLY), int(MsgKind.COMMIT)]
    cols["cmd_id"][2, :2] = [200, 201]
    dst[2, :2] = [3, 2]
    msgs = MsgBatch(**{f: jnp.asarray(v) for f, v in cols.items()})
    alive = jnp.ones(R, bool)
    got = _route_segmented(cfg, msgs, jnp.asarray(dst), alive, 8)
    kind = np.asarray(got.kind)
    cid = np.asarray(got.cmd_id)
    # replica 0's broadcast reaches 1..4 but not 0
    assert kind[0, 0] == 0
    for d in (1, 2, 4):
        assert kind[d, 0] == int(MsgKind.ACCEPT) and cid[d, 0] == 100
        assert kind[d, 1] == 0  # nothing else routed there
    # replica 3: broadcast first (pooled order), then the two unicasts
    assert list(kind[3, :3]) == [int(MsgKind.ACCEPT),
                                 int(MsgKind.PREPARE_REPLY),
                                 int(MsgKind.ACCEPT_REPLY)]
    assert list(cid[3, :3]) == [100, 101, 200]
    # client-bound + self-unicast rows route nowhere
    assert not (cid == 102).any() and not (cid == 201).any()


def test_overflow_drops_beyond_capacity():
    """More addressed rows than capacity: exactly the first
    ``capacity`` rows (pooled order) land, the rest drop silently."""
    cfg = MinPaxosConfig(n_replicas=R, window=64, inbox=4)
    m = 8
    cols = {f: np.zeros((R, m), np.int32) for f in MsgBatch._fields}
    cols["kind"][0, :] = int(MsgKind.ACCEPT)
    cols["cmd_id"][0, :] = np.arange(m) + 1
    dst = np.full((R, m), -2, np.int32)
    dst[0, :] = 1  # 8 unicasts at capacity 4
    msgs = MsgBatch(**{f: jnp.asarray(v) for f, v in cols.items()})
    alive = jnp.ones(R, bool)
    got = _route_segmented(cfg, msgs, jnp.asarray(dst), alive, 4)
    assert list(np.asarray(got.cmd_id)[1]) == [1, 2, 3, 4]
    _assert_tree_equal(got, _route(cfg, msgs, jnp.asarray(dst), alive, 4))


@pytest.mark.parametrize("protocol", ["minpaxos", "classic", "mencius"])
def test_compaction_state_equivalence(protocol):
    """compact_inbox at adequate capacity: the protocol STATE (and so
    the commit stream) stays byte-identical to the uncompacted run;
    only the inbox frame layout differs. Exercises kill/revive so
    dead-replica zeroing composes with the pack.

    Deliberately reuses test_kernel_golden's exact config + ext width:
    the uncompacted legs then share the golden scenarios' compiled
    ``cluster_step`` (same static cfg, same shapes — one in-process
    jit cache), so this test only pays the 3 compacted-variant
    compiles (tier-1 budget discipline)."""
    from minpaxos_tpu.models.paxos import classic_config

    from tests.test_kernel_golden import _KW

    def build(compact):
        kw = dict(_KW, compact_inbox=compact) if compact else dict(_KW)
        cfg = (classic_config(**kw) if protocol == "classic"
               else MinPaxosConfig(**kw))
        if protocol == "mencius":
            from minpaxos_tpu.models.mencius import MenciusCluster

            return MenciusCluster(cfg, ext_rows=8)
        return Cluster(cfg, ext_rows=8)

    def drive(cl):
        rng = np.random.default_rng(11)
        if protocol != "mencius":
            cl.elect(0)
            cl.step()
            cl.step()
        for i in range(10):
            if i == 4:
                cl.kill(2)
            if i == 7:
                cl.revive(2)
            n = 5
            cl.propose(np.full(n, int(Op.PUT)), rng.integers(0, 30, n),
                       rng.integers(0, 99, n), np.arange(n) + i * 10,
                       client_id=1, to=0)
            cl.step()
        for _ in range(6):
            cl.step()
        return cl

    # compacted capacity 36 < inbox + ext = 40, >= this load's occupancy
    a = drive(build(0))
    b = drive(build(36))
    _assert_tree_equal(a.cs.states, b.cs.states,
                       ctx=f"{protocol}: state diverged under compaction")
    assert a.replies == b.replies
