"""Two-process SPMD: the multihost glue exercised by a REAL
multi-controller run (VERDICT r3 missing #3 — the degenerate
single-process case proves nothing about mesh/addressability).

Two OS processes × 4 virtual CPU devices each join via
jax.distributed; the 8-device 'shard' mesh spans both; each process
runs the identical fused program and asserts commits on its OWN
addressable slice. This is the jax-native analogue of the reference's
N-process TCP deployment (genericsmr.go:125-172) on the throughput
(shard) axis.
"""

import json
import os
import pathlib
import subprocess
import sys

from minpaxos_tpu.utils.netutil import free_ports

REPO = pathlib.Path(__file__).resolve().parent.parent
WORKER = pathlib.Path(__file__).resolve().parent / "_multihost_worker.py"


def test_two_process_spmd_commits_on_both_slices(tmp_path):
    port = free_ports(1)[0]
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = str(REPO)
    procs = []
    outs = []
    for pid in range(2):
        out = tmp_path / f"worker{pid}.json"
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, str(WORKER), str(port), str(pid), str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    recs = []
    for pid, p in enumerate(procs):
        try:
            _, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise AssertionError(f"worker {pid} hung")
        assert p.returncode == 0, (
            f"worker {pid} rc={p.returncode}\n{err.decode()[-2000:]}")
        recs.append(json.loads(outs[pid].read_text()))
    # both processes saw the global 8-device mesh, owned disjoint
    # 4-shard slices, and observed commits on their own slice
    assert all(r["ok"] for r in recs), recs
    assert recs[0]["my_slice"] == [0, 4] and recs[1]["my_slice"] == [4, 8]
