"""Concurrent-client swarm leg (ISSUE 15 CI satellite): a real
in-process TCP cluster driven by ClientSwarm's selector loop — many
concurrent closed-loop sessions multiplexed through the ingress
coalescer, every command acked exactly once.

The ~64-session leg rides tier-1 (the obs_smoke/bench_tcp gate's
in-repo half); the 1k-session leg is `slow`. Neither adds a compiled
variant: the servers run the same step shapes every other distributed
test compiles.
"""

from __future__ import annotations

import time

import pytest

from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.runtime.client import ClientSwarm, gen_workload
from minpaxos_tpu.runtime.master import Master, register_with_master
from minpaxos_tpu.runtime.replica import ReplicaServer, RuntimeFlags
from minpaxos_tpu.utils.netutil import CONTROL_OFFSET, free_ports

SMALL = dict(window=1 << 10, inbox=1024, exec_batch=512, kv_pow2=12,
             catchup_rows=64, recovery_rows=64)


class _Cluster:
    """Master + 3 in-process replicas (test_distributed's harness
    shape, local copy: test modules aren't importable packages)."""

    def __init__(self, tmp_path, n=3):
        self.mport = free_ports(1)[0]
        self.addrs = [("127.0.0.1", p) for p in
                      free_ports(n, sibling_offset=CONTROL_OFFSET)]
        self.master = Master("127.0.0.1", self.mport, n, ping_s=0.3)
        self.master.start()
        for host, port in self.addrs:
            register_with_master(("127.0.0.1", self.mport), host, port,
                                 timeout_s=5.0)
        cfg = MinPaxosConfig(n_replicas=n, **SMALL)
        self.servers = []
        for i in range(n):
            s = ReplicaServer(i, self.addrs, cfg,
                              RuntimeFlags(store_dir=str(tmp_path),
                                           tick_s=0.001))
            s.start()
            self.servers.append(s)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if self.servers[0].snapshot["prepared"]:
                return
            time.sleep(0.05)
        raise AssertionError("leader never prepared")

    def stop(self):
        for s in self.servers:
            s.stop()
        self.master.stop()


def _run_swarm(tmp_path, sessions: int, ops_per_session: int,
               timeout_s: float) -> tuple[dict, _Cluster]:
    c = _Cluster(tmp_path)
    try:
        n = sessions * ops_per_session
        ops, keys, vals = gen_workload(n, key_range=1000, seed=3)
        swarm = ClientSwarm(("127.0.0.1", c.mport), sessions=sessions)
        try:
            res = swarm.run(ops, keys, vals, ops_per_session,
                            timeout_s=timeout_s)
        finally:
            swarm.close()
        # coalescer evidence on the leader: parked-tick-loop wakeups
        # and drained multi-row batches (the counters paxtop's
        # COALESCE column reads)
        stats = c.servers[0].stats
        return {**res, "leader_stats": stats}, c
    except BaseException:
        c.stop()
        raise


def test_swarm_64_sessions_exactly_once(tmp_path):
    res, c = _run_swarm(tmp_path, sessions=64, ops_per_session=4,
                        timeout_s=60.0)
    try:
        assert res["acked"] == res["sent"] == 256, res
        assert res["dead_sessions"] == 0, res
        assert len(res["lat_ms_sorted"]) == 256
        st = res["leader_stats"]
        assert st.get("coalesce_wakeups", 0) > 0, st
        # a 64-way concurrent burst must actually coalesce: some
        # drained batch carried more than one client's rows
        hist = (c.servers[0].metrics.snapshot()
                .get("histograms") or {}).get("coalesce_batch_rows")
        assert hist and hist["count"] > 0, hist
    finally:
        c.stop()


@pytest.mark.slow
def test_swarm_1k_sessions_bounded_queueing(tmp_path):
    """1024 concurrent sessions: overload may engage the admission
    gate (counted rejects + client retransmits), but every command is
    still acked exactly once — bounded queueing, not tail blowup."""
    res, c = _run_swarm(tmp_path, sessions=1024, ops_per_session=2,
                        timeout_s=180.0)
    try:
        assert res["acked"] == res["sent"] == 2048, res
        assert res["dead_sessions"] == 0, res
        st = res["leader_stats"]
        assert st.get("coalesce_wakeups", 0) > 0, st
    finally:
        c.stop()
