"""Randomized fault-schedule safety tests + MC counterexample replays.

Drives the pod-mode cluster through random mixes of proposals, leader
kills, elections, and revivals, then checks the Paxos safety
invariants the TLA+ spec names (EgalitarianPaxos.tla:687-708):

- Consistency: no two replicas disagree on any committed slot's
  command.
- Stability: a slot once committed on a replica never changes there.
- Exactly-once: every successful reply is delivered at most once per
  (client, cmd_id) (the reference's client -check, client.go:279-284).

Liveness is NOT asserted under arbitrary faults (a majority can be
dead); only safety must hold unconditionally.

Plus the paxmc regression harness: every counterexample JSON checked
into tests/fixtures/mc_*.json (model-checker findings — VERIFY.md's
counterexample-replay workflow) replays action-by-action through the
real step functions and must still reproduce its recorded invariant
violation. A finding that stops reproducing means the kernels' failure
mode changed — the fixture must be re-derived or retired explicitly,
never silently.
"""

import json
from pathlib import Path

import numpy as np
import pytest

from minpaxos_tpu.models.cluster import Cluster, tree_slice
from minpaxos_tpu.models.minpaxos import COMMITTED, MinPaxosConfig
from minpaxos_tpu.wire.messages import Op

CFG = MinPaxosConfig(n_replicas=3, window=512, inbox=512, exec_batch=128,
                     kv_pow2=10, catchup_rows=32)

#: every model-checker counterexample checked into the tree replays as
#: a regression case; the glob IS the registry (drop a file in, get a
#: test). parametrize at collection time so each fixture is its own
#: test id.
MC_FIXTURES = sorted(
    (Path(__file__).resolve().parent / "fixtures").glob("mc_*.json"))


@pytest.mark.parametrize(
    "path", MC_FIXTURES or [None],
    ids=[p.stem for p in MC_FIXTURES] or ["no-fixtures"])
def test_mc_counterexample_fixture_replays(path):
    """Each checked-in paxmc counterexample must still reproduce its
    recorded invariant violation when replayed through the REAL step
    functions (deterministic: pure kernels + a pinned action trace)."""
    if path is None:
        pytest.skip("no MC counterexample fixtures checked in "
                    "(harness active — drop tests/fixtures/mc_*.json)")
    from minpaxos_tpu.verify.mc import replay_counterexample

    ce = json.loads(path.read_text())
    reproduced, report = replay_counterexample(ce)
    assert reproduced, (
        f"{path.name}: recorded violation no longer reproduces — "
        f"re-derive the fixture (tools/mc.py --mutant ... --emit-trace) "
        f"or retire it explicitly; final report: {report.to_dict()}")
    # the replayed failure is the same CLASS of violation as recorded
    # (exact strings may drift with numpy reprs; the invariant may not)
    recorded = " ".join(ce["report"]["violations"])
    replayed = " ".join(report.violations)
    for marker in ("DIVERGENCE", "BACKWARD", "never proposed",
                   "REFINEMENT", "LASSO"):
        if marker in recorded:
            assert marker in replayed, (marker, report.violations)


def snapshot_committed(c: Cluster, r: int):
    """Committed slots still resident in the window, keyed by ABSOLUTE
    slot number (windows slide past executed prefixes independently)."""
    st = tree_slice(c.cs.states, r)
    upto = int(np.asarray(st.committed_upto))
    base = int(np.asarray(st.window_base))
    if upto < base:
        return {"upto": upto, "entries": {}}
    sl = slice(0, upto - base + 1)
    cols = [np.asarray(a)[sl] for a in
            (st.op, st.key_lo, st.val_lo, st.cmd_id, st.client_id)]
    entries = {base + i: tuple(int(col[i]) for col in cols)
               for i in range(upto - base + 1)}
    return {"upto": upto, "entries": entries}


@pytest.mark.parametrize("protocol", ["minpaxos", "classic"])
@pytest.mark.parametrize("seed", [11, 22, 33])
def test_random_fault_schedule_safety(seed, protocol):
    rng = np.random.default_rng(seed)
    c = Cluster(CFG._replace(explicit_commit=(protocol == "classic")),
                ext_rows=256)
    c.elect(0)
    c.run(3)
    stable: dict[int, dict[int, tuple]] = {r: {} for r in range(3)}
    # slot -> (first observer replica, value); all later observations
    # from any replica must match (Consistency even when windows never
    # overlap). Only CROSS-replica matches count toward the vacuity
    # guard — same-replica re-observation is just Stability again.
    agreed: dict[int, tuple[int, tuple]] = {}
    compared = 0
    next_cmd = 0

    for round_ in range(30):
        action = rng.random()
        alive = np.asarray(c.cs.alive)
        if action < 0.55:
            n = int(rng.integers(1, 40))
            c.propose(
                ops=rng.choice([Op.PUT, Op.GET], n),
                keys=rng.integers(0, 30, n),
                vals=rng.integers(1, 1000, n),
                cmd_ids=np.arange(next_cmd, next_cmd + n),
                client_id=1,
                to=c.leader if alive[c.leader] else int(np.argmax(alive)),
            )
            next_cmd += n
        elif action < 0.70 and alive.sum() > 2:
            c.kill(int(rng.choice(np.nonzero(alive)[0])))
        elif action < 0.85 and not alive.all():
            c.revive(int(rng.choice(np.nonzero(~alive)[0])))
        else:
            cand = np.nonzero(alive)[0]
            c.elect(int(rng.choice(cand)))
        c.run(int(rng.integers(1, 4)))

        # ---- invariants after every round ----
        snaps = [snapshot_committed(c, r) for r in range(3)]
        # Stability: committed slots never change (checked while the
        # slot remains resident; slid-out slots were already verified)
        for r, snap in enumerate(snaps):
            for i, entry in snap["entries"].items():
                if i in stable[r]:
                    assert stable[r][i] == entry, (
                        f"seed {seed} round {round_}: replica {r} slot {i} "
                        f"changed after commit: {stable[r][i]} -> {entry}")
                else:
                    stable[r][i] = entry
        # Consistency: every replica's observation of a committed slot
        # matches the first observation recorded for that slot, by any
        # replica, in any round — co-residency not required
        for r, snap in enumerate(snaps):
            for i, entry in snap["entries"].items():
                if i in agreed:
                    first_r, first_entry = agreed[i]
                    assert first_entry == entry, (
                        f"seed {seed} round {round_}: replica {r} slot {i} "
                        f"disagrees with committed value: "
                        f"{first_entry} vs {entry}")
                    if r != first_r:
                        compared += 1
                else:
                    agreed[i] = (r, entry)

    # Exactly-once across the whole run
    dups = [e for e in c.reply_log if e.get("duplicate")]
    assert not dups, f"duplicate replies: {dups[:5]}"
    # the consistency check must actually have compared something
    assert compared > 0, "Consistency check never fired (vacuous test)"


def test_revived_replica_full_value_agreement():
    c = Cluster(CFG, ext_rows=256)
    c.elect(0)
    c.run(3)
    c.kill(2)
    n = 60
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n), vals=np.arange(n) * 7,
              cmd_ids=np.arange(n), client_id=9)
    c.run(5)
    c.revive(2)
    c.run(12)  # catch-up: 60 slots / 32 rows, peer visited every 3 ticks
    st2 = tree_slice(c.cs.states, 2)
    upto = int(np.asarray(st2.committed_upto))
    assert upto == n - 1
    # and it executed the catch-up into its KV replica: every key holds
    # the exact value the leader committed
    assert int(np.asarray(st2.executed_upto)) == n - 1
    live = np.asarray(st2.kv.slot) == 1
    got = dict(zip(np.asarray(st2.kv.key_lo)[live].tolist(),
                   np.asarray(st2.kv.val[:, 1])[live].tolist()))
    assert got == {int(k): int(k) * 7 for k in range(n)}


def test_election_recovers_inflight_span_beyond_recovery_rows():
    """VERDICT round-1 weak #3: a new leader must learn the ENTIRE
    uncommitted suffix, even when the in-flight span is far larger than
    `recovery_rows` (one sweep chunk), and must never no-op fill a slot
    whose value survives on a majority member.

    Schedule: follower 1 misses a 200-slot batch (> 6x recovery_rows);
    the batch commits on leader 0 + follower 2; leader 0 dies; follower
    1 — whose log is EMPTY for the whole span — is elected. Its chunked
    PREPARE_INST sweep must pull every slot from replica 2 and
    re-commit the original values. Reference behavior: full CatchUpLog
    (bareminpaxos.go:488-513) + suffix adoption (:912-966)."""
    cfg = CFG._replace(recovery_rows=32, catchup_rows=32)
    c = Cluster(cfg, ext_rows=256)
    c.elect(0)
    c.run(3)
    c.kill(1)
    n = 200
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n), vals=np.arange(n) * 3,
              cmd_ids=np.arange(n), client_id=7)
    c.run(4)  # leader 0 + follower 2 accept and commit the batch
    st0 = tree_slice(c.cs.states, 0)
    assert int(np.asarray(st0.committed_upto)) >= n - 1, "precondition"
    c.kill(0)
    c.revive(1)
    c.elect(1)
    c.run(60)  # sweep: ~7 chunks + adoption + re-accept + commit rounds
    for r in (1, 2):
        st = tree_slice(c.cs.states, r)
        assert int(np.asarray(st.committed_upto)) >= n - 1, (
            f"replica {r} frontier stalled at "
            f"{int(np.asarray(st.committed_upto))}")
        snap = snapshot_committed(c, r)
        for i in range(n):
            op, key, val, cmd, cli = snap["entries"][i]
            assert op == int(Op.PUT) and key == i and val == i * 3 \
                and cmd == i and cli == 7, (
                    f"replica {r} slot {i} lost its committed value: "
                    f"{snap['entries'][i]} (no-op fill would show op=0)")


def test_laggard_healed_by_new_leader_after_failover():
    """Code-review regression: replica 2 falls behind, then the ORIGINAL
    leader dies. The newly elected leader must still heal replica 2 from
    its retained window (every replica keeps `retention` executed slots
    resident for exactly this)."""
    c = Cluster(CFG, ext_rows=256)
    c.elect(0)
    c.run(3)
    c.kill(2)
    n = 60
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n), vals=np.arange(n) * 5,
              cmd_ids=np.arange(n), client_id=4)
    c.run(6)
    c.revive(2)
    c.kill(0)
    c.elect(1)
    c.run(20)  # new leader's catch-up heals replica 2
    st2 = tree_slice(c.cs.states, 2)
    assert int(np.asarray(st2.committed_upto)) >= n - 1
    assert int(np.asarray(st2.executed_upto)) >= n - 1
    live = np.asarray(st2.kv.slot) == 1
    got = dict(zip(np.asarray(st2.kv.key_lo)[live].tolist(),
                   np.asarray(st2.kv.val[:, 1])[live].tolist()))
    assert got == {int(k): int(k) * 5 for k in range(n)}
