"""Randomized fault-schedule safety tests.

Drives the pod-mode cluster through random mixes of proposals, leader
kills, elections, and revivals, then checks the Paxos safety
invariants the TLA+ spec names (EgalitarianPaxos.tla:687-708):

- Consistency: no two replicas disagree on any committed slot's
  command.
- Stability: a slot once committed on a replica never changes there.
- Exactly-once: every successful reply is delivered at most once per
  (client, cmd_id) (the reference's client -check, client.go:279-284).

Liveness is NOT asserted under arbitrary faults (a majority can be
dead); only safety must hold unconditionally.
"""

import numpy as np
import pytest

from minpaxos_tpu.models.cluster import Cluster, tree_slice
from minpaxos_tpu.models.minpaxos import COMMITTED, MinPaxosConfig
from minpaxos_tpu.wire.messages import Op

CFG = MinPaxosConfig(n_replicas=3, window=512, inbox=512, exec_batch=128,
                     kv_pow2=10, catchup_rows=32)


def snapshot_committed(c: Cluster, r: int):
    st = tree_slice(c.cs.states, r)
    upto = int(np.asarray(st.committed_upto))
    if upto < 0:
        return {}
    sl = slice(0, upto + 1)
    return {
        "upto": upto,
        "op": np.asarray(st.op)[sl].copy(),
        "key": np.asarray(st.key_lo)[sl].copy(),
        "val": np.asarray(st.val_lo)[sl].copy(),
        "cmd": np.asarray(st.cmd_id)[sl].copy(),
        "cli": np.asarray(st.client_id)[sl].copy(),
    }


@pytest.mark.parametrize("seed", [11, 22, 33])
def test_random_fault_schedule_safety(seed):
    rng = np.random.default_rng(seed)
    c = Cluster(CFG, ext_rows=256)
    c.elect(0)
    c.run(3)
    stable: dict[int, dict[int, tuple]] = {r: {} for r in range(3)}
    next_cmd = 0

    for round_ in range(30):
        action = rng.random()
        alive = np.asarray(c.cs.alive)
        if action < 0.55:
            n = int(rng.integers(1, 40))
            c.propose(
                ops=rng.choice([Op.PUT, Op.GET], n),
                keys=rng.integers(0, 30, n),
                vals=rng.integers(1, 1000, n),
                cmd_ids=np.arange(next_cmd, next_cmd + n),
                client_id=1,
                to=c.leader if alive[c.leader] else int(np.argmax(alive)),
            )
            next_cmd += n
        elif action < 0.70 and alive.sum() > 2:
            c.kill(int(rng.choice(np.nonzero(alive)[0])))
        elif action < 0.85 and not alive.all():
            c.revive(int(rng.choice(np.nonzero(~alive)[0])))
        else:
            cand = np.nonzero(alive)[0]
            c.elect(int(rng.choice(cand)))
        c.run(int(rng.integers(1, 4)))

        # ---- invariants after every round ----
        snaps = [snapshot_committed(c, r) for r in range(3)]
        # Stability: committed slots never change
        for r, snap in enumerate(snaps):
            if not snap:
                continue
            for i in range(snap["upto"] + 1):
                entry = (snap["op"][i], snap["key"][i], snap["val"][i],
                         snap["cmd"][i], snap["cli"][i])
                if i in stable[r]:
                    assert stable[r][i] == entry, (
                        f"seed {seed} round {round_}: replica {r} slot {i} "
                        f"changed after commit: {stable[r][i]} -> {entry}")
                else:
                    stable[r][i] = entry
        # Consistency: replicas agree on common committed prefix
        for ra in range(3):
            for rb in range(ra + 1, 3):
                if not snaps[ra] or not snaps[rb]:
                    continue
                lo = min(snaps[ra]["upto"], snaps[rb]["upto"]) + 1
                for fld in ("op", "key", "val", "cmd", "cli"):
                    np.testing.assert_array_equal(
                        snaps[ra][fld][:lo], snaps[rb][fld][:lo],
                        err_msg=f"seed {seed} round {round_}: "
                                f"replicas {ra}/{rb} diverge on {fld}")

    # Exactly-once across the whole run
    dups = [e for e in c.reply_log if e.get("duplicate")]
    assert not dups, f"duplicate replies: {dups[:5]}"


def test_revived_replica_full_value_agreement():
    c = Cluster(CFG, ext_rows=256)
    c.elect(0)
    c.run(3)
    c.kill(2)
    n = 60
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n), vals=np.arange(n) * 7,
              cmd_ids=np.arange(n), client_id=9)
    c.run(5)
    c.revive(2)
    c.run(12)  # catch-up: 60 slots / 32 rows, peer visited every 3 ticks
    st2 = tree_slice(c.cs.states, 2)
    upto = int(np.asarray(st2.committed_upto))
    assert upto == n - 1
    np.testing.assert_array_equal(np.asarray(st2.val_lo)[:n], np.arange(n) * 7)
    # and it executed the catch-up into its KV replica
    assert int(np.asarray(st2.executed_upto)) == n - 1
