"""Unit tests for the host runtime pieces: frame<->column packing and
the durable stable store."""

import numpy as np
import pytest

from minpaxos_tpu.runtime import batches
from minpaxos_tpu.runtime.stable import SLOT_DT, StableStore
from minpaxos_tpu.wire.messages import MsgKind, make_batch


def test_column_buffer_fill_and_drain():
    buf = batches.ColumnBuffer(8)
    buf.append(3, kind=1, inst=np.array([5, 6, 7]), ballot=9)
    assert buf.fill == 3
    cols, n = buf.drain()
    assert n == 3
    np.testing.assert_array_equal(cols["inst"][:3], [5, 6, 7])
    assert (cols["ballot"][:3] == 9).all()
    assert buf.fill == 0 and buf.cols["inst"].sum() == 0


def test_column_buffer_overflow_drops():
    buf = batches.ColumnBuffer(4)
    buf.append(6, kind=1, inst=np.arange(6))
    assert buf.fill == 4 and buf.dropped == 2


def test_propose_frame_to_rows_splits_i64():
    buf = batches.ColumnBuffer(16)
    key = np.array([(1 << 40) + 7, -3], dtype=np.int64)
    frame = make_batch(MsgKind.PROPOSE, cmd_id=np.array([1, 2]), op=1,
                       key=key, val=np.array([10, 20]), timestamp=0)
    batches.frame_to_rows(buf, MsgKind.PROPOSE, frame, conn_id=42)
    cols, n = buf.drain()
    assert n == 2
    from minpaxos_tpu.ops.packed import join_i64

    np.testing.assert_array_equal(
        join_i64(cols["key_hi"][:2], cols["key_lo"][:2]), key)
    assert (cols["client_id"][:2] == 42).all()
    assert (cols["kind"][:2] == int(MsgKind.PROPOSE)).all()


def test_accept_reply_run_length_roundtrip():
    """Kernel-native (inst, count) ack runs ride the wire 1:1: device
    cmd_id <-> wire count, no re-expansion on receive (the kernel
    consumes ranges natively — models/minpaxos.py step 6)."""
    cols = {c: np.zeros(10, np.int32) for c in batches.COLS}
    # two runs from the kernel: slots 5..8 ok at ballot 3 (count=4 on
    # the start row), slot 20 nack (count=1)
    cols["kind"][:2] = int(MsgKind.ACCEPT_REPLY)
    cols["inst"][:2] = [5, 20]
    cols["cmd_id"][:2] = [4, 1]
    cols["ballot"][:2] = [3, 7]
    cols["op"][:2] = [1, 0]
    cols["src"][:2] = 1
    cols["last_committed"][:2] = 4
    frames = batches.rows_to_frames(cols, cols["kind"] != 0)
    assert len(frames) == 1
    kind, frame = frames[0]
    assert kind == MsgKind.ACCEPT_REPLY
    assert len(frame) == 2  # one wire row per run
    np.testing.assert_array_equal(sorted(frame["count"]), [1, 4])
    # receive side: count lands back in cmd_id, still 2 rows
    buf = batches.ColumnBuffer(16)
    batches.frame_to_rows(buf, MsgKind.ACCEPT_REPLY, frame, conn_id=0)
    out, n = buf.drain()
    assert n == 2
    np.testing.assert_array_equal(np.sort(out["inst"][:2]), [5, 20])
    np.testing.assert_array_equal(np.sort(out["cmd_id"][:2]), [1, 4])
    np.testing.assert_array_equal(np.sort(out["op"][:2]), [0, 1])


def test_accept_frame_roundtrip():
    cols = {c: np.zeros(4, np.int32) for c in batches.COLS}
    cols["kind"][:3] = int(MsgKind.ACCEPT)
    cols["src"][:3] = 2
    cols["inst"][:3] = [9, 10, 11]
    cols["ballot"][:3] = 17
    cols["last_committed"][:3] = 8
    cols["op"][:3] = 1
    cols["key_lo"][:3] = [1, 2, 3]
    cols["val_lo"][:3] = [4, 5, 6]
    cols["cmd_id"][:3] = [100, 101, 102]
    cols["client_id"][:3] = 55
    frames = batches.rows_to_frames(cols, cols["kind"] != 0)
    (kind, frame), = frames
    assert kind == MsgKind.ACCEPT and len(frame) == 3
    buf = batches.ColumnBuffer(8)
    batches.frame_to_rows(buf, kind, frame, conn_id=0)
    out, n = buf.drain()
    assert n == 3
    for c in ("inst", "ballot", "last_committed", "op", "key_lo", "val_lo",
              "cmd_id", "client_id"):
        np.testing.assert_array_equal(out[c][:3], cols[c][:3], err_msg=c)


def test_stable_store_roundtrip(tmp_path):
    path = str(tmp_path / "store")
    s = StableStore(path, sync=True)
    s.append_slots(np.arange(5), np.full(5, 16), np.full(5, 3),
                   np.ones(5), np.arange(5) * 10, np.arange(5) * 100,
                   np.arange(5), np.zeros(5))
    s.append_frontier(3)
    s.flush()
    s.close()
    r = StableStore(path)
    assert r.recovered
    assert r.frontier == 3
    assert r.committed_prefix() == 3
    assert r.max_inst() == 4
    rec = r.read_range(1, 3)
    np.testing.assert_array_equal(rec["inst"], [1, 2, 3])
    np.testing.assert_array_equal(rec["val"], [100, 200, 300])
    r.close()


def test_stable_store_ballot_supersede(tmp_path):
    path = str(tmp_path / "store")
    s = StableStore(path)
    s.append_slots([7], [16], [3], [1], [1], [111], [0], [0])
    s.append_slots([7], [32], [3], [1], [2], [222], [1], [0])  # higher ballot
    s.append_slots([7], [16], [3], [1], [3], [333], [2], [0])  # stale: ignored
    s.flush()
    s.close()
    r = StableStore(path)
    rec = r.read_range(7, 7)
    assert int(rec["ballot"][0]) == 32 and int(rec["val"][0]) == 222
    r.close()


def test_stable_store_torn_tail(tmp_path):
    """A crash mid-append leaves a torn record; replay must ignore it."""
    path = str(tmp_path / "store")
    s = StableStore(path)
    s.append_slots(np.arange(3), np.full(3, 16), np.full(3, 3),
                   np.ones(3), np.arange(3), np.arange(3), np.arange(3),
                   np.zeros(3))
    s.append_frontier(2)
    s.flush()
    s.close()
    with open(path, "ab") as f:
        f.write(b"\x01\xff\xff")  # garbage half-header/payload
    r = StableStore(path)
    assert r.committed_prefix() == 2
    assert len(r.read_range(0, 10)) == 3
    r.close()


def test_packed_step_layout_matches_cols():
    """_packed_step's outbox matrix rows must follow batches.COLS order
    (+ dst, + padded acked) — _device_tick unpacks positionally."""
    import jax.numpy as jnp
    import numpy as np

    from minpaxos_tpu.models.minpaxos import (
        MinPaxosConfig,
        MsgBatch,
        init_replica,
        replica_step_impl,
    )
    from minpaxos_tpu.runtime import batches
    from minpaxos_tpu.runtime.replica import _packed_step
    from minpaxos_tpu.wire.messages import MsgKind, Op

    assert MsgBatch._fields == batches.COLS
    cfg = MinPaxosConfig(n_replicas=3, window=64, inbox=16, exec_batch=8,
                         kv_pow2=6, catchup_rows=4, recovery_rows=4)
    st = init_replica(cfg, 0)
    from minpaxos_tpu.models.minpaxos import become_leader
    st, _ = become_leader(cfg, st)
    # donation rejects aliased leaves (init shares zero buffers), same
    # copy ReplicaServer.__init__ performs
    import jax
    st = jax.tree_util.tree_map(lambda x: x.copy(), st)
    row = {c: np.zeros(16, np.int32) for c in batches.COLS}
    row["kind"][0] = int(MsgKind.PROPOSE)
    row["src"][0] = -1
    row["op"][0] = int(Op.PUT)
    row["key_lo"][0] = 7
    row["val_lo"][0] = 9
    row["cmd_id"][0] = 3
    inbox = MsgBatch(**{k: jnp.asarray(v) for k, v in row.items()})
    st2, out_mats, exec_mats, scals = _packed_step(
        cfg, st, inbox, replica_step_impl)
    # outputs are stacked per substep (k=1 here): [1, 14, M] / [1, 6,
    # E] / [1, N_SCAL]
    assert out_mats.shape[0] == exec_mats.shape[0] == scals.shape[0] == 1
    out_mat = np.asarray(out_mats)[0]
    scal = scals[0]
    ncols = len(batches.COLS)
    assert out_mat.shape[0] == ncols + 2
    cols = {c: out_mat[i] for i, c in enumerate(batches.COLS)}
    # a 1-of-3 leader is not yet prepared (needs a majority of
    # PREPARE_REPLYs), so the propose bounces as a client-bound
    # rejection that still carries the command columns — exactly the
    # layout the unpack depends on
    rej = cols["kind"] == int(MsgKind.PROPOSE_REPLY)
    assert rej.any()
    i = int(np.argmax(rej))
    assert cols["key_lo"][i] == 7 and cols["val_lo"][i] == 9
    assert cols["cmd_id"][i] == 3
    dst = out_mat[ncols]
    assert dst[i] == -2  # client-bound
    # scal layout: ops/substeps.py SCAL_* (frontier, window_base,
    # crt_inst, dropped, lo, count, leader, prepared, executed, low
    # anchor, high anchor, work_pending)
    from minpaxos_tpu.ops import substeps

    scal = np.asarray(scal)
    assert scal.shape == (substeps.N_SCAL,)
    assert scal[0] == -1 and scal[1] == 0  # nothing committed yet
    assert scal[6] == 0 and scal[7] == 0  # leader 0, not yet prepared
    assert scal[substeps.SCAL_EXECUTED] == -1
    # an unprepared leader has pending work (the prepare round)
    assert scal[substeps.SCAL_WORK_PENDING] == 1


def test_cluster_step_strips_exec_gate():
    """Vmapped compositions must run ungated exec (cond-under-vmap
    evaluates both branches): cluster_step_impl rewrites the static
    config before tracing the per-replica step."""
    from minpaxos_tpu.models.minpaxos import MinPaxosConfig

    seen = []

    def spy_step(cfg, state, inbox):
        seen.append(cfg.gate_exec)
        from minpaxos_tpu.models.minpaxos import replica_step_impl
        return replica_step_impl(cfg, state, inbox)

    cfg = MinPaxosConfig(n_replicas=3, window=32, inbox=8, exec_batch=4,
                         kv_pow2=6, catchup_rows=4, recovery_rows=4)
    assert cfg.gate_exec  # default on (the TCP runtime's fast path)
    import jax
    import jax.numpy as jnp

    from minpaxos_tpu.models.cluster import Cluster, cluster_step_impl
    from minpaxos_tpu.models.minpaxos import MsgBatch

    cs = Cluster(cfg).cs  # the real pod-mode construction
    ext = jax.tree_util.tree_map(
        lambda x: jnp.zeros((3,) + x.shape, x.dtype),
        MsgBatch.empty(4))
    cluster_step_impl(cfg, cs, ext, step_impl=spy_step)
    assert seen and not any(seen)
