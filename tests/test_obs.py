"""paxmon observability layer: typed registry, flight recorder, trace
export, control-socket STATS/TRACE verbs, master fan-out, paxtop.

Unit half (no cluster): registry/recorder semantics incl. ring
wraparound and Chrome trace-event schema validity for ALL four
dispatch regimes. Integration half: one real 3-replica in-process
cluster driven through commits + an idle window, then observed end to
end — replica control socket, master fan-out, and tools/paxtop.py as
a genuine subprocess.
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from minpaxos_tpu.obs.metrics import Histogram, MetricsRegistry
from minpaxos_tpu.obs.recorder import (
    KIND_FULL,
    KIND_FUSED,
    KIND_IDLE_SKIP,
    KIND_NAMES,
    KIND_NARROW,
    FlightRecorder,
    chrome_trace,
    validate_chrome_trace,
)

REPO = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------- registry


def test_registry_counters_gauges_and_snapshot_isolation():
    reg = MetricsRegistry("r0")
    c = reg.counter("dispatches", "device round-trips")
    c.inc()
    c.inc(4)
    g = reg.gauge("committed")
    g.set(17)
    reg.fn_gauge("conns", lambda: 3)
    snap = reg.counters()
    assert snap == {"dispatches": 5, "committed": 17, "conns": 3}
    # snapshots are FRESH dicts: mutating one never touches the
    # registry, and later advances never mutate an old snapshot
    snap["dispatches"] = -1
    c.inc()
    assert reg.counters()["dispatches"] == 6
    assert snap["dispatches"] == -1
    # get-or-create returns the same underlying metric
    assert reg.counter("dispatches") is c


def test_registry_full_snapshot_shape_is_json_serializable():
    reg = MetricsRegistry("r1")
    reg.counter("ticks").inc(2)
    reg.histogram("tick_wall_ms").observe(0.7)
    snap = reg.snapshot()
    assert snap["namespace"] == "r1"
    assert snap["counters"]["ticks"] == 2
    h = snap["histograms"]["tick_wall_ms"]
    assert h["count"] == 1 and len(h["counts"]) == len(h["bounds"]) + 1
    json.dumps(snap)  # the control plane ships this as JSON lines


def test_histogram_percentiles_and_bad_bounds():
    h = Histogram("t", bounds=(1.0, 2.0, 4.0, 8.0))
    for v in [0.5] * 50 + [3.0] * 49 + [100.0]:  # overflow observed
        h.observe(v)
    assert h.total == 100 and h.counts[-1] == 1
    assert 0.0 < h.percentile(0.5) <= 1.0
    assert h.percentile(0.99) >= 2.0
    assert h.percentile(1.0) <= 8.0  # overflow clamps to the last edge
    with pytest.raises(ValueError):
        Histogram("bad", bounds=(2.0, 1.0))
    with pytest.raises(ValueError):
        Histogram("empty", bounds=())


# ---------------------------------------------------------- recorder


def test_recorder_ring_wraparound_keeps_newest_in_order():
    rec = FlightRecorder(8)
    for i in range(20):
        rec.record(1000 * i, KIND_FULL, 1, i, 0, i, 0, 1, 2, 3, 0, 4, 5, 6)
    assert rec.total == 20
    snap = rec.snapshot()
    assert snap.shape == (8, 18)  # schema v7: + coal_occ, coal_wake
    # newest 8 rows, oldest-first (timestamps strictly increasing)
    np.testing.assert_array_equal(snap[:, 0],
                                  [1000 * i for i in range(12, 20)])
    assert (np.diff(snap[:, 0]) > 0).all()
    # `last` bounds the copy further
    assert len(rec.snapshot(last=3)) == 3
    np.testing.assert_array_equal(rec.snapshot(last=3)[:, 3], [17, 18, 19])
    with pytest.raises(ValueError):
        FlightRecorder(0)


def test_trace_export_all_four_regimes_validates():
    rec = FlightRecorder(64)
    t = 5_000_000_000
    for i, kind in enumerate([KIND_FULL, KIND_FUSED, KIND_NARROW,
                              KIND_IDLE_SKIP] * 4):
        t += 2_000_000
        # pipelined rows (every other) carry a hidden host wall
        rec.record(t, kind, 3 if kind == KIND_FUSED else 1, 8, 12,
                   100 + i, 2, 15, 30, 700, 250 if i % 2 else 0,
                   120, 90, 40)
    events = rec.to_events(pid=2)
    trace = chrome_trace(events)
    assert validate_chrome_trace(trace) == []
    json.dumps(trace)  # loadable = serializable first
    ticks = [e for e in events if e.get("cat") == "tick"]
    assert {e["args"]["kind"] for e in ticks} == set(KIND_NAMES)
    assert all(e["pid"] == 2 for e in events)
    # per-phase children exist for device ticks, not for idle skips
    # (schema v2: the blocking step_us is gone; the dispatch splits
    # into enqueue + readback, and the hidden host wall rides
    # overlap_us on the tick args + its own counter track)
    names = {e["name"] for e in events}
    assert {"enqueue", "readback", "persist", "dispatch", "reply"} <= names
    assert "device_step" not in names and "step_us" not in names
    assert {e["args"]["overlap_us"] for e in ticks} == {0, 250}
    # two-track rendering: dispatch phases on tid 0, host phases on
    # tid 1 (a deferred tick's host work then renders under the next
    # tick's dispatch slice instead of overlapping it on one track)
    phase_tid = {e["name"]: e["tid"] for e in events
                 if e.get("cat") == "phase"}
    assert phase_tid["enqueue"] == 0 and phase_tid["readback"] == 0
    assert phase_tid["persist"] == 1 and phase_tid["reply"] == 1
    skips = [e for e in ticks if e["args"]["kind"] == "idle_skip"]
    assert skips and all(e["args"]["k"] == 1 for e in ticks
                         if e["args"]["kind"] == "full")
    # counter events carry numeric args (what Perfetto graphs);
    # overlap_us is one of the counter tracks
    cs = [e for e in events if e["ph"] == "C"]
    assert cs and all(isinstance(v, int) for e in cs
                      for v in e["args"].values())
    assert any(e["name"] == "overlap_us" for e in cs)


def test_trace_schema_version_stamped_and_checked():
    """chrome_trace stamps the ring-layout revision; a trace from a
    different layout must fail validation instead of silently
    mislabeling phases in a viewer."""
    from minpaxos_tpu.obs.recorder import SCHEMA_VERSION

    tr = chrome_trace([])
    assert tr["otherData"]["paxmonSchemaVersion"] == SCHEMA_VERSION == 7
    assert validate_chrome_trace(tr) == []
    stale = chrome_trace([])
    stale["otherData"]["paxmonSchemaVersion"] = 4
    errs = validate_chrome_trace(stale)
    assert errs and "schema version mismatch" in errs[0]
    # traces without the stamp (e.g. hand-built fixtures) still pass
    assert validate_chrome_trace({"traceEvents": []}) == []


def test_trace_validator_rejects_malformed_events():
    bad = {"traceEvents": [
        {"name": "x", "ph": "X", "ts": 1.0, "pid": 0, "tid": 0},  # no dur
        {"name": "", "ph": "X", "ts": 1.0, "dur": 1, "pid": 0, "tid": 0},
        {"name": "c", "ph": "C", "ts": 1.0, "pid": 0, "tid": 0,
         "args": {"v": "NaN-ish string"}},
        {"name": "y", "ph": "??", "ts": 1.0},
    ]}
    errs = validate_chrome_trace(bad)
    assert len(errs) == 4, errs
    assert validate_chrome_trace([]) and validate_chrome_trace({})


# ---------------------------------------------------------------- dlog


def test_dlog_prefix_and_monotonic_deltas(capsys):
    import importlib

    # utils/__init__ re-exports the dlog FUNCTION under the module's
    # name; fetch the module itself
    dmod = importlib.import_module("minpaxos_tpu.utils.dlog")
    dmod.set_dlog_id("r7")
    try:
        dmod._dlog_enabled("hello %d", 42)
        dmod._dlog_enabled("again")
        err = capsys.readouterr().err
    finally:
        dmod.set_dlog_id("")
    lines = [ln for ln in err.splitlines() if ln.startswith("[dlog")]
    assert len(lines) == 2
    assert all(" r7 " in ln for ln in lines), lines
    assert "hello 42" in lines[0]
    # second line carries the delta since the first (+X.XXXms)
    assert "+" in lines[1].split("]")[0] and "ms]" in lines[1]
    # the disabled binding stays a bound no-op
    dmod._dlog_disabled("never %s", "printed")


# ----------------------------------------------- cluster integration


def _ctl(addr: tuple[str, int], req: dict) -> dict:
    """One control-socket round trip (the real TCP path paxtop uses)."""
    from minpaxos_tpu.utils.netutil import CONTROL_OFFSET

    host, port = addr
    with socket.create_connection((host, port + CONTROL_OFFSET),
                                  timeout=10) as s:
        f = s.makefile("rw")
        f.write(json.dumps(req) + "\n")
        f.flush()
        return json.loads(f.readline())


def test_stats_trace_verbs_master_fanout_and_paxtop(tmp_path):
    """End to end against a live 3-replica cluster: STATS/TRACE over
    the replica control socket, the master's cluster-wide fan-out,
    and tools/paxtop.py --once --json as a real subprocess. exec_batch
    is squeezed so commit backlogs force fused dispatches; a quiet
    window afterwards accumulates idle skips — both regimes must show
    up in the flight-recorder trace alongside full steps."""
    from test_distributed import Harness

    from minpaxos_tpu.runtime.client import gen_workload
    from minpaxos_tpu.runtime.master import cluster_stats, cluster_trace

    h = Harness(tmp_path, cfg_overrides=dict(exec_batch=16))
    try:
        cli = h.client()
        ops, keys, vals = gen_workload(400, seed=5)
        stats = cli.run_workload(ops, keys, vals, timeout_s=60)
        assert stats["acked"] == 400, stats
        # client-side paxmon rides the driver stats into bench records
        assert stats["client_metrics"]["proposed_rows"] >= 400
        cli.close_conn()

        # the old bug, pinned: `stats` is a snapshot, not the live dict
        s1 = h.servers[0].stats
        s1["dispatches"] = -999
        assert h.servers[0].stats["dispatches"] != -999

        # quiet window: the idle fast path must record skips
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(s.stats["idle_skips"] > 0 for s in h.servers.values()):
                break
            time.sleep(0.1)

        # STATS verb: typed snapshot + published scalar vector
        r = _ctl(h.addrs[0], {"m": "stats"})
        assert r["ok"] and r["id"] == 0 and r["protocol"] == "minpaxos"
        cnt = r["metrics"]["counters"]
        assert cnt["dispatches"] > 0 and cnt["proposals"] >= 400
        assert cnt["full_steps"] + cnt["fused_dispatches"] + \
            cnt["narrow_steps"] == cnt["dispatches"]
        assert r["metrics"]["gauges"]["committed"] >= 400
        assert r["metrics"]["gauges"]["net_frames_in"] > 0  # transport
        assert r["metrics"]["histograms"]["tick_wall_ms"]["count"] > 0
        assert r["scalars"]["frontier"] == r["frontier"]
        assert r["scalars"]["work_pending"] in (0, 1)

        # squeezed exec_batch guarantees backlog fusion somewhere
        fused = [s.stats["fused_dispatches"] for s in h.servers.values()]
        assert any(f > 0 for f in fused), fused

        # TRACE verb: schema-valid, regimes visible
        rid = max(h.servers, key=lambda i: h.servers[i].stats[
            "fused_dispatches"])
        tr = _ctl(h.addrs[rid], {"m": "trace", "last": 4096})
        assert tr["ok"] and tr["recorder"]
        trace = chrome_trace(tr["events"])
        assert validate_chrome_trace(trace) == []
        kinds = {e["args"]["kind"] for e in tr["events"]
                 if e.get("cat") == "tick"}
        assert {"full", "fused", "idle_skip"} <= kinds, kinds

        # master fan-out: one RPC, all replicas
        maddr = ("127.0.0.1", h.mport)
        ms = cluster_stats(maddr)
        assert ms["ok"] and len(ms["replicas"]) == 3
        assert all(rr["ok"] for rr in ms["replicas"]), ms["replicas"]
        assert {rr["id"] for rr in ms["replicas"]} == {0, 1, 2}
        mt = cluster_trace(maddr, last=256)
        assert validate_chrome_trace(mt["trace"]) == []
        from minpaxos_tpu.obs.recorder import WATCH_PID

        pids = {e["pid"] for e in mt["trace"]["traceEvents"]}
        assert pids == {0, 1, 2, WATCH_PID}, pids

        # paxwatch EVENTS fan-out (live cluster): replica 0 journaled
        # its boot election, every replica its peer-link installs, and
        # the collections carry the clock anchors the offline merge
        # aligns by — and the merged v6 trace above already carried
        # the journals as instant events on the reserved pid
        from minpaxos_tpu.obs import watch as W
        from minpaxos_tpu.runtime.master import cluster_events

        ev = cluster_events(maddr)
        assert ev["ok"] and len(ev["replicas"]) == 3
        assert all(rr["ok"] and rr["journal"]["anchor"]["mono_ns"] > 0
                   for rr in ev["replicas"]), ev["replicas"]
        rows = W.align_event_collections(
            [rr["journal"] for rr in ev["replicas"]])
        kinds = set(rows[:, W.EV_KIND].tolist())
        assert W.EV_ELECTION in kinds and W.EV_PEER_UP in kinds, kinds
        j0 = [rr for rr in ev["replicas"] if rr["id"] == 0][0]["journal"]
        r0 = np.asarray(j0["events"], np.int64)
        elecs = r0[r0[:, W.EV_KIND] == W.EV_ELECTION]
        assert len(elecs) >= 1 and int(elecs[0][W.EV_SUBJECT]) == 0
        # the journal total rides stats as an fn-gauge (paxtop's feed)
        assert cnt is not None  # (STATS leg above)
        st0 = _ctl(h.addrs[0], {"m": "stats"})
        assert st0["metrics"]["gauges"]["events"] >= j0["total"] > 0
        wevs = [e for e in mt["trace"]["traceEvents"]
                if e.get("cat") == "paxwatch"]
        assert wevs and all(e["pid"] == WATCH_PID and e["ph"] == "i"
                            for e in wevs)

        # the shipped live view, as a subprocess (no jax import there)
        out = subprocess.run(
            [sys.executable, str(REPO / "tools/paxtop.py"),
             "-mport", str(h.mport), "--once", "--json"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        payload = json.loads(out.stdout)
        rows = payload["derived"]
        assert len(rows) == 3 and all(rw["ok"] for rw in rows)
        lead = [rw for rw in rows if rw["role"] == "leader"]
        assert len(lead) == 1 and lead[0]["frontier"] >= 399
        assert all(rw["tick_p50_ms"] > 0 for rw in rows)

        # paxtop -dump-trace writes a Perfetto-loadable file
        tf = tmp_path / "cluster_trace.json"
        out = subprocess.run(
            [sys.executable, str(REPO / "tools/paxtop.py"),
             "-mport", str(h.mport), "-dump-trace", str(tf),
             "-last", "128"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert validate_chrome_trace(json.loads(tf.read_text())) == []
    finally:
        h.stop()


def test_norecorder_flag_disables_trace_not_stats(tmp_path):
    """RuntimeFlags(recorder=False) (the server's -norecorder A/B
    knob): TRACE answers empty-but-ok, STATS keeps full metrics."""
    from test_distributed import Harness

    from minpaxos_tpu.runtime.client import gen_workload

    h = Harness(tmp_path, n=1,
                flags_overrides={0: {"recorder": False}})
    try:
        cli = h.client()
        ops, keys, vals = gen_workload(50, seed=9)
        assert cli.run_workload(ops, keys, vals,
                                timeout_s=60)["acked"] == 50
        cli.close_conn()
        assert h.servers[0].recorder is None
        tr = _ctl(h.addrs[0], {"m": "trace"})
        assert tr["ok"] and tr["recorder"] is False and tr["events"] == []
        st = _ctl(h.addrs[0], {"m": "stats"})
        assert st["ok"] and st["metrics"]["counters"]["dispatches"] > 0
    finally:
        h.stop()
