"""The pipelined tick loop's equality and durability contracts
(runtime/replica.py `_device_tick` / `_finish_host`).

The pipeline's claim is REORDERING, not approximation: deferring a
tick's host phases under the next tick's device compute must produce
byte-identical replies (content and per-connection order) and
leaf-identical device state versus the strictly serial `-nopipeline`
order, over any trace. These tests drive two replica servers — one
per mode — through the same randomized multi-tick trace WITHOUT their
protocol threads (the test owns the tick loop, so both runs see
identical inputs), then compare everything. The `-durable` half pins
the fsync-before-reply ordering per tick, including at a simulated
crash point between a tick's dispatch and its deferred host phases.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.runtime.replica import CONTROL, ReplicaServer, RuntimeFlags
from minpaxos_tpu.runtime.transport import FROM_CLIENT
from minpaxos_tpu.wire.messages import MsgKind, Op, make_batch

CID = 7  # the one client connection id both runs use

CFG = MinPaxosConfig(n_replicas=1, window=128, inbox=16, exec_batch=8,
                     kv_pow2=8, catchup_rows=8, recovery_rows=8,
                     gossip_ticks=1)


def _mk_server(tmp_path, name: str, pipeline: bool,
               durable: bool = False) -> ReplicaServer:
    """A single-replica server with NO threads/sockets started: the
    test drives _drain/_device_tick itself, so pipelined and serial
    runs consume byte-identical tick sequences."""
    d = tmp_path / name
    d.mkdir()
    flags = RuntimeFlags(pipeline=pipeline, durable=durable,
                         store_dir=str(d))
    return ReplicaServer(0, [("127.0.0.1", 7077)], CFG, flags)


def _capture_replies(srv: ReplicaServer, log: list) -> None:
    srv.transport.send_client = (  # type: ignore[method-assign]
        lambda cid, kind, rows: log.append((cid, int(kind), rows.copy()))
        or True)


def _elect(srv: ReplicaServer) -> None:
    srv.queue.put((CONTROL, 0, "be_the_leader", None))
    for _ in range(20):
        if srv._drain(0.001):
            srv._become_leader()
        srv._device_tick(srv.inbox)
        if srv.snapshot["prepared"]:
            return
    raise AssertionError(f"never prepared: {srv.snapshot}")


def _trace(n_frames: int, rows: int, seed: int) -> list[np.ndarray]:
    """Randomized PROPOSE frames with globally unique cmd_ids and a
    PUT/GET mix over a small key space (GETs observe earlier PUTs, so
    reply VALUES depend on execution order — a reordering bug shows up
    in the payload, not just the stream shape)."""
    rng = np.random.default_rng(seed)
    out = []
    for f in range(n_frames):
        ops = rng.choice([int(Op.PUT), int(Op.GET)], size=rows,
                         p=[0.7, 0.3])
        out.append(make_batch(
            MsgKind.PROPOSE,
            cmd_id=(1000 + f * rows + np.arange(rows)).astype(np.int32),
            op=ops.astype(np.uint8),
            key=rng.integers(0, 40, rows).astype(np.int64),
            val=rng.integers(1, 1 << 20, rows).astype(np.int64),
            timestamp=0))
    return out


def _run_trace(srv: ReplicaServer, trace: list[np.ndarray],
               extra_ticks: int = 12) -> list:
    """Feed the whole trace through the queue (so the pipelined run
    sees queued follow-up traffic — the defer condition), then a FIXED
    number of drain+tick rounds: both modes execute the same number of
    dispatches, keeping device tick counters comparable."""
    replies: list = []
    _capture_replies(srv, replies)
    _elect(srv)
    for frame in trace:
        srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE, frame))
    for _ in range(3 * len(trace) + extra_ticks):
        srv._drain(0.001)
        srv._device_tick(srv.inbox)
    srv._flush_inflight()
    return replies


def _assert_replies_equal(a: list, b: list) -> None:
    assert len(a) == len(b), (len(a), len(b))
    for i, ((cid_a, kind_a, rows_a), (cid_b, kind_b, rows_b)) in enumerate(
            zip(a, b)):
        assert (cid_a, kind_a) == (cid_b, kind_b), i
        for f in rows_a.dtype.names:
            if f == "timestamp":
                continue  # wall-clock stamp: the one intended delta
            np.testing.assert_array_equal(rows_a[f], rows_b[f],
                                          err_msg=f"reply {i} field {f}")


def test_pipelined_equals_serial_over_randomized_trace(tmp_path):
    """Leaf-for-leaf state + reply-stream equality, pipelined vs
    -nopipeline, over a randomized multi-tick PUT/GET trace — and the
    pipelined run must actually have deferred host phases (else this
    proves nothing)."""
    trace = _trace(n_frames=6, rows=CFG.inbox, seed=11)
    srv_p = _mk_server(tmp_path, "pipe", pipeline=True)
    srv_s = _mk_server(tmp_path, "serial", pipeline=False)
    try:
        rep_p = _run_trace(srv_p, trace)
        rep_s = _run_trace(srv_s, trace)
        assert srv_p.stats["pipelined_ticks"] > 0, srv_p.stats
        assert srv_s.stats["pipelined_ticks"] == 0, srv_s.stats
        # every admitted command was replied to, exactly once
        n_cmds = sum(len(rep[2]["cmd_id"]) for rep in rep_p
                     if rep[1] == int(MsgKind.PROPOSE_REPLY))
        assert n_cmds == 6 * CFG.inbox
        _assert_replies_equal(rep_p, rep_s)
        assert srv_p.snapshot == srv_s.snapshot
        for leaf_p, leaf_s in zip(
                jax.tree_util.tree_leaves(srv_p.state),
                jax.tree_util.tree_leaves(srv_s.state)):
            np.testing.assert_array_equal(np.asarray(leaf_p),
                                          np.asarray(leaf_s))
        # the dispatch-regime mix is part of the equality claim too:
        # the pipeline must not change WHAT was dispatched, only when
        # host phases ran
        for key in ("dispatches", "full_steps", "fused_dispatches",
                    "narrow_steps", "proposals", "executed"):
            assert srv_p.stats[key] == srv_s.stats[key], key
    finally:
        srv_p.store.close()
        srv_s.store.close()


def test_durable_no_reply_precedes_its_ticks_fsync(tmp_path):
    """-durable ordering through the pipeline: at the instant any
    reply frame is handed to the transport, the store must have NO
    unflushed records (this tick's accepted/committed slots were
    already fsynced) — for immediate AND deferred host phases."""
    srv = _mk_server(tmp_path, "durable", pipeline=True, durable=True)
    dirty = [False]
    violations = []
    store = srv.store
    orig_slots, orig_front = store.append_slots, store.append_frontier
    orig_flush = store.flush

    def slots(*a, **kw):
        dirty[0] = True
        return orig_slots(*a, **kw)

    def front(committed_upto):
        # append_frontier no-ops at/below the recorded frontier
        if committed_upto > store.frontier:
            dirty[0] = True
        return orig_front(committed_upto)

    def flush():
        dirty[0] = False
        return orig_flush()

    store.append_slots, store.append_frontier = slots, front
    store.flush = flush

    def send_client(cid, kind, rows):
        if dirty[0]:
            violations.append((cid, int(kind), rows["cmd_id"].tolist()))
        return True

    srv.transport.send_client = send_client  # type: ignore[method-assign]
    try:
        _elect(srv)
        for frame in _trace(n_frames=4, rows=CFG.inbox, seed=23):
            srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE, frame))
        for _ in range(24):
            srv._drain(0.001)
            srv._device_tick(srv.inbox)
        srv._flush_inflight()
        assert violations == []
        assert srv.stats["pipelined_ticks"] > 0  # the deferred path ran
        assert srv.stats["executed"] == 4 * CFG.inbox
    finally:
        srv.store.close()


def test_durable_crash_point_loses_reply_and_persist_together(tmp_path):
    """Simulated crash between a tick's dispatch and its DEFERRED host
    phases (the new window the pipeline opens): the tick's replies
    must not have left — reply strictly follows persist+fsync in
    program order, so a crash can lose both but never the reply
    alone. The client treats the silence as unacked and retries."""
    srv = _mk_server(tmp_path, "crash", pipeline=True, durable=True)
    replies: list = []
    _capture_replies(srv, replies)
    flushes = [0]
    orig_flush = srv.store.flush
    srv.store.flush = lambda: flushes.__setitem__(0, flushes[0] + 1) or orig_flush()
    try:
        _elect(srv)
        n_before = len(replies)
        f_before = flushes[0]
        # two frames queued: tick 1 processes frame 1 and DEFERS its
        # host phases (queue non-empty)...
        for frame in _trace(n_frames=2, rows=CFG.inbox, seed=31):
            srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE, frame))
        srv._drain(0.001)
        srv._device_tick(srv.inbox)
        assert srv._inflight is not None  # host phases pending
        # ...crash here: the deferred tick's persist AND replies are
        # both lost — neither happened yet
        assert len(replies) == n_before
        assert flushes[0] == f_before
        srv._inflight = None  # the crash drops the in-flight work
    finally:
        srv.store.close()


def test_narrow_anchor_validation_quiet_on_legit_traffic(tmp_path):
    """The post-readback anchor validation must not false-positive on
    ordinary narrow-view traffic (a spurious fallback would disable
    the narrow win every other dispatch): drive proposes through a
    narrow-windowed pipelined server; narrow dispatches happen, zero
    fallbacks, and the doubt flag stays clear."""
    d = tmp_path / "narrow"
    d.mkdir()
    flags = RuntimeFlags(pipeline=True, narrow_window=32, store_dir=str(d))
    srv = ReplicaServer(0, [("127.0.0.1", 7077)], CFG, flags)
    _capture_replies(srv, [])
    try:
        _elect(srv)
        for frame in _trace(n_frames=3, rows=CFG.inbox, seed=17):
            srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE, frame))
        for _ in range(18):
            srv._drain(0.001)
            srv._device_tick(srv.inbox)
        srv._flush_inflight()
        assert srv.stats["narrow_steps"] > 0, srv.stats
        assert srv.stats["narrow_fallbacks"] == 0, srv.stats
        assert not srv._narrow_doubt
        assert srv.stats["executed"] == 3 * CFG.inbox
    finally:
        srv.store.close()


def test_nopipeline_flag_reaches_runtime_flags():
    """cli/server.py wires -nopipeline into RuntimeFlags.pipeline
    (parse-only: the flag is the documented A/B escape hatch)."""
    import argparse

    from minpaxos_tpu.cli import server as cli_server

    # reuse the real parser by probing a tiny shim: build the parser
    # the same way main() does, but stop at parse_args
    p = argparse.ArgumentParser()
    p.add_argument("-nopipeline", action="store_true")
    assert p.parse_args([]).nopipeline is False
    assert p.parse_args(["-nopipeline"]).nopipeline is True
    # and the flag text is present in the CLI module
    import inspect

    src = inspect.getsource(cli_server)
    assert "-nopipeline" in src and "pipeline=not args.nopipeline" in src


@pytest.mark.parametrize("pipeline", [True, False])
def test_tick_counters_and_recorder_fields(tmp_path, pipeline):
    """Both modes record schema-v2 rows: enqueue/readback always
    populated; overlap_us > 0 only where host phases were deferred."""
    from minpaxos_tpu.obs.recorder import (
        F_ENQUEUE_US,
        F_OVERLAP_US,
        F_READBACK_US,
    )

    srv = _mk_server(tmp_path, f"rec{int(pipeline)}", pipeline=pipeline)
    _capture_replies(srv, [])
    try:
        _elect(srv)
        for frame in _trace(n_frames=3, rows=CFG.inbox, seed=5):
            srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE, frame))
        for _ in range(18):
            srv._drain(0.001)
            srv._device_tick(srv.inbox)
        srv._flush_inflight()
        rows = srv.recorder.snapshot()
        assert (rows[:, F_ENQUEUE_US] > 0).all()
        assert (rows[:, F_READBACK_US] >= 0).all()
        overlapped = rows[:, F_OVERLAP_US] > 0
        if pipeline:
            assert overlapped.any()
            assert int(overlapped.sum()) == srv.stats["pipelined_ticks"]
        else:
            assert not overlapped.any()
    finally:
        srv.store.close()
