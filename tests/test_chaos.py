"""paxchaos: fault-plan/shim determinism, byte transparency, store CRC
recovery, backoff satellites, and the partition-the-leader integration
scenario (ROBUSTNESS.md).
"""

import queue
import struct
import time

import numpy as np
import pytest

from minpaxos_tpu.chaos import ChaosShim, FaultPlan
from minpaxos_tpu.chaos.campaign import SCHEDULES, build_schedule
from minpaxos_tpu.runtime.stable import (
    MAGIC_V1,
    REC_FRONTIER,
    REC_SLOTS,
    SLOT_DT,
    StableStore,
)
from minpaxos_tpu.runtime.transport import FROM_PEER, Transport
from minpaxos_tpu.utils.netutil import free_ports
from minpaxos_tpu.wire.messages import MsgKind, make_batch


# ------------------------------------------------------------- plan

def test_fault_plan_roundtrip_and_validation():
    p = (FaultPlan(3, seed=7).isolate(0)
         .set_link(1, 2, drop=0.1, reorder=4, delay_s=0.01, jitter_s=0.02))
    d = p.to_dict()
    assert FaultPlan.from_dict(d).to_dict() == d
    assert not p.is_noop() and FaultPlan(3).is_noop()
    with pytest.raises(ValueError):
        FaultPlan(3).set_link(0, 0, block=True)  # self-link
    with pytest.raises(ValueError):
        FaultPlan(3).set_link(0, 3, block=True)  # out of range
    with pytest.raises(ValueError):
        FaultPlan(3).set_link(0, 1, drop=1.5)  # not a probability
    with pytest.raises(ValueError):
        FaultPlan(3).set_link(0, 1, delay_s=100.0)  # over MAX_DELAY_S
    # one-way partition blocks exactly one direction
    ow = FaultPlan(3).partition([1], [0], one_way=True)
    assert ow.link(1, 0).block and ow.link(0, 1) is None


def test_schedule_determinism_pinned():
    """Acceptance pin: the same (schedule, seed) reproduces the
    IDENTICAL fault schedule — event times, ops, and the plan dicts
    (whose seed drives every per-link network decision)."""
    for name in SCHEDULES:
        a = build_schedule(name, 1234, 3)
        b = build_schedule(name, 1234, 3)
        assert a == b, name
        assert a != build_schedule(name, 1235, 3), name
        assert a, f"{name}: empty schedule"
        times = [t for t, _, _ in a]
        assert times == sorted(times), name


# ------------------------------------------------------------- shim

def _drain_queue(q):
    out = []
    while True:
        try:
            out.append(q.get_nowait())
        except queue.Empty:
            return out


def test_shim_seed_determinism():
    """Same plan + seed => identical drop/dup/delay decisions per
    link, independent of wall clock; a different seed differs."""
    def run(seed):
        q = queue.Queue()
        plan = FaultPlan(3, seed=seed).set_link(
            1, 0, drop=0.3, dup=0.2, delay_s=0.0)
        sh = ChaosShim(0, plan, q)
        decisions = [sh._in[1].decide() for _ in range(300)]
        sh.stop()
        return decisions

    assert run(11) == run(11)
    assert run(11) != run(12)
    # and end-to-end through ingest: the delivered subset matches
    def deliver(seed):
        q = queue.Queue()
        sh = ChaosShim(0, FaultPlan(2, seed=seed).set_link(1, 0, drop=0.4),
                       q)
        for i in range(200):
            sh.ingest(1, int(MsgKind.ACCEPT), i)
        sh.stop()
        return [item[3] for item in _drain_queue(q)]

    assert deliver(5) == deliver(5)
    assert deliver(5) != deliver(6)


def test_shim_reorder_deterministic_permutation():
    def run(seed):
        q = queue.Queue()
        sh = ChaosShim(0, FaultPlan(2, seed=seed).set_link(1, 0, reorder=4),
                       q)
        for i in range(12):  # three full windows: no time-flush path
            sh.ingest(1, int(MsgKind.ACCEPT), i)
        sh.stop()
        return [item[3] for item in _drain_queue(q)]

    got = run(3)
    assert sorted(got) == list(range(12))
    assert got != list(range(12)), "permutation never fired"
    assert got == run(3)
    counts = ChaosShim(0, FaultPlan(2, seed=3), queue.Queue()).counts()
    assert set(counts) == {"blocked_in", "dropped", "delayed",
                           "duplicated", "reordered", "blocked_out"}


def test_shim_duplicate_and_delay():
    q = queue.Queue()
    sh = ChaosShim(0, FaultPlan(2, seed=9).set_link(1, 0, dup=1.0), q)
    for i in range(5):
        sh.ingest(1, int(MsgKind.ACCEPT), i)
    got = [item[3] for item in _drain_queue(q)]
    assert got == [0, 0, 1, 1, 2, 2, 3, 3, 4, 4]
    assert sh.counts()["duplicated"] == 5
    sh.stop()
    # a delayed frame arrives later, via the pump thread
    q2 = queue.Queue()
    sh2 = ChaosShim(0, FaultPlan(2, seed=9).set_link(1, 0, delay_s=0.04),
                    q2)
    t0 = time.monotonic()
    sh2.ingest(1, int(MsgKind.ACCEPT), "x")
    item = q2.get(timeout=2.0)
    assert item == (FROM_PEER, 1, int(MsgKind.ACCEPT), "x")
    assert time.monotonic() - t0 >= 0.03
    assert sh2.counts()["delayed"] == 1
    sh2.stop()


def _mk_transport_pair():
    addrs = [("127.0.0.1", p) for p in free_ports(2)]
    ta, tb = Transport(0, addrs), Transport(1, addrs)
    ta.listen()
    tb.listen()
    tb.connect_peers()  # 1 dials 0
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if ta.peer_alive(1) and tb.peer_alive(0):
            return ta, tb
        time.sleep(0.02)
    raise TimeoutError("transport pair never meshed")


def test_disabled_shim_is_byte_transparent():
    """No shim, a no-op-plan shim, and a cleared shim must all deliver
    the exact bytes the baseline path delivers."""
    ta, tb = _mk_transport_pair()
    try:
        frame = make_batch(MsgKind.ACCEPT, leader_id=1,
                           inst=np.arange(4), ballot=17, op=1,
                           key=np.arange(4) * 3, val=np.arange(4) * 7,
                           cmd_id=np.arange(4), client_id=0,
                           last_committed=-1)

        def send_and_recv():
            assert tb.send_peer(0, MsgKind.ACCEPT, frame)
            tb.flush_all()
            src, conn, kind, rows = ta.queue.get(timeout=5)
            assert (src, conn, kind) == (FROM_PEER, 1, MsgKind.ACCEPT)
            return rows.tobytes()

        base = send_and_recv()
        ta.set_chaos(ChaosShim(0, FaultPlan(2, seed=1), ta.queue))
        assert send_and_recv() == base  # no-op plan: transparent
        ta.set_chaos(None)
        assert send_and_recv() == base  # healed: transparent
        # and a real fault actually bites: inbound block 1->0
        ta.set_chaos(ChaosShim(
            0, FaultPlan(2, seed=1).set_link(1, 0, block=True), ta.queue))
        assert tb.send_peer(0, MsgKind.ACCEPT, frame)
        tb.flush_all()
        with pytest.raises(queue.Empty):
            ta.queue.get(timeout=0.4)
        assert ta.chaos.counts()["blocked_in"] == 1
        assert ta.chaos_faults_total() == 1
        # outbound block swallows at the sender, reporting success
        tb.set_chaos(ChaosShim(
            1, FaultPlan(2, seed=1).set_link(1, 0, block=True), tb.queue))
        assert tb.send_peer(0, MsgKind.ACCEPT, frame)
        assert tb.chaos.counts()["blocked_out"] == 1
    finally:
        ta.stop()
        tb.stop()


def test_dial_peer_backoff_grows_per_peer():
    """Repeated refused dials double the per-peer suppression window
    (capped); suppressed vs refused are distinct tallies."""
    dead = free_ports(1)[0]  # nothing listening: connect refused fast
    addrs = [("127.0.0.1", free_ports(1)[0]), ("127.0.0.1", dead)]
    t = Transport(0, addrs)
    try:
        assert not t.dial_peer(1)  # refused
        assert t._dial_tallies["refused"] == 1
        assert not t.dial_peer(1)  # inside the grown window: suppressed
        assert t._dial_tallies["suppressed"] == 1
        w1 = t._dial_window[1]
        t._last_dial[1] = -1e9  # age out the window, fail again
        assert not t.dial_peer(1)
        assert t._dial_tallies["refused"] == 2
        assert t._dial_window[1] == min(2 * w1, t.DIAL_BACKOFF_CAP_S)
        # an inbound connection resets the backoff
        t._install_peer(1, _FakeSock())
        assert 1 not in t._dial_fails and 1 not in t._dial_window
    finally:
        t.stop()


class _FakeSock:
    def close(self):
        pass

    def recv(self, n):
        return b""  # read loop exits immediately

    def fileno(self):
        return -1


def test_backoff_sleeps_seeded_and_bounded():
    from minpaxos_tpu.runtime.master import backoff_sleeps

    def seq(seed, n=8):
        g = backoff_sleeps(0.05, 2.0, np.random.default_rng(seed))
        return [next(g) for _ in range(n)]

    assert seq(4) == seq(4)
    assert seq(4) != seq(5)
    for i, s in enumerate(seq(4)):
        nominal = min(0.05 * 2 ** i, 2.0)
        assert 0.5 * nominal <= s <= nominal
    assert max(seq(4, 12)) <= 2.0


# ------------------------------------------------- stable store CRC

def _mk_store(path, n=5, frontier=4):
    s = StableStore(str(path), sync=True)
    s.append_slots(np.arange(n), np.full(n, 16), np.full(n, 4),
                   np.ones(n), np.arange(n) * 10, np.arange(n) * 100,
                   np.arange(n), np.zeros(n))
    s.append_frontier(frontier)
    s.flush()
    s.close()


def test_store_crc_bit_flip_skipped_and_healed(tmp_path, capsys):
    """A flipped payload byte must be detected (CRC), skipped with a
    warning + counter, leave a non-committed hole, and converge once
    the records are re-appended (the peer re-send heal path)."""
    path = tmp_path / "store"
    _mk_store(path)
    raw = bytearray(path.read_bytes())
    raw[8 + 5 + 4 + 6] ^= 0xFF  # inside the first record's payload
    path.write_bytes(bytes(raw))
    r = StableStore(str(path))
    assert r.corrupt_records == 1
    assert "CRC mismatch" in capsys.readouterr().err
    # the whole slots batch was one record: its slots are holes now
    assert not r.is_committed(np.arange(5)).any()
    assert r.committed_prefix() == -1  # frontier record intact, no slots
    assert r.frontier == 4
    # peers re-send the commits: recovery converges
    n = 5
    r.append_slots(np.arange(n), np.full(n, 16), np.full(n, 4),
                   np.ones(n), np.arange(n) * 10, np.arange(n) * 100,
                   np.arange(n), np.zeros(n))
    r.flush()
    assert r.committed_prefix() == 4
    assert r.is_committed(np.arange(5)).all()
    r.close()
    # and the healed log replays clean
    r2 = StableStore(str(path))
    assert r2.corrupt_records == 1  # the flipped record is still there
    assert r2.committed_prefix() == 4
    r2.close()


def test_store_mid_log_truncation_converges(tmp_path):
    """A crash-truncated log replays its intact prefix; re-appending
    the lost tail (leader catch-up) converges to the full prefix."""
    path = tmp_path / "store"
    s = StableStore(str(path), sync=True)
    s.append_slots(np.arange(3), np.full(3, 16), np.full(3, 4),
                   np.ones(3), np.zeros(3), np.zeros(3), np.arange(3),
                   np.zeros(3))
    s.append_frontier(2)
    s.flush()
    size_after_first = path.stat().st_size
    s.append_slots(np.arange(3, 6), np.full(3, 16), np.full(3, 4),
                   np.ones(3), np.zeros(3), np.zeros(3), np.arange(3),
                   np.zeros(3))
    s.append_frontier(5)
    s.close()
    with open(path, "r+b") as f:  # cut into the second slots record
        f.truncate(size_after_first + 20)
    r = StableStore(str(path))
    assert r.committed_prefix() == 2
    assert r.corrupt_records == 0  # torn tail, not corruption
    r.append_slots(np.arange(3, 6), np.full(3, 16), np.full(3, 4),
                   np.ones(3), np.zeros(3), np.zeros(3), np.arange(3),
                   np.zeros(3))
    r.append_frontier(5)
    r.flush()
    assert r.committed_prefix() == 5
    r.close()
    r2 = StableStore(str(path))
    assert r2.committed_prefix() == 5 and r2.corrupt_records == 0
    r2.close()


def test_store_corrupt_length_field_resyncs_not_truncates(tmp_path,
                                                          capsys):
    """A flipped LENGTH byte mid-file declares a record that runs past
    EOF — indistinguishable from a torn tail at the break check. The
    CRC resync must recover every valid record after it; without it,
    the open-time torn-tail truncation would amplify one bad byte into
    irreversible loss of the whole (committed) suffix."""
    path = tmp_path / "store"
    s = StableStore(str(path), sync=True)
    s.append_slots(np.arange(3), np.full(3, 16), np.full(3, 4),
                   np.ones(3), np.zeros(3), np.zeros(3), np.arange(3),
                   np.zeros(3))
    s.append_frontier(2)
    s.append_slots(np.arange(3, 6), np.full(3, 16), np.full(3, 4),
                   np.ones(3), np.zeros(3), np.zeros(3), np.arange(3),
                   np.zeros(3))
    s.append_frontier(5)
    s.close()
    size = path.stat().st_size
    raw = bytearray(path.read_bytes())
    raw[12] |= 0x80  # first record's len u32 high byte: way past EOF
    path.write_bytes(bytes(raw))
    r = StableStore(str(path))
    assert r.corrupt_records == 1
    assert "resynced" in capsys.readouterr().err
    # the suffix survived: both frontiers and the second slots batch
    assert r.frontier == 5
    assert r.is_committed(np.arange(3, 6)).all()
    assert not r.is_committed(np.arange(3)).any()  # the lost record
    assert path.stat().st_size == size  # nothing truncated away
    # peers re-send the lost slots: recovery converges
    r.append_slots(np.arange(3), np.full(3, 16), np.full(3, 4),
                   np.ones(3), np.zeros(3), np.zeros(3), np.arange(3),
                   np.zeros(3))
    r.flush()
    assert r.committed_prefix() == 5
    r.close()
    r2 = StableStore(str(path))  # garbage still in place, still skipped
    assert r2.corrupt_records == 1 and r2.committed_prefix() == 5
    r2.close()


def test_store_v1_log_replays_and_appends_v1(tmp_path):
    """Pre-CRC (MPXL0001) files keep working: replay ignores the
    missing CRCs and appends stay in v1 framing so the file remains
    self-consistent."""
    path = tmp_path / "store"
    rec = np.zeros(3, SLOT_DT)
    rec["inst"] = np.arange(3)
    rec["ballot"] = 16
    rec["status"] = 4
    rec["val"] = [7, 8, 9]
    payload = rec.tobytes()
    with open(path, "wb") as f:
        f.write(MAGIC_V1)
        f.write(struct.pack("<BI", REC_SLOTS, len(payload)) + payload)
        f.write(struct.pack("<BI", REC_FRONTIER, 4) + struct.pack("<i", 2))
    s = StableStore(str(path))
    assert not s.crc_framing
    assert s.committed_prefix() == 2
    np.testing.assert_array_equal(s.read_range(0, 2)["val"], [7, 8, 9])
    s.append_slots([3], [16], [4], [1], [0], [10], [3], [0])
    s.append_frontier(3)
    s.close()
    r = StableStore(str(path))
    assert r.committed_prefix() == 3 and r.corrupt_records == 0
    r.close()


# ------------------------------------------------- recorder (v3 row)

def test_recorder_chaos_counter_track():
    from minpaxos_tpu.obs.recorder import (
        KIND_FULL,
        FlightRecorder,
        chrome_trace,
        validate_chrome_trace,
    )

    rec = FlightRecorder(8)
    rec.record(1_000_000, KIND_FULL, 1, 4, 4, 10, 0, 1, 2, 3, 0, 4, 5, 6,
               900_000)
    rec.record(3_000_000, KIND_FULL, 1, 4, 4, 11, 0, 1, 2, 3, 0, 4, 5, 6,
               2_900_000, chaos_faults=17)
    events = rec.to_events(pid=0)
    assert validate_chrome_trace(chrome_trace(events)) == []
    cs = [e for e in events if e["name"] == "chaos_faults"]
    assert len(cs) == 1 and cs[0]["args"]["chaos_faults"] == 17


# ------------------------------------------------------ integration

def test_partition_leader_stalls_heals_converges():
    """THE paxchaos scenario: partition the leader from the majority on
    a live cluster mid-workload — progress must stall (a minority
    leader committing would be the safety bug), the partition must
    inject real faults, and after healing the cluster must converge,
    resume committing, and pass every invariant (byte-identical
    committed prefixes, monotonic frontiers, linearizable per-key
    history, exactly-once replies)."""
    from minpaxos_tpu.chaos.campaign import run_schedule

    r = run_schedule("isolated_leader", seed=42, ops_n=150)
    assert r["ok"], r
    assert r["stall_observed"], r
    assert r["faults_injected"] > 0, r
    assert r["resumed_commits"] and r["converged"], r
    assert r["check"]["ok"] and r["check"]["violations"] == [], r
    assert r["duplicates"] == 0 and r["acked"] == r["expected"] > 0, r
