"""Ingress-coalescer policy units and the event-driven runtime's
equality contract (runtime/batches.py IngressCoalescer + the
runtime/replica.py exec chase).

Policy units drive the coalescer directly — no cluster, no sockets:
max-wait/max-rows boundaries, single-command dispatch, the cv kick,
and the admission-reject path are all observable through the queue
protocol plus the paxmon counters the coalescer registers.

The equality pin mirrors tests/test_pipeline.py: the event-driven
path's claim is RESCHEDULING, not approximation — coalesced ingress
plus the overlapped commit->exec->reply chase must produce
byte-identical replies and leaf-identical device state versus the
cadence-driven strict order (-nocoalesce -nooverlapexec), over a
randomized multi-tick trace.
"""

from __future__ import annotations

import queue
import threading
import time

import jax
import numpy as np
import pytest

from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.runtime.batches import IngressCoalescer
from minpaxos_tpu.runtime.replica import CONTROL, ReplicaServer, RuntimeFlags
from minpaxos_tpu.runtime.transport import FROM_CLIENT, FROM_PEER
from minpaxos_tpu.wire.messages import MsgKind, Op, make_batch

CID = 7

CFG = MinPaxosConfig(n_replicas=1, window=128, inbox=16, exec_batch=8,
                     kv_pow2=8, catchup_rows=8, recovery_rows=8,
                     gossip_ticks=1)


def _frame(rows: int, base: int = 0) -> np.ndarray:
    return make_batch(
        MsgKind.PROPOSE,
        cmd_id=(base + np.arange(rows)).astype(np.int32),
        op=np.full(rows, int(Op.PUT), np.uint8),
        key=np.arange(rows).astype(np.int64),
        val=np.arange(rows).astype(np.int64),
        timestamp=0)


def _client_item(rows: int, base: int = 0):
    return (FROM_CLIENT, CID, MsgKind.PROPOSE, _frame(rows, base))


# ------------------------------------------------- batch-formation policy


def test_single_command_dispatches_at_max_wait_not_poll_interval():
    """A lone command lingers AT MOST max_wait_us (counted as a
    deadline hit), never a poll interval: the whole point of the
    coalescer for the serial-latency story."""
    c = IngressCoalescer(max_wait_us=2000, max_rows=64)
    c.put(_client_item(1))
    t0 = time.perf_counter()
    src, cid, kind, rows = c.get(timeout=5.0)
    dt = time.perf_counter() - t0
    assert kind == MsgKind.PROPOSE and len(rows) == 1
    assert dt < 0.5  # 2 ms linger with wide scheduling slack
    assert c._c_deadline_hits.value == 1
    assert c.last_occupancy == 1
    assert c.empty()


def test_zero_max_wait_dispatches_immediately():
    c = IngressCoalescer(max_wait_us=0, max_rows=64)
    c.put(_client_item(1))
    c.get(timeout=1.0)
    assert c._c_deadline_hits.value == 0  # no linger, no deadline


def test_max_rows_boundary_skips_the_linger():
    """Pending rows >= max_rows: the batch is device-sized already —
    dispatch without waiting out max_wait (no deadline hit)."""
    c = IngressCoalescer(max_wait_us=10_000_000, max_rows=8)
    c.put(_client_item(8))
    t0 = time.perf_counter()
    c.get(timeout=1.0)
    assert time.perf_counter() - t0 < 1.0  # not the 10 s max-wait
    assert c._c_deadline_hits.value == 0
    assert c.last_occupancy == 8


def test_max_rows_boundary_one_below_lingers():
    """max_rows - 1 pending rows DOES linger (deadline hit): the
    boundary is >=, not >."""
    c = IngressCoalescer(max_wait_us=1000, max_rows=8)
    c.put(_client_item(7))
    c.get(timeout=1.0)
    assert c._c_deadline_hits.value == 1


def test_linger_accumulates_occupancy_across_frames():
    """Frames queued before the drain all count toward the drained
    batch's occupancy (the histogram sample), and FIFO order holds."""
    c = IngressCoalescer(max_wait_us=500, max_rows=256)
    for f in range(3):
        c.put(_client_item(4, base=f * 4))
    first = c.get(timeout=1.0)
    assert c.last_occupancy == 12  # all three frames were pending
    assert int(first[3]["cmd_id"][0]) == 0  # FIFO
    assert int(c.get_nowait()[3]["cmd_id"][0]) == 4
    assert int(c.get_nowait()[3]["cmd_id"][0]) == 8
    with pytest.raises(queue.Empty):
        c.get_nowait()


def test_cv_kick_wakes_a_parked_getter():
    """put() must wake a blocked get() immediately — the cadence
    replacement. The getter parks with a long timeout; the kick lands
    well before it."""
    c = IngressCoalescer(max_wait_us=0, max_rows=64)
    got: list = []

    def park():
        got.append(c.get(timeout=5.0))

    t = threading.Thread(target=park)
    t.start()
    time.sleep(0.05)  # let the getter park
    t0 = time.perf_counter()
    c.put(_client_item(1))
    t.join(timeout=2.0)
    assert not t.is_alive() and got
    assert time.perf_counter() - t0 < 1.0  # woke on the kick
    assert c._c_wakeups.value == 1


def test_get_timeout_raises_empty():
    c = IngressCoalescer(max_wait_us=0, max_rows=64)
    with pytest.raises(queue.Empty):
        c.get(timeout=0.01)


def test_non_client_items_carry_zero_row_weight():
    """CONTROL and peer frames pass through without counting toward
    the batch-formation policy (they are not coalescable proposals)."""
    c = IngressCoalescer(max_wait_us=10_000_000, max_rows=4)
    c.put((CONTROL, 0, "be_the_leader", None))
    c.put((FROM_PEER, 1, MsgKind.BEACON, _frame(4)))
    assert c.qsize() == 2 and c._pending_rows == 0
    t0 = time.perf_counter()
    assert c.get(timeout=1.0)[2] == "be_the_leader"
    assert time.perf_counter() - t0 < 1.0  # zero pending rows: no linger
    assert c._c_deadline_hits.value == 0


# ------------------------------------------------------ admission control


def test_backpressure_reject_counts_and_drops():
    """Gate True + pending beyond max_rows: the put is DROPPED and
    counted — bounded queueing, the client's retransmit recovers."""
    c = IngressCoalescer(max_wait_us=0, max_rows=4,
                         admit_gate=lambda: True)
    c.put(_client_item(4))       # fills the bound
    c.put(_client_item(4, 100))  # beyond the bound: shed
    assert c._c_rejects.value == 4
    assert c.qsize() == 1
    assert c._pending_rows == 4


def test_admission_gate_false_admits_beyond_bound():
    """A healthy replica (gate False) never sheds: the bound only
    engages under the overload verdict."""
    c = IngressCoalescer(max_wait_us=0, max_rows=4,
                         admit_gate=lambda: False)
    c.put(_client_item(4))
    c.put(_client_item(4, 100))
    assert c._c_rejects.value == 0
    assert c.qsize() == 2


def test_admission_never_sheds_control_or_peer_traffic():
    """Only client PROPOSE rows are sheddable: protocol traffic and
    control events must get through no matter how hot the gate is."""
    c = IngressCoalescer(max_wait_us=0, max_rows=1,
                         admit_gate=lambda: True)
    c.put(_client_item(1))
    c.put((CONTROL, 0, "be_the_leader", None))
    c.put((FROM_PEER, 1, MsgKind.ACCEPT, _frame(8)))
    assert c.qsize() == 3
    assert c._c_rejects.value == 0


def test_paxmon_metrics_registered():
    from minpaxos_tpu.obs.metrics import MetricsRegistry

    m = MetricsRegistry(namespace="test")
    c = IngressCoalescer(max_wait_us=500, max_rows=8, metrics=m)
    c.put(_client_item(3))
    c.get(timeout=1.0)
    snap = m.snapshot()
    counters = dict(snap.get("counters") or {})
    counters.update(snap.get("gauges") or {})
    assert counters.get("coalesce_deadline_hits") == 1
    assert counters.get("coalesce_pending_rows") == 0
    hist = (snap.get("histograms") or {}).get("coalesce_batch_rows")
    assert hist and hist["count"] == 1


# --------------------------------------- strict vs event-driven equality


def _mk_server(tmp_path, name: str, event_driven: bool) -> ReplicaServer:
    d = tmp_path / name
    d.mkdir()
    flags = RuntimeFlags(store_dir=str(d), coalesce=event_driven,
                         overlap_exec=event_driven,
                         coalesce_wait_us=200)
    return ReplicaServer(0, [("127.0.0.1", 7077)], CFG, flags)


def _capture_replies(srv: ReplicaServer, log: list) -> None:
    srv.transport.send_client = (  # type: ignore[method-assign]
        lambda cid, kind, rows: log.append((cid, int(kind), rows.copy()))
        or True)


def _elect(srv: ReplicaServer) -> None:
    srv.queue.put((CONTROL, 0, "be_the_leader", None))
    for _ in range(20):
        if srv._drain(0.001):
            srv._become_leader()
        srv._device_tick(srv.inbox)
        if srv.snapshot["prepared"]:
            return
    raise AssertionError(f"never prepared: {srv.snapshot}")


def _trace(n_frames: int, rows: int, seed: int) -> list[np.ndarray]:
    rng = np.random.default_rng(seed)
    out = []
    for f in range(n_frames):
        ops = rng.choice([int(Op.PUT), int(Op.GET)], size=rows,
                         p=[0.7, 0.3])
        out.append(make_batch(
            MsgKind.PROPOSE,
            cmd_id=(1000 + f * rows + np.arange(rows)).astype(np.int32),
            op=ops.astype(np.uint8),
            key=rng.integers(0, 40, rows).astype(np.int64),
            val=rng.integers(1, 1 << 20, rows).astype(np.int64),
            timestamp=0))
    return out


def _run_trace_ticks(srv: ReplicaServer, trace: list[np.ndarray],
                     n_ticks: int) -> list:
    """Drive the REAL ``_tick`` (drain + dispatch + exec chase) — not
    the bare _drain/_device_tick pair test_pipeline uses — so the
    event-driven server exercises its chase and the strict server its
    cadence, over identical queued input."""
    replies: list = []
    _capture_replies(srv, replies)
    _elect(srv)
    for frame in trace:
        srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE, frame))
    for _ in range(n_ticks):
        srv._tick()
    srv._flush_inflight()
    return replies


def test_event_driven_equals_strict_order_over_randomized_trace(tmp_path):
    """Byte-exact replies (content and per-connection order) and
    leaf-identical device state: coalescer+chase ON vs OFF, same
    trace. The event-driven run must actually coalesce (wakeups or
    drained occupancy observed) and chase (more dispatches per wakeup
    than ticks), else this proves nothing."""
    trace = _trace(n_frames=6, rows=CFG.inbox, seed=11)
    n_ticks = 3 * len(trace) + 12
    srv_e = _mk_server(tmp_path, "event", event_driven=True)
    srv_s = _mk_server(tmp_path, "strict", event_driven=False)
    try:
        rep_e = _run_trace_ticks(srv_e, trace, n_ticks)
        rep_s = _run_trace_ticks(srv_s, trace, n_ticks)
        assert srv_e.coalescer is not None
        assert srv_s.coalescer is None
        # both runs fully drained the trace
        for srv in (srv_e, srv_s):
            assert srv.stats["executed"] == 6 * CFG.inbox, srv.stats
        n_cmds = sum(len(rep[2]["cmd_id"]) for rep in rep_e
                     if rep[1] == int(MsgKind.PROPOSE_REPLY))
        assert n_cmds == 6 * CFG.inbox
        assert len(rep_e) == len(rep_s), (len(rep_e), len(rep_s))
        for i, ((ca, ka, ra), (cb, kb, rb)) in enumerate(zip(rep_e, rep_s)):
            assert (ca, ka) == (cb, kb), i
            for f in ra.dtype.names:
                if f == "timestamp":
                    continue  # wall-clock stamp: the one intended delta
                np.testing.assert_array_equal(
                    ra[f], rb[f], err_msg=f"reply {i} field {f}")
        assert srv_e.snapshot == srv_s.snapshot
        for leaf_e, leaf_s in zip(
                jax.tree_util.tree_leaves(srv_e.state),
                jax.tree_util.tree_leaves(srv_s.state)):
            np.testing.assert_array_equal(np.asarray(leaf_e),
                                          np.asarray(leaf_s))
    finally:
        srv_e.store.close()
        srv_s.store.close()


def test_exec_chase_runs_followups_in_one_wakeup(tmp_path):
    """The chase's observable effect: after one _tick on a committed
    backlog with an empty queue, execution has caught the frontier —
    the strict server needs further ticks for the same progress."""
    srv = _mk_server(tmp_path, "chase", event_driven=True)
    _capture_replies(srv, [])
    try:
        _elect(srv)
        srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE,
                       _frame(CFG.inbox, base=1000)))
        srv._tick()  # drains, dispatches, then chases the exec backlog
        srv._flush_inflight()
        snap = srv.snapshot
        assert snap["frontier"] >= 0
        assert int(snap.get("executed", -1)) == int(snap["frontier"]), snap
    finally:
        srv.store.close()


def test_recorder_carries_coalescer_fields(tmp_path):
    """Schema-v7 rows: drained occupancy and the cumulative wakeup
    count ride the flight recorder on the event-driven server."""
    from minpaxos_tpu.obs.recorder import F_COAL_OCC, F_COAL_WAKE

    srv = _mk_server(tmp_path, "rec", event_driven=True)
    _capture_replies(srv, [])
    try:
        _elect(srv)
        for f in range(3):
            srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE,
                           _frame(CFG.inbox, base=1000 + f * CFG.inbox)))
        for _ in range(12):
            srv._tick()
        srv._flush_inflight()
        rows = srv.recorder.snapshot()
        assert rows.shape[1] >= F_COAL_WAKE + 1
        assert (rows[:, F_COAL_OCC] > 0).any()  # some tick drained rows
        wake = rows[:, F_COAL_WAKE]
        assert (np.diff(wake[wake > 0]) >= 0).all()  # cumulative counter
    finally:
        srv.store.close()


def test_nocoalesce_cli_flags_reach_runtime_flags():
    """cli/server.py wires the ISSUE-15 escape hatches into
    RuntimeFlags (source-text pin, like -nopipeline's)."""
    import inspect

    from minpaxos_tpu.cli import server as cli_server

    src = inspect.getsource(cli_server)
    assert "-nocoalesce" in src
    assert "coalesce=not args.nocoalesce" in src
    assert "-nooverlapexec" in src
    assert "overlap_exec=not args.nooverlapexec" in src
    assert "-coalesce-wait-us" in src
    assert "coalesce_wait_us=args.coalesce_wait_us" in src
