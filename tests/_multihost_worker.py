"""Worker process for the two-process SPMD test (run via subprocess).

Usage: python _multihost_worker.py <coordinator_port> <process_id> <out_file>

Each of the 2 processes owns 4 virtual CPU devices; the global mesh is
8 devices along 'shard'. Every process runs the SAME fused program;
each asserts commits on its OWN addressable slice, then writes a JSON
line to its out_file. This is the real multi-controller shape of
parallel/multihost.py — the degenerate single-process test can't catch
a mesh/addressability bug.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def main() -> None:
    port, pid, out_file = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
    from minpaxos_tpu.parallel import multihost
    from minpaxos_tpu.models.minpaxos import MinPaxosConfig
    from minpaxos_tpu.parallel.sharded import (
        elect_all,
        init_sharded,
        make_propose_ext,
        sharded_step,
    )

    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=2, process_id=pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 8, jax.devices()

    mesh = multihost.global_shard_mesh(1)
    n_shards = 8
    my_slice = multihost.process_shard_slice(n_shards)

    cfg = MinPaxosConfig(n_replicas=3, window=128, inbox=128,
                         exec_batch=32, kv_pow2=8, catchup_rows=8,
                         recovery_rows=8)
    ss = init_sharded(cfg, n_shards, mesh)
    ss = elect_all(cfg, ss, 0)

    quiet = make_propose_ext(cfg, n_shards, cfg.inbox, 0,
                             jnp.int32(0), jnp.int32(0))
    ext = make_propose_ext(cfg, n_shards, cfg.inbox, 16,
                           jnp.int32(0), jnp.int32(1))
    for e in (quiet, quiet, ext, quiet, quiet, quiet):
        ss, execr, _, _ = sharded_step(cfg, ss, e)

    upto = ss.states.committed_upto[:, 0]
    local = np.concatenate(
        [np.asarray(s.data).reshape(-1) for s in upto.addressable_shards])
    rec = {
        "process": pid,
        "n_local_shards": int(local.size),
        "min_committed": int(local.min()),
        "my_slice": [my_slice.start, my_slice.stop],
        "ok": bool(local.size == 4 and (local >= 15).all()),
    }
    with open(out_file, "w") as f:
        f.write(json.dumps(rec) + "\n")


if __name__ == "__main__":
    main()
