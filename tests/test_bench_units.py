"""Unit tests for bench.py's measurement helpers.

The headline latency numbers are RECONSTRUCTED from per-round cursor
histories (slot injected when crt_inst first passes it, committed when
committed_upto first reaches it) — a bug here misreports the benchmark
without failing it, so the reconstruction gets its own oracle tests.
Also covers the sibling-offset port allocator the TCP harnesses use.
"""

from __future__ import annotations

import socket

import numpy as np

import bench
from minpaxos_tpu.utils.netutil import free_ports


def test_latency_single_shard_hand_computed():
    # row 0 is the pre-phase baseline cursor; rows 1.. are rounds
    crts = np.array([[0], [2], [4], [4], [4]])   # 0-1 in r1, 2-3 in r2
    uptos = np.array([[-1], [-1], [1], [2], [3]])  # 0-1 @r2, 2 @r3, 3 @r4
    p50, p99, n, unc = bench._latency_rounds(uptos, crts, round_ms=1.0)
    # slot0: in r1 c r2 -> 2; slot1: 2; slot2: in r2 c r3 -> 2;
    # slot3: in r2 c r4 -> 3
    assert n == 4 and unc == 0
    assert p50 == 2.0
    assert np.isclose(p99, np.percentile([2, 2, 2, 3], 99))


def test_latency_same_round_inject_commit_is_one_round():
    crts = np.array([[0], [3]])
    uptos = np.array([[-1], [2]])
    p50, p99, n, unc = bench._latency_rounds(uptos, crts, round_ms=2.5)
    assert n == 3 and unc == 0
    assert p50 == 2.5 and p99 == 2.5  # 1 round at 2.5 ms/round


def test_latency_slots_before_baseline_excluded():
    # slots 0-4 were assigned before the measured phase (baseline crt=5)
    crts = np.array([[5], [7]])
    uptos = np.array([[-1], [6]])
    p50, p99, n, unc = bench._latency_rounds(uptos, crts, round_ms=1.0)
    assert n == 2 and unc == 0  # only slots 5, 6 enter the sample


def test_latency_uncommitted_tail_reported_not_sampled():
    crts = np.array([[0], [5], [10]])
    uptos = np.array([[-1], [4], [6]])  # slots 7-9 assigned, never committed
    p50, p99, n, unc = bench._latency_rounds(uptos, crts, round_ms=1.0)
    assert unc == 3
    assert n == 7  # slots 0-6 committed and sampled


def test_latency_from_hist_hand_computed():
    """Resident-loop histogram percentiles: bin b = latency b+1 rounds;
    the sample reconstructs exactly, so percentiles match
    np.percentile of the explicit per-slot latencies."""
    hist = np.zeros(16, np.int32)
    hist[1] = 3  # three slots at 2 rounds
    hist[2] = 1  # one slot at 3 rounds
    p50, p99, n, overflow = bench._latency_from_hist(hist, round_ms=2.0)
    assert n == 4 and overflow == 0
    assert p50 == np.percentile(np.array([2, 2, 2, 3]) * 2.0, 50)
    assert p99 == np.percentile(np.array([2, 2, 2, 3]) * 2.0, 99)


def test_latency_from_hist_empty_and_overflow():
    p50, p99, n, overflow = bench._latency_from_hist(
        np.zeros(8, np.int32), 1.0)
    assert n == 0 and overflow == 0 and np.isnan(p50) and np.isnan(p99)
    hist = np.zeros(4, np.int32)
    hist[-1] = 5  # tail beyond the bin range: counted, reported
    p50, p99, n, overflow = bench._latency_from_hist(hist, 1.0)
    assert n == 5 and overflow == 5
    assert p50 == 4.0  # clipped AT the last bin, never dropped


def test_trend_reads_committed_artifacts():
    """tools/trend.py (report-only): the cross-PR trajectory view
    parses every committed BENCH_r*.json driver capture — including
    the crashed (r01) and truncated-replay (r05) ones, which must
    surface as labeled rows, never silent skips — and renders a
    markdown table."""
    import json as _json
    import subprocess
    import sys as _sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    out = subprocess.run(
        [_sys.executable, str(repo / "tools/trend.py"), "--json"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    rows = _json.loads(out.stdout)["bench"]
    names = {r["artifact"] for r in rows}
    committed = {p.name for p in repo.glob("BENCH_r*.json")}
    assert committed <= names  # nothing silently skipped
    assert all("provenance" in r for r in rows)
    out = subprocess.run(
        [_sys.executable, str(repo / "tools/trend.py")],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    assert "| artifact |" in out.stdout


def test_overflow_warning_is_loud_and_parser_safe():
    """A saturated histogram must warn on STDOUT (the artifact stamp
    alone was missable) without corrupting the one-JSON-line contract:
    the line cannot start with '{' (salvage_partial / the ladder
    driver filter on that) and must name the count."""
    assert bench.overflow_warning(0) is None
    w = bench.overflow_warning(37)
    assert w.startswith("WARNING") and not w.startswith("{")
    assert "latency_hist_overflow=37" in w and "SATURATED" in w


def test_latency_hist_agrees_with_latency_rounds():
    """The two latency paths are the same estimator: build a cursor
    history, compute host-side percentiles, then bin the same per-slot
    latencies into a histogram and compare bit-for-bit."""
    crts = np.array([[0], [2], [4], [4], [4]])
    uptos = np.array([[-1], [-1], [1], [2], [3]])
    p50_a, p99_a, n_a, _ = bench._latency_rounds(uptos, crts, 1.5)
    hist = np.zeros(512, np.int32)
    for lat in (2, 2, 2, 3):  # hand-derived from the history above
        hist[lat - 1] += 1
    p50_b, p99_b, n_b, _ = bench._latency_from_hist(hist, 1.5)
    assert (p50_a, p99_a, n_a) == (p50_b, p99_b, n_b)


def test_latency_round_ms_scales_linearly():
    rng = np.random.default_rng(3)
    # monotone random cursor walk, 3 shards
    crts = np.cumsum(rng.integers(0, 5, (20, 3)), axis=0)
    uptos = np.maximum(crts - rng.integers(1, 6, (20, 3)), -1)
    uptos[-1] = crts[-1] - 1  # drained
    a = bench._latency_rounds(uptos, crts, 1.0)
    b = bench._latency_rounds(uptos, crts, 7.0)
    assert np.isclose(b[0], 7 * a[0]) and np.isclose(b[1], 7 * a[1])
    assert a[2] == b[2] and a[3] == b[3] == 0


def test_free_ports_sibling_reserved():
    ports = free_ports(3, sibling_offset=1000)
    assert len(set(ports)) == 3
    for p in ports:
        for q in (p, p + 1000):
            s = socket.socket()
            try:
                s.bind(("127.0.0.1", q))  # both halves actually free
            finally:
                s.close()


def test_free_ports_collision_skipped():
    # hold some port's sibling; allocator must never hand out that port
    held = socket.socket()
    held.bind(("127.0.0.1", 0))
    blocked_sibling = held.getsockname()[1]
    try:
        ports = free_ports(20, sibling_offset=1000)
        assert blocked_sibling - 1000 not in ports
    finally:
        held.close()


def test_keybuf_amortized_append_and_view():
    from minpaxos_tpu.models.cluster import KeyBuf, pack_reply_key

    kb = KeyBuf()
    expect = []
    for i in range(40):  # crosses several doubling boundaries
        keys = pack_reply_key(i % 5, np.arange(i * 31, i * 31 + 17))
        kb.append(keys)
        expect.append(np.atleast_1d(keys))
    got = kb.view()
    ref = np.concatenate(expect)
    assert got.dtype == np.int64 and np.array_equal(got, ref)
    # scalar append path
    kb2 = KeyBuf()
    kb2.append(pack_reply_key(7, 9))
    assert kb2.view().tolist() == [(7 << 32) | 9]


def test_wait_for_backend_retries_then_gives_up():
    from minpaxos_tpu.utils.backend import wait_for_backend

    calls = []

    def dead_probe(t):
        calls.append(t)
        return None

    sleeps = []
    out = wait_for_backend(attempts=3, probe=dead_probe,
                           sleep=sleeps.append, retry_sleep_s=7)
    assert out is None and len(calls) == 3
    assert sleeps == [7, 7]  # no sleep after the final attempt

    # recovers mid-way
    seq = iter([None, "axon"])
    out = wait_for_backend(attempts=5, probe=lambda t: next(seq),
                           sleep=lambda s: None)
    assert out == "axon"

    # cpu-only backend rejected when a real chip is required...
    out = wait_for_backend(attempts=2, probe=lambda t: "cpu",
                           sleep=lambda s: None)
    assert out is None
    # ...but accepted when not
    out = wait_for_backend(attempts=1, probe=lambda t: "cpu",
                           want_non_cpu=False)
    assert out == "cpu"


def test_probe_backend_real_subprocess_cpu():
    """probe_backend spawns a real python; with the CPU platform pinned
    it must report 'cpu'. The child env strips PYTHONPATH: the tunnel's
    sitecustomize rides PYTHONPATH and dials the relay at import time
    even under JAX_PLATFORMS=cpu, so inheriting it makes this test of
    the outage PLAYBOOK fail exactly when the relay is down (round-4
    verdict weak #5)."""
    import os

    from minpaxos_tpu.utils.backend import probe_backend

    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    assert probe_backend(timeout_s=120.0, env=env) == "cpu"


def test_keybuf_contains_matches_isin():
    from minpaxos_tpu.models.cluster import KeyBuf, pack_reply_key

    kb = KeyBuf()
    assert not kb.contains(np.asarray([1, 2, 3])).any()  # empty buffer
    rng = np.random.default_rng(7)
    for i in range(5):  # interleave appends and probes (cache refresh)
        kb.append(pack_reply_key(i, rng.integers(0, 1000, size=50)))
        probe = pack_reply_key(rng.integers(0, 6, size=200),
                               rng.integers(0, 1200, size=200))
        assert np.array_equal(kb.contains(probe),
                              np.isin(probe, kb.view()))


def test_pack_reply_key_no_collisions_across_clients():
    from minpaxos_tpu.models.cluster import pack_reply_key

    a = pack_reply_key(1, np.arange(1000))
    b = pack_reply_key(2, np.arange(1000))
    assert len(np.intersect1d(a, b)) == 0
    # cmd_id is masked to 32 bits; same (cid, mid) always packs equal
    assert pack_reply_key(3, 5) == pack_reply_key(3, 5)


def test_free_ports_impossible_request_raises():
    import pytest

    with pytest.raises(OSError):
        # no port p can have p+70000 as a sibling (> 65535)
        free_ports(1, sibling_offset=70000)


def test_salvage_partial_prefers_last_parseable_tpu_record():
    import bench

    good = ('{"value": 37700.0, "platform": "tpu", '
            '"partial": "healthy_phase_only"}')
    # truncated final line (child killed mid-write) falls back to the
    # earlier complete record
    out = ("[noise]\n" + good + "\n" + '{"value": 999').encode()
    assert bench.salvage_partial(out) == good
    # a CPU fallback record must never masquerade as a TPU headline
    assert bench.salvage_partial(
        b'{"value": 1.0, "platform": "cpu"}') is None
    assert bench.salvage_partial(None) is None
    assert bench.salvage_partial(b"no json here") is None
    # error records are not salvageable
    assert bench.salvage_partial(
        b'{"value": 0.0, "platform": "tpu", "error": "boom"}') is None


def test_ladder_merges_first_rung_fault_leg(monkeypatch):
    """Bigger rungs skip kill/recover (worker-crash risk); the ladder
    must carry rung 0's measured leg into the winning record."""
    import json
    import subprocess
    import types

    import bench

    recs = [
        {"value": 100.0, "platform": "tpu",
         "kill_recover": {"victim": 2, "dip_pct": 1.0}},
        {"value": 200.0, "platform": "tpu",
         "kill_recover": {"skipped": "first rung only"}},
        {"value": 300.0, "platform": "tpu",
         "kill_recover": {"skipped": "first rung only"}},
    ]
    calls = []

    def fake_run(cmd, env=None, stdout=None, timeout=None):
        i = len(calls)
        calls.append(env.get("MP_BENCH_FAULT"))
        return types.SimpleNamespace(
            returncode=0, stdout=(json.dumps(recs[i]) + "\n").encode())

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench, "_wait_for_backend", lambda **kw: "tpu")
    out = []
    monkeypatch.setattr("builtins.print", lambda *a, **kw: out.append(a))
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.delenv("MP_BENCH_CHILD", raising=False)
    bench.main()
    # fault leg requested only at rung 0
    assert calls == ["1", "0", "0"]
    final = json.loads(out[-1][0])
    assert final["value"] == 300.0  # biggest rung wins
    # ...but carries rung 0's measured kill/recover
    assert final["kill_recover"]["victim"] == 2
    assert final["kill_recover"]["measured_at_shape"] == [64, 2048, 256, 16]


def test_load_prior_tpu_record_hermetic(tmp_path):
    """load_prior_tpu_record picks the newest parseable real-TPU record,
    skips error/CPU records, and stamps the file's own mtime (so a
    stale artifact can never masquerade as a fresh measurement)."""
    import json
    import os
    import time as _time

    import bench

    assert bench.load_prior_tpu_record(str(tmp_path)) is None
    (tmp_path / ".bench_tpu_old.json").write_text(
        json.dumps({"value": 1.0, "platform": "tpu"}) + "\n")
    (tmp_path / ".bench_tpu_err.json").write_text(
        json.dumps({"value": 0.0, "platform": "tpu", "error": "x"}) + "\n")
    (tmp_path / ".bench_tpu_cpu.json").write_text(
        json.dumps({"value": 2.0, "platform": "cpu"}) + "\n")
    now = _time.time()
    os.utime(tmp_path / ".bench_tpu_old.json", (now - 100, now - 100))
    os.utime(tmp_path / ".bench_tpu_err.json", (now - 1, now - 1))
    os.utime(tmp_path / ".bench_tpu_cpu.json", (now - 2, now - 2))
    prior = bench.load_prior_tpu_record(str(tmp_path))
    # newest files are error/cpu (skipped); the real record wins
    assert prior["record"]["value"] == 1.0
    assert prior["file"] == ".bench_tpu_old.json"
    assert "NOT this run" in prior["note"] and prior["file_mtime_utc"]


def test_failed_ladder_attaches_prior_tpu_record(monkeypatch):
    """When every rung fails, the failure record carries the saved
    prior TPU measurement as labeled context; the live headline stays
    honestly 0.0, with the unmissable top-level markers: a
    measured_this_run=false flag and the replay file's mtime sitting
    NEXT TO the value fields (VERDICT round-5 item 8 — a BENCH_rN
    produced on a dead relay must not be misread as fresh)."""
    import json
    import types

    import bench

    def fake_run(cmd, env=None, stdout=None, timeout=None):
        if env.get("JAX_PLATFORMS") == "cpu":  # the cpu-reference child
            return types.SimpleNamespace(returncode=0, stdout=b"{}")
        return types.SimpleNamespace(returncode=1, stdout=b"")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench, "_wait_for_backend", lambda **kw: "tpu")
    monkeypatch.setattr(
        bench, "load_prior_tpu_record",
        lambda repo_dir=None: {"file": "x.json",
                               "file_mtime_utc": "2026-07-31T04:36:00Z",
                               "record": {"value": 9.0}})
    out = []
    monkeypatch.setattr("builtins.print", lambda *a, **kw: out.append(a))
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.delenv("MP_BENCH_CHILD", raising=False)
    bench.main()
    final = json.loads(out[-1][0])
    assert final["value"] == 0.0 and final["error"]
    assert final["prior_tpu_record"]["record"]["value"] == 9.0
    # top-level self-description: not measured, and the replay's age
    # is right next to the (zero) value
    assert final["measured_this_run"] is False
    assert final["replayed_value"] == 9.0
    assert final["replayed_record_mtime_utc"] == "2026-07-31T04:36:00Z"


def test_failure_record_marks_not_measured_without_replay(monkeypatch):
    """A failure record with NO prior artifact still carries
    measured_this_run=false and no replay fields."""
    import json

    import bench

    out = []
    monkeypatch.setattr("builtins.print", lambda *a, **kw: out.append(a))
    bench._failure("probe", "backend unreachable")
    rec = json.loads(out[-1][0])
    assert rec["value"] == 0.0
    assert rec["measured_this_run"] is False
    assert "replayed_record_mtime_utc" not in rec


def test_fresh_ladder_record_marks_measured(monkeypatch):
    """A successful rung's record says measured_this_run=true — the
    positive half of the self-description contract."""
    import json
    import types

    import bench

    rec = {"value": 100.0, "platform": "tpu", "measured_this_run": True,
           "kill_recover": {"victim": 2}}

    def fake_run(cmd, env=None, stdout=None, timeout=None):
        return types.SimpleNamespace(
            returncode=0, stdout=(json.dumps(rec) + "\n").encode())

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench, "_wait_for_backend",
                        lambda **kw: "tpu" if not out else None)
    out = []
    monkeypatch.setattr("builtins.print", lambda *a, **kw: out.append(a))
    monkeypatch.setenv("JAX_PLATFORMS", "tpu")
    monkeypatch.delenv("MP_BENCH_CHILD", raising=False)
    bench.main()
    final = json.loads(out[-1][0])
    assert final["value"] == 100.0
    assert final["measured_this_run"] is True


# -- shape_ladder adaptive-capacity policy (PR 11): pure helpers, no
# compile — the measured behavior is gated by the tier-1 ladder smoke


def test_adaptive_capacity_policy():
    from tools.shape_ladder import adaptive_capacity

    # hwm + 25% headroom, rounded up to 32; floor of 64
    assert adaptive_capacity(49) == 96
    assert adaptive_capacity(0) == 64
    assert adaptive_capacity(1281) == 1632
    for hwm in (1, 31, 32, 100, 500, 4096):
        cap = adaptive_capacity(hwm)
        assert cap % 32 == 0 and cap >= hwm + hwm // 4
        assert cap >= 64


def test_ladder_legality_contract():
    """Base points keep the PR-8/9 bar (drain-exact); adaptive points
    must additionally show no capacity-attributable loss — absolute
    lossless OR equal-to-base committed totals (deep-pipeline shapes
    bounce proposals off the full window at ANY capacity)."""
    from tools.shape_ladder import _legal

    base_lossy = {"drained_exact": True, "lossless": False}
    assert _legal(base_lossy)  # window bounce, not a capacity fault
    assert not _legal({"drained_exact": False, "lossless": True})
    assert not _legal({"drained_exact": True, "error": "boom"})
    adaptive_clean = {"drained_exact": True, "adaptive": True,
                      "lossless": True}
    assert _legal(adaptive_clean)
    adaptive_vs_base = {"drained_exact": True, "adaptive": True,
                        "lossless": False, "lossless_vs_base": True}
    assert _legal(adaptive_vs_base)
    adaptive_lossy = {"drained_exact": True, "adaptive": True,
                      "lossless": False}
    assert not _legal(adaptive_lossy)  # capacity dropped proposals
    mencius_base = {"drained_exact": True, "lossless": None}
    assert _legal(mencius_base)
