"""Native (C++) layer: build, scan parity with the Python decoder,
corrupt-stream latching, cycle clock — and the measured win.

The library is optional everywhere; these tests build it (skipping if
no g++) and check the native StreamDecoder path is bit-identical to
the pure-Python one, including the latch-after-partial-results corrupt
semantics. Counterpart of the reference's rdtsc shim (rdtsc.s:1-8),
plus the frame scan that replaces codec.py's per-frame header loop.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from minpaxos_tpu import native
from minpaxos_tpu.native import build as native_build
from minpaxos_tpu.wire import codec
from minpaxos_tpu.wire.messages import MsgKind, make_batch


@pytest.fixture(scope="module")
def lib():
    path = native_build.build(quiet=True)
    if path is None:
        pytest.skip("no g++ toolchain")
    # (re)bind in-process if the module was imported pre-build
    if native.libnative is None:
        import importlib

        importlib.reload(native)
    assert native.libnative is not None
    return native.libnative


def _frames(rng, n):
    out = []
    for _ in range(n):
        pick = rng.integers(0, 3)
        if pick == 0:
            out.append(codec.encode_frame(MsgKind.PREPARE, make_batch(
                MsgKind.PREPARE, leader_id=int(rng.integers(0, 5)),
                ballot=int(rng.integers(0, 1 << 20)),
                last_committed=int(rng.integers(-1, 100)))))
        elif pick == 1:
            k = int(rng.integers(1, 6))
            out.append(codec.encode_frame(MsgKind.ACCEPT, make_batch(
                MsgKind.ACCEPT, inst=np.arange(k), ballot=7, op=1,
                key=rng.integers(0, 1 << 40, k), val=rng.integers(0, 9, k),
                cmd_id=np.arange(k), client_id=3, leader_id=0,
                last_committed=-1)))
        else:
            out.append(codec.encode_frame(MsgKind.BEACON, make_batch(
                MsgKind.BEACON, rid=1,
                timestamp=int(rng.integers(0, 1 << 60)))))
    return out


def _drain(dec, data, rng):
    got = []
    i = 0
    while i < len(data):
        step = int(rng.integers(1, 64))
        got += dec.feed(data[i:i + step])
        i += step
    return got


def test_scan_parity_random_chunking(lib):
    rng = np.random.default_rng(0)
    data = b"".join(_frames(rng, 200))
    nat = _drain(codec.StreamDecoder(), data, np.random.default_rng(1))
    pyd = codec.StreamDecoder()
    with pytest.MonkeyPatch.context() as mp:
        mp.setattr(codec._native, "libnative", None)
        py = _drain(pyd, data, np.random.default_rng(1))
    assert len(nat) == len(py) == 200
    for (k1, r1), (k2, r2) in zip(nat, py):
        assert k1 == k2
        assert r1.tobytes() == r2.tobytes()


def test_scan_corrupt_latches_after_partial_results(lib):
    good = codec.encode_frame(MsgKind.PREPARE, make_batch(
        MsgKind.PREPARE, leader_id=0, ballot=1, last_committed=-1))
    for bad in (b"\x00aaaa", b"\xf0aaaa",
                b"\x01" + (1 << 30).to_bytes(4, "little")):
        dec = codec.StreamDecoder()
        got = dec.feed(good + bad)
        assert len(got) == 1 and got[0][0] == MsgKind.PREPARE
        assert isinstance(dec.error, ValueError)
        with pytest.raises(ValueError):
            dec.feed(b"")


def test_scan_empty_and_partial_tail(lib):
    dec = codec.StreamDecoder()
    assert dec.feed(b"") == []
    frame = codec.encode_frame(MsgKind.PREPARE, make_batch(
        MsgKind.PREPARE, leader_id=0, ballot=9, last_committed=2))
    assert dec.feed(frame[:3]) == []
    assert dec.pending_bytes() == 3
    got = dec.feed(frame[3:])
    assert len(got) == 1 and got[0][1]["ballot"][0] == 9
    assert dec.pending_bytes() == 0


def test_cputicks_monotonic_and_cheap(lib):
    t = [lib.mp_cputicks() for _ in range(100)]
    assert all(b >= a for a, b in zip(t, t[1:]))
    assert lib.mp_monotonic_ns() > 0


def test_scan_speedup_measured(lib):
    """The win the native layer exists for: many small frames. Prints
    the measured ratio; asserts only that the native path is not
    pathologically slower (timing on shared CI is noisy)."""
    rng = np.random.default_rng(2)
    data = b"".join(_frames(rng, 50) * 100)  # ~5000 small frames

    def run(native_on):
        dec = codec.StreamDecoder()
        with pytest.MonkeyPatch.context() as mp:
            if not native_on:
                mp.setattr(codec._native, "libnative", None)
            t0 = time.perf_counter()
            n = len(dec.feed(data))
            dt = time.perf_counter() - t0
        assert n == 5000
        return dt

    run(True), run(False)  # warm
    t_nat = min(run(True) for _ in range(3))
    t_py = min(run(False) for _ in range(3))
    print(f"\nnative scan: {t_nat * 1e3:.2f}ms  python: {t_py * 1e3:.2f}ms "
          f" speedup x{t_py / t_nat:.1f} (5000 frames)")
    assert t_nat < t_py * 1.5
