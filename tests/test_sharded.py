"""Sharded-Paxos over the virtual 8-device CPU mesh.

Validates the north-star path (BASELINE.md): many independent groups
advanced by one jitted step, shard axis partitioned over real (virtual)
devices, commits flowing in every shard, failure masking per shard.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.parallel import ShardedCluster, make_mesh
from minpaxos_tpu.parallel.sharded import init_sharded, elect_all, sharded_step


SMALL = MinPaxosConfig(
    n_replicas=3, window=256, inbox=256, exec_batch=64, kv_pow2=10,
    catchup_rows=16, recovery_rows=16)


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("shard", "replica")
    mesh2 = make_mesh(n_shard_devices=4, n_replica_devices=2)
    assert mesh2.shape["shard"] == 4 and mesh2.shape["replica"] == 2


def test_sharded_commits_all_shards():
    mesh = make_mesh()
    g = 16  # 16 shards over 8 devices
    sc = ShardedCluster(SMALL, g, ext_rows=64, mesh=mesh)
    sc.elect(0)
    for _ in range(4):
        sc.step(32)
    for _ in range(3):
        sc.step(0)  # drain
    tot, lo, hi = sc.committed()
    assert lo == hi, "shards advance in lockstep under identical load"
    assert tot == g * 4 * 32


def test_sharded_state_is_actually_sharded():
    mesh = make_mesh()
    ss = init_sharded(SMALL, 8, mesh)
    sharding = ss.states.ballot.sharding
    assert len(sharding.device_set) == len(jax.devices())


def test_sharded_step_preserves_sharding():
    mesh = make_mesh()
    sc = ShardedCluster(SMALL, 8, ext_rows=64, mesh=mesh)
    sc.elect(0)
    sc.step(8)
    assert len(sc.ss.states.ballot.sharding.device_set) == len(jax.devices())


def test_replica_axis_mesh_executes():
    """Replicas spread across devices: routing becomes collectives."""
    mesh = make_mesh(n_shard_devices=2, n_replica_devices=4)
    # replica-axis sharding of a 4-replica group: R axis over 4 devices
    cfg = MinPaxosConfig(n_replicas=4, window=128, inbox=128,
                         exec_batch=32, kv_pow2=8)
    from jax.sharding import NamedSharding, PartitionSpec as P

    ss = init_sharded(cfg, 2)
    def put(x):
        spec = P("shard", "replica") if x.ndim >= 2 else P("shard")
        return jax.device_put(x, NamedSharding(mesh, spec))
    ss = jax.tree_util.tree_map(put, ss)
    ss = elect_all(cfg, ss, 0)
    from minpaxos_tpu.parallel.sharded import make_propose_ext
    ext = make_propose_ext(cfg, 2, 128, 16, jnp.int32(0), jnp.int32(0))
    quiet = jax.tree_util.tree_map(jnp.zeros_like, ext)
    # deliver prepares, then replies, then proposals, then drain
    ss, _, _, _ = sharded_step(cfg, ss, quiet)
    ss, _, _, _ = sharded_step(cfg, ss, quiet)
    ss, _, _, _ = sharded_step(cfg, ss, ext)
    for _ in range(4):
        ss, _, _, _ = sharded_step(cfg, ss, quiet)
    upto = np.asarray(ss.states.committed_upto[:, 0])
    assert (upto >= 15).all()


def test_per_shard_failure_mask():
    """Killing a follower in shard 0 only affects shard 0 (and not even
    it: majority still commits)."""
    g = 4
    sc = ShardedCluster(SMALL, g, ext_rows=64)
    sc.elect(0)
    sc.ss = sc.ss._replace(alive=sc.ss.alive.at[0, 2].set(False))
    for _ in range(3):
        sc.step(16)
    for _ in range(3):
        sc.step(0)
    tot, lo, hi = sc.committed()
    assert tot == g * 3 * 16, "2-of-3 majority still commits everywhere"


def test_fused_run_bounded_keyspace_never_drops_kv_inserts():
    """The bench's saturation guard: with key_space bounded below KV
    capacity, long fused runs churn (PUT overwrites reuse slots) and
    kv.dropped stays 0 everywhere. With an UNBOUNDED key space the same
    run inserts more distinct keys than the table holds — the scenario
    the guard exists for (bench.py headline + side configs)."""
    g = 4
    sc = ShardedCluster(SMALL, g, ext_rows=64,
                        key_space=1 << (SMALL.kv_pow2 - 1))
    sc.elect(0)
    # 24 rounds x 64 proposals/shard = 1536 distinct-capable inserts
    # per shard, 3x the 512-entry key space and 1.5x table capacity
    for _ in range(3):
        sc.run_fused(8, 64)
    sc.run_fused(8, 0)  # drain
    dropped = np.asarray(sc.ss.states.kv.dropped)
    assert (dropped == 0).all(), dropped
    # device-generated proposals that outrun the 256-slot window are
    # rejected (no client retry on-device), so assert the part the
    # test needs: every shard committed well past the key space, so
    # the table really churned overwrite-heavy without dropping
    tot, lo, hi = sc.committed()
    assert lo + 1 > 2 * (1 << (SMALL.kv_pow2 - 1)), (tot, lo, hi)


def test_multihost_glue_single_process_degenerate():
    """Single-process: initialize() no-ops, the global mesh covers all
    local devices, and the process shard slice is the whole range —
    the same launcher path that multi-controller jobs take."""
    from minpaxos_tpu.parallel import multihost

    multihost.initialize(num_processes=1)  # must not raise / contact anyone
    mesh = multihost.global_shard_mesh()
    assert mesh.devices.size == len(jax.devices())
    assert multihost.process_shard_slice(16) == slice(0, 16)
    # the mesh drives a real sharded cluster end-to-end
    sc = ShardedCluster(SMALL, 16, ext_rows=64, mesh=mesh)
    sc.elect(0)
    sc.run_fused(4, 16)
    tot, _, _ = sc.committed()
    assert tot > 0


def test_fused_substeps_cut_commit_rounds():
    """substeps=2 delivers message traffic twice per fused round, so a
    proposal's commit lands ~one ROUND earlier (commit-on-quorum
    within the round the quorum forms — VERDICT round-4 item 5). Same
    commits, fewer rounds-to-commit; the throughput/latency tradeoff
    is measured by bench.py, correctness pinned here."""
    def first_round_reaching(substeps):
        sc = ShardedCluster(SMALL, 2)
        sc.elect(0)
        uptos, _ = sc.run_fused(6, 16, substeps=substeps)
        want = 15  # all 16 round-0 proposals committed
        for r in range(6):
            if uptos[r].min() >= want:
                return r, uptos
        return 99, uptos

    r1, u1 = first_round_reaching(1)
    r2, u2 = first_round_reaching(2)
    assert r1 < 99 and r2 < 99, (u1, u2)
    assert r2 < r1, (r1, r2, u1[:, 0], u2[:, 0])
