"""paxray: device-side telemetry for the resident loop (ISSUE 9).

Contract pinned here:

* telemetry is a PURE OBSERVER — protocol state, committed results and
  the latency histogram are byte-identical with the ring armed or not
  (the ``BENCH_TELEMETRY=0/1`` parity), and the readback is
  deterministic across reruns from the same seed;
* the ring rides the donation discipline (consumed per dispatch like
  the state tree) and its row layout is pinned against the canonical
  obs/recorder.py field table;
* the unified timeline renders: device-round events merge with host
  flight-recorder events into a schema-v4 Chrome trace that validates,
  with the device tracks under the reserved pid — and a host event
  squatting on the reserved pid FAILS validation.

Shapes deliberately mirror tests/test_workload.py (same cfg/g/
ext_rows/k) so the telemetry-off dispatch shares its compiled
dispatch, and every telemetry-on test shares ONE (64-row ring)
compilation — tier-1 budget discipline.
"""

from __future__ import annotations

import jax
import numpy as np

from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.obs.recorder import (
    DEVICE_PID,
    SCHEMA_VERSION,
    TEL_ASSIGNED,
    TEL_CLAIM_ROWS,
    TEL_COMMITTED,
    TEL_FIELD_NAMES,
    TEL_IN_FLIGHT,
    TEL_INBOX_HWM,
    TEL_INBOX_ROWS,
    TEL_INJECTED,
    TEL_PREPARED,
    TEL_ROUND,
    FlightRecorder,
    chrome_trace,
    device_round_events,
    telemetry_valid_rows,
    validate_chrome_trace,
)
from minpaxos_tpu.ops.telemetry import N_TEL_FIELDS, telemetry_row
from minpaxos_tpu.parallel.sharded import DONATION, ShardedCluster

SMALL = MinPaxosConfig(
    n_replicas=3, window=256, inbox=256, exec_batch=64, kv_pow2=10,
    catchup_rows=16, recovery_rows=16)

TEL_ROUNDS = 64  # ONE ring shape for every telemetry-on test


def _boot(seed=5, tel_rounds=0) -> ShardedCluster:
    sc = ShardedCluster(SMALL, 2, ext_rows=32, key_space=1 << 8, seed=seed)
    sc.elect(0)
    sc.begin_resident(telemetry_rounds=tel_rounds)
    return sc


def _run(sc: ShardedCluster, dispatches=3, k=6, p=24):
    for _ in range(dispatches):
        committed, in_flight = sc.run_resident(k, p)
    for _ in range(6):
        committed, in_flight = sc.run_resident(k, 0)
        if in_flight == 0:
            break
    return committed, in_flight


# ------------------------------------------------------------- layout


def test_telemetry_row_layout_pinned_to_recorder():
    """ops/telemetry.py's traced constructor and obs/recorder.py's
    canonical field table cannot drift: a row built from distinct
    per-field values must land each value at its named index."""
    vals = dict(round_idx=10, committed_delta=11, in_flight=12,
                assigned=13, injected_rows=14, inbox_rows=15,
                claim_rows=16, prepared_shards=17, inbox_hwm=18)
    row = np.asarray(telemetry_row(**vals))
    assert row.shape == (N_TEL_FIELDS,) and row.dtype == np.int32
    assert len(TEL_FIELD_NAMES) == N_TEL_FIELDS
    assert row[TEL_ROUND] == 10 and row[TEL_COMMITTED] == 11
    assert row[TEL_IN_FLIGHT] == 12 and row[TEL_ASSIGNED] == 13
    assert row[TEL_INJECTED] == 14 and row[TEL_INBOX_ROWS] == 15
    assert row[TEL_CLAIM_ROWS] == 16 and row[TEL_PREPARED] == 17
    assert row[TEL_INBOX_HWM] == 18


# ------------------------------------------------------ parity / purity


def test_telemetry_parity_state_byte_identical():
    """THE BENCH_TELEMETRY=0/1 acceptance pin: telemetry on vs off —
    same committed totals, same exact latency histogram, and a
    byte-identical final cluster state from the same seed."""
    sc_off = _boot(tel_rounds=0)
    c_off, f_off = _run(sc_off)
    hist_off = sc_off.end_resident()

    sc_on = _boot(tel_rounds=TEL_ROUNDS)
    c_on, f_on = _run(sc_on)
    tel = sc_on.resident_telemetry()
    hist_on = sc_on.end_resident()

    assert (c_off, f_off) == (c_on, f_on)
    assert f_on == 0  # drained exactly — accounting below is total
    assert np.array_equal(hist_off, hist_on)
    for a, b in zip(jax.tree_util.tree_leaves(sc_off.ss),
                    jax.tree_util.tree_leaves(sc_on.ss)):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # and the ring actually observed the run it rode along with
    assert len(tel) > 0
    assert int(tel[:, TEL_COMMITTED].sum()) == c_on
    assert int(tel[:, TEL_ASSIGNED].sum()) == c_on
    assert int(tel[-1, TEL_IN_FLIGHT]) == 0


def test_telemetry_determinism_pin():
    """Same seed => identical telemetry rows across fresh runs (the
    readback is part of the reproducible record); a different seed
    changes the stream but not the accounting identities."""
    runs = []
    for seed in (3, 3, 4):
        sc = _boot(seed=seed, tel_rounds=TEL_ROUNDS)
        committed, in_flight = _run(sc)
        runs.append((committed, sc.resident_telemetry()))
        sc.end_resident()
    assert np.array_equal(runs[0][1], runs[1][1])
    assert runs[0][0] == runs[2][0]  # same protocol progress...
    assert int(runs[2][1][:, TEL_COMMITTED].sum()) == runs[2][0]


def test_telemetry_content_semantics():
    """Field-level sanity at a hand-checkable scale: rounds are
    consecutive absolute indices, the steady flag is saturated after
    the election, injected rows follow the proposal schedule, inbox
    rows appear once routed traffic exists, and claim rows never
    exceed commits."""
    sc = _boot(tel_rounds=TEL_ROUNDS)
    committed, _ = _run(sc, dispatches=2)
    tel = sc.resident_telemetry()
    sc.end_resident()
    g, p = 2, 24
    rounds = tel[:, TEL_ROUND]
    assert (np.diff(rounds) == 1).all()  # one row per round, no holes
    assert (tel[:, TEL_PREPARED] == g).all()  # steady post-election
    # 2 proposing dispatches of 6 rounds, then drain rounds inject 0
    assert (tel[:12, TEL_INJECTED] == g * p).all()
    assert (tel[12:, TEL_INJECTED] == 0).all()
    assert tel[0, TEL_INBOX_ROWS] == 0  # nothing routed before round 1
    assert (tel[1:12, TEL_INBOX_ROWS] > 0).all()
    # the occupancy column feeding adaptive capacity (PR 11): the max
    # DELIVERED per-inbox load (routed + injected) is bounded by the
    # cross-cluster totals and by the static capacity, positive
    # exactly when anything was delivered — and round 0 (nothing
    # routed yet, p rows injected at the leader) pins the injected
    # contribution exactly
    assert ((tel[:, TEL_INBOX_HWM]
             <= tel[:, TEL_INBOX_ROWS] + tel[:, TEL_INJECTED]).all()
            and (tel[:, TEL_INBOX_HWM] <= SMALL.inbox + 32).all())
    assert ((tel[:, TEL_INBOX_HWM] > 0)
            == ((tel[:, TEL_INBOX_ROWS] + tel[:, TEL_INJECTED]) > 0)).all()
    assert tel[0, TEL_INBOX_HWM] == p
    assert int(tel[:, TEL_CLAIM_ROWS].sum()) <= committed
    assert int(tel[:, TEL_COMMITTED].sum()) == committed


def test_telemetry_ring_wraps_to_last_rounds():
    """More rounds than ring rows: the ring keeps the LAST
    ``TEL_ROUNDS`` rounds (a ring, not a truncation), still
    consecutive."""
    sc = _boot(tel_rounds=TEL_ROUNDS)
    # 13 dispatches x 6 rounds = 78 rounds > 64 ring rows
    for _ in range(10):
        sc.run_resident(6, 24)
    for _ in range(3):
        committed, in_flight = sc.run_resident(6, 0)
    tel = sc.resident_telemetry()
    last_round = sc._seed - 1  # rounds are 0-indexed by the _seed ctr
    sc.end_resident()
    assert len(tel) == TEL_ROUNDS
    assert int(tel[-1, TEL_ROUND]) == last_round
    assert (np.diff(tel[:, TEL_ROUND]) == 1).all()


def test_telemetry_buffer_is_donated():
    """The ring rides the donation discipline the bench artifact
    stamps: consumed per dispatch like the state tree and the other
    bookkeeping buffers."""
    assert DONATION["sharded_run_resident"] is True
    sc = _boot(tel_rounds=TEL_ROUNDS)
    old_tel = sc._telemetry
    old_ballot = sc.ss.states.ballot
    sc.run_resident(6, 8)
    assert old_tel.is_deleted()
    assert old_ballot.is_deleted()


# ------------------------------------------------------ unified timeline


def _synthetic_dispatches(rows, t0_ns=1_000_000_000, wall_ns=2_000_000,
                          k=6):
    """A dispatch log covering the telemetry rows, k rounds per
    dispatch, on the monotonic_ns clock the host recorder uses."""
    rows = telemetry_valid_rows(rows)
    first, last = int(rows[0, TEL_ROUND]), int(rows[-1, TEL_ROUND])
    disp, t = [], t0_ns
    r = first
    while r <= last:
        disp.append({"t0_ns": t, "t1_ns": t + wall_ns, "round0": r,
                     "k": k})
        t += wall_ns
        r += k
    return disp


def test_merged_device_host_trace_validates_v4():
    """The tentpole's piece 3: real telemetry readback + host
    flight-recorder rows merge into ONE schema-v4 Chrome trace that
    validates, device rounds under the reserved pid, host ticks under
    replica pids, with the frontier/in-flight counter tracks
    present."""
    sc = _boot(tel_rounds=TEL_ROUNDS)
    committed, _ = _run(sc, dispatches=2)
    tel = sc.resident_telemetry()
    sc.end_resident()

    rec = FlightRecorder(64)
    t = 1_000_000_000
    for i in range(4):
        t += 2_000_000
        rec.record(t, 1, 6, 48, 0, 100 + i, 0, 5, 30, 500, 0, 20, 30,
                   10, t - 100_000)
    disp = _synthetic_dispatches(tel)
    events = rec.to_events(pid=0) + device_round_events(tel, disp,
                                                        n_shards=2)
    trace = chrome_trace(events)
    assert trace["otherData"]["paxmonSchemaVersion"] == SCHEMA_VERSION == 7
    assert validate_chrome_trace(trace) == []

    dev = [e for e in events if e.get("cat") == "device_round"]
    assert len(dev) == len(tel)
    assert all(e["pid"] == DEVICE_PID for e in dev)
    assert all(e["name"] == "round:steady" for e in dev)  # post-elect
    args0 = dev[0]["args"]
    assert set(args0) == set(TEL_FIELD_NAMES)
    cnames = {e["name"] for e in events if e["ph"] == "C"
              and e["pid"] == DEVICE_PID}
    assert {"device_frontier", "device_in_flight"} <= cnames
    # the device_frontier counter integrates to the committed total
    fr = [e["args"]["device_frontier"] for e in events
          if e.get("name") == "device_frontier"]
    assert fr[-1] == committed
    # host events stayed on their own pid
    assert all(e["pid"] == 0 for e in events
               if e.get("cat") in ("tick", "phase"))


def test_reserved_pid_is_enforced():
    """A host-looking event on the reserved device pid, or a device
    event off it, must fail validation — the merge contract."""
    good = {"name": "tick:full", "cat": "tick", "ph": "X", "ts": 1.0,
            "dur": 1.0, "pid": 0, "tid": 0}
    squatter = dict(good, pid=DEVICE_PID)
    errs = validate_chrome_trace(chrome_trace([good, squatter]))
    assert errs and "reserved" in errs[0]
    stray = {"name": "round:steady", "cat": "device_round", "ph": "X",
             "ts": 1.0, "dur": 1.0, "pid": 3, "tid": 0}
    errs = validate_chrome_trace(chrome_trace([stray]))
    assert errs and "reserved pid" in errs[0]


def test_device_round_events_skips_uncovered_rounds():
    """Rounds with no covering dispatch (telemetry of a window the
    host never logged) are skipped, not misplaced at t=0."""
    row = np.asarray(telemetry_row(5, 1, 2, 3, 4, 5, 6, 2, 3))[None]
    evs = device_round_events(row, [{"t0_ns": 0, "t1_ns": 1000,
                                     "round0": 99, "k": 2}], n_shards=2)
    assert evs == []
