"""paxref: abstract spec, refinement mapping, liveness under fairness.

Three layers, matching VERIFY.md's refinement section:

* the executable abstract Multi-Paxos machine (verify/spec.py) — each
  action enforces exactly its TLA-style precondition, the agreement
  theorem fires on non-intersecting vote quorums, and the quorum
  parameters come only from the certified ledger;
* the refinement mapping (verify/refine.py) — healthy explorations of
  all kernels map every edge onto an abstract action with zero
  violations, and the planted skip-quorum2 mutant (a leader committing
  below the phase-2 quorum — invisible to every safety invariant)
  yields a replayable commit-no-quorum counterexample;
* liveness under weak fairness (verify/liveness.py) — the fair-suffix
  graph drains into all-goal terminal states for the default and a
  flexible certified pair, and the planted dueling-leaders mutant
  yields a fair lasso whose stem+cycle replays to the same quotient
  state with the command uncommitted.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

from minpaxos_tpu.verify.quorum import certified_pairs, spec_quorums
from minpaxos_tpu.verify.spec import (
    ABSTRACT_ACTIONS,
    MSGKIND_ACTIONS,
    SpecState,
    SpecViolation,
    spec_for_model,
)

FIXTURES = Path(__file__).resolve().parent / "fixtures"


# ------------------------------------------------ abstract spec machine


def _spec(q1=2, q2=2, n=3):
    return SpecState(n=n, q1=q1, q2=q2)


def test_spec_happy_path_commits():
    s = _spec()
    s.phase1a(16)
    s.phase1b(0, 16)
    s.phase1b(1, 16)
    s.phase2a(16, 0, "v")
    s.phase2b(0, 16, 0)
    s.phase2b(1, 16, 0)
    s.commit(0, "v")
    assert s.chosen[0] == "v"
    s.check_agreement()  # and the theorem holds on the final state


def test_spec_phase1b_promise_monotonic():
    s = _spec()
    s.phase1a(16)
    s.phase1b(0, 16)
    with pytest.raises(SpecViolation, match="promise"):
        s.phase1b(0, 16)  # equal ballot is NOT a fresh promise
    with pytest.raises(SpecViolation, match="promise"):
        s.phase1b(0, 15)


def test_spec_phase2a_uniqueness_and_safety():
    s = _spec()
    s.phase1a(16)
    with pytest.raises(SpecViolation, match="never started"):
        s.phase2a(17, 0, "v")
    with pytest.raises(SpecViolation, match="not safe"):
        s.phase2a(16, 0, "v")  # no q1 promise quorum yet
    s.phase1b(0, 16)
    s.phase1b(1, 16)
    s.phase2a(16, 0, "v")
    s.phase2a(16, 0, "v")  # re-proposing the SAME value is idempotent
    with pytest.raises(SpecViolation, match="already proposed"):
        s.phase2a(16, 0, "w")


def test_spec_phase2a_adopts_highest_prior_vote():
    # ballot 16's value is voted by acceptor 0; ballot 33's proposer
    # must adopt it — proposing anything else is unsafe
    s = _spec()
    s.phase1a(16)
    s.phase1b(0, 16)
    s.phase1b(1, 16)
    s.phase2a(16, 0, "v")
    s.phase2b(0, 16, 0)
    s.phase1a(33)
    s.phase1b(0, 33)
    s.phase1b(1, 33)
    with pytest.raises(SpecViolation, match="not safe"):
        s.phase2a(33, 0, "w")
    s.phase2a(33, 0, "v")


def test_spec_phase2b_requires_proposal_and_promise():
    s = _spec()
    s.phase1a(16)
    s.phase1b(0, 16)
    s.phase1b(1, 16)
    with pytest.raises(SpecViolation, match="nothing proposed"):
        s.phase2b(0, 16, 0)
    s.phase2a(16, 0, "v")
    s.phase1a(33)
    s.phase1b(2, 33)  # acceptor 2 promised PAST ballot 16
    with pytest.raises(SpecViolation, match="promise"):
        s.phase2b(2, 16, 0)


def test_spec_commit_requires_quorum_and_stability():
    s = _spec()
    s.phase1a(16)
    s.phase1b(0, 16)
    s.phase1b(1, 16)
    s.phase2a(16, 0, "v")
    s.phase2b(0, 16, 0)
    with pytest.raises(SpecViolation, match="quorum"):
        s.commit(0, "v")  # one vote < q2=2
    s.phase2b(1, 16, 0)
    s.commit(0, "v")
    with pytest.raises(SpecViolation, match="already chose"):
        s.commit(0, "w")


def test_spec_skip_is_owner_only():
    s = _spec()
    s.skip(1, 1, "noop")  # slot 1 % 3 == owner 1
    assert s.chosen[1] == "noop"
    with pytest.raises(SpecViolation, match="not owned"):
        s.skip(0, 1, "noop")


def test_spec_agreement_theorem_fires_on_nonintersecting_quorums():
    # hand-build the split-brain a q2=1 pair permits when q1+q2 <= n:
    # two single-acceptor "quorums" vote different values for slot 0 —
    # the theorem must flag it (this is the abstract shadow of the
    # flex-broken kernel mutant)
    s = SpecState(n=3, q1=2, q2=1)
    s.phase1a(16)
    s.phase1b(0, 16)
    s.phase1b(1, 16)
    s.phase2a(16, 0, "v")
    s.phase2b(0, 16, 0)
    s.votes[(1, 0)] = {33: "w"}  # rogue vote at a later ballot
    s.started.add(33)
    with pytest.raises(SpecViolation, match="agreement broken"):
        s.check_agreement()


def test_spec_refuses_out_of_range_quorums():
    with pytest.raises(SpecViolation, match="out of range"):
        SpecState(n=3, q1=0, q2=2)
    with pytest.raises(SpecViolation, match="out of range"):
        SpecState(n=3, q1=2, q2=4)


def test_msgkind_action_table_names_only_known_actions():
    assert MSGKIND_ACTIONS, "spec-sync table must not be empty"
    for kind, actions in MSGKIND_ACTIONS.items():
        assert isinstance(kind, str) and actions, kind
        for a in actions:
            assert a in ABSTRACT_ACTIONS, (kind, a)


# ------------------------------------- certified quorum parameterization


def test_spec_quorums_resolves_defaults_from_ledger():
    assert spec_quorums(3) == (2, 2)
    assert spec_quorums(3, 3, 1) == (3, 1)
    assert (2, 2) in certified_pairs(3)


def test_spec_quorums_refuses_uncertified_pairs():
    with pytest.raises(ValueError, match="certified"):
        spec_quorums(3, 2, 1)  # the flex-broken mutant pair


def test_spec_for_model_builds_parameterized_machine():
    s = spec_for_model(3, 1, 3)
    assert (s.q1, s.q2) == (1, 3)
    with pytest.raises(ValueError):
        spec_for_model(3, 2, 1)


# ------------------------------------------------- refinement checking


def _refine(protocol, bounds, **kw):
    from minpaxos_tpu.verify.refine import RefinementExplorer

    ex = RefinementExplorer(protocol, bounds, **kw)
    return ex, ex.run()


def test_refinement_healthy_minpaxos_maps_every_edge():
    from minpaxos_tpu.verify.mc import Bounds

    b = Bounds(max_depth=4, drops=1, dups=0, internal=1, elections=1,
               n_cmds=1, propose_to=(0,))
    ex, res = _refine("minpaxos", b)
    assert res.ok and res.drained, res.counterexample
    stats = ex.refine_stats()
    # EVERY transition was edge-checked (including seen-state-pruned
    # ones — refinement is an edge property, not a state property)
    assert stats["edges_checked"] == res.transitions
    acts = stats["abstract_actions"]
    assert acts.get("Phase1a") and acts.get("Phase1b"), acts
    assert sum(acts.values()) >= stats["edges_checked"]


def test_refinement_healthy_mencius_labels_skips():
    from minpaxos_tpu.verify.mc import Bounds

    b = Bounds(max_depth=4, drops=1, dups=0, internal=1, elections=0,
               n_cmds=1, propose_to=(0, 1))
    ex, res = _refine("mencius", b)
    assert res.ok and res.drained, res.counterexample
    acts = ex.refine_stats()["abstract_actions"]
    # cede commits are Skip actions; real value commits also appear
    assert acts.get("Skip") and acts.get("Commit"), acts


def test_skip_quorum2_mutant_yields_replayable_counterexample():
    from minpaxos_tpu.verify.mc import Bounds, replay_counterexample

    b = Bounds(max_depth=5, drops=0, dups=0, internal=1, elections=0,
               n_cmds=1, propose_to=(0,))
    _ex, res = _refine("minpaxos", b, mutant="skip-quorum2")
    assert res.counterexample is not None, \
        "skip-quorum2 mutant evaded refinement"
    ce = res.counterexample
    assert ce.kind == "refinement" and ce.mutant == "skip-quorum2"
    assert any("commit-no-quorum" in v
               for v in ce.report["violations"]), ce.report
    # lossless JSON round-trip, then replay re-installs the mutant
    reproduced, rep = replay_counterexample(
        json.loads(json.dumps(ce.to_dict())))
    assert reproduced, rep.violations
    assert any("REFINEMENT" in v for v in rep.violations)


def test_refinement_rejects_unknown_mutant_and_uncertified_pair():
    from minpaxos_tpu.verify.refine import RefinementExplorer

    with pytest.raises(ValueError, match="mutant"):
        RefinementExplorer("minpaxos", mutant="no-such-mutant")
    with pytest.raises(ValueError, match="certified"):
        RefinementExplorer("minpaxos", q1=2, q2=1)


# --------------------------------------------- liveness under fairness


def test_liveness_flexible_pair_proves_eventual_commit():
    from minpaxos_tpu.verify.liveness import LivenessExplorer, fair_bounds

    r = LivenessExplorer("minpaxos", fair_bounds(n_cmds=1),
                         q1=3, q2=1).explore()
    assert r.ok, r.to_dict()
    assert r.drained and r.goal_states > 0
    assert r.deadlocks == 0 and r.fair_lassos == 0
    # the fair suffix of a healthy run is a DAG: progress is monotone
    assert r.cyclic_sccs == 0


@pytest.mark.slow
def test_liveness_default_quorums_prove_eventual_commit():
    from minpaxos_tpu.verify.liveness import LivenessExplorer, fair_bounds

    r = LivenessExplorer("minpaxos", fair_bounds(n_cmds=1)).explore()
    assert r.ok and r.cyclic_sccs == 0, r.to_dict()


@pytest.mark.slow
def test_dueling_leaders_mutant_yields_fair_lasso():
    from minpaxos_tpu.verify.liveness import (LivenessExplorer,
                                              dueling_bounds)
    from minpaxos_tpu.verify.mc import replay_counterexample

    r = LivenessExplorer("minpaxos", dueling_bounds(),
                         mutant="dueling-leaders", max_states=3000,
                         max_queue_rows=10).explore()
    assert r.fair_lassos > 0 and r.lasso is not None, r.to_dict()
    ce = r.lasso
    assert ce.kind == "lasso" and ce.loop_start is not None
    # the cycle is a genuine duel: both rivals elect inside it
    cycle = ce.trace[ce.loop_start:]
    electors = {a["r"] for a in cycle if a["a"] == "elect"}
    assert electors == {0, 1}, cycle
    reproduced, rep = replay_counterexample(
        json.loads(json.dumps(ce.to_dict())))
    assert reproduced and any("LASSO" in v for v in rep.violations)


def test_lasso_fixture_replays_through_liveness_contract():
    # the glob harness in test_safety_random.py replays this fixture
    # too; here we additionally pin the lasso-specific contract (cycle
    # closes on the SAME quotient state, goal unreached inside it)
    from minpaxos_tpu.verify.liveness import replay_lasso

    path = FIXTURES / "mc_lasso_dueling_minpaxos.json"
    ce = json.loads(path.read_text())
    assert ce["kind"] == "lasso" and ce["mutant"] == "dueling-leaders"
    reproduced, report = replay_lasso(ce)
    assert reproduced
    assert any("LASSO" in v for v in report.violations)


def test_replay_lasso_rejects_non_lasso_counterexamples():
    from minpaxos_tpu.verify.liveness import replay_lasso

    ce = json.loads(
        (FIXTURES / "mc_refine_skip_quorum2_minpaxos.json").read_text())
    with pytest.raises(ValueError, match="lasso"):
        replay_lasso(ce)
