"""paxsoak units (ISSUE 18): exact-Zipf profiles pinned against the
closed-form mass, byte-reproducible open-loop arrival schedules, the
hot-key workload knob's device/host equivalence, EV_PHASE journaling
and the paxtop SOAK stanza, the scorecard's alarm-classification /
criteria join on synthetic timelines, and a small multi-process
OpenLoopSwarm exactly-once leg against a real in-process cluster
(the chaos-smoke compiled shape — no new variants).
"""

from __future__ import annotations

import importlib.util
import time
from pathlib import Path

import numpy as np
import pytest

from minpaxos_tpu.obs import watch as W
from minpaxos_tpu.soak.profiles import (
    OP_GET,
    OP_PUT,
    PROFILES,
    ArrivalSpec,
    WorkloadProfile,
    arrival_times,
    profile_rows,
    resolve_profile,
    sample_zipf,
    zipf_pmf,
)

REPO = Path(__file__).resolve().parents[1]


# ------------------------------------------------------- exact Zipf

def test_zipf_pmf_closed_form():
    pmf = zipf_pmf(1024, 1.2)
    assert pmf.shape == (1024,)
    assert abs(pmf.sum() - 1.0) < 1e-12
    # p(k) proportional to k^-s: pin the ratio, not the normalizer
    assert pmf[0] / pmf[1] == pytest.approx(2.0 ** 1.2, rel=1e-12)
    assert np.all(np.diff(pmf) < 0)  # strictly rank-decreasing


def test_zipf_sample_mass_pinned_against_closed_form():
    """The sampler is EXACT finite-support Zipf: empirical mass of the
    hottest ranks matches the closed-form pmf within sampling noise
    (this is the property numpy's unbounded rng.zipf cannot give)."""
    n, n_keys, s = 200_000, 1024, 1.2
    rng = np.random.default_rng(99)
    ranks = sample_zipf(n, n_keys, s, rng)
    assert ranks.min() >= 0 and ranks.max() < n_keys
    pmf = zipf_pmf(n_keys, s)
    for top in (1, 8, 64):
        want = pmf[:top].sum()
        got = float(np.mean(ranks < top))
        # ~4.5 sigma of a Bernoulli(want) mean over n draws
        tol = 4.5 * np.sqrt(want * (1 - want) / n)
        assert abs(got - want) < tol, (top, got, want, tol)


def test_zipf_sampler_deterministic():
    a = sample_zipf(1000, 256, 1.8, np.random.default_rng(7))
    b = sample_zipf(1000, 256, 1.8, np.random.default_rng(7))
    assert np.array_equal(a, b)


# --------------------------------------------------------- profiles

def test_profile_rows_reproducible_and_shaped():
    prof = PROFILES["mixed"]  # zipf_s=0.9, write_pct=50
    ops, keys, vals = profile_rows(prof, 20_000, seed=5)
    ops2, keys2, vals2 = profile_rows(prof, 20_000, seed=5)
    assert (np.array_equal(ops, ops2) and np.array_equal(keys, keys2)
            and np.array_equal(vals, vals2))
    assert set(np.unique(ops)) <= {OP_PUT, OP_GET}
    wfrac = float(np.mean(ops == OP_PUT))
    assert abs(wfrac - 0.50) < 0.02
    assert keys.min() >= 0 and keys.max() < prof.key_space
    # log-uniform value magnitudes stay inside the configured octaves
    assert vals.min() >= 1 << prof.val_pow2_min
    assert vals.max() < 1 << prof.val_pow2_max


def test_profile_resolve_and_roundtrip():
    p = resolve_profile("hot_zipf")
    assert p is PROFILES["hot_zipf"]
    assert resolve_profile(p.to_dict()) == p
    assert resolve_profile(p) is p
    with pytest.raises(ValueError, match="unknown profile"):
        resolve_profile("nope")


def test_profile_op_codes_mirror_wire():
    from minpaxos_tpu.wire.messages import Op

    assert OP_PUT == int(Op.PUT)
    assert OP_GET == int(Op.GET)


def test_gen_workload_profile_hook():
    from minpaxos_tpu.runtime.client import gen_workload

    got = gen_workload(512, seed=11, profile="hot_zipf")
    want = profile_rows(PROFILES["hot_zipf"], 512, seed=11)
    for g, w in zip(got, want):
        assert np.array_equal(g, w)


# --------------------------------------------------------- arrivals

def test_arrival_schedule_byte_reproducible():
    spec = ArrivalSpec(rate_hz=200.0, duration_s=8.0, burst_x=5.0,
                       burst_t0_frac=0.25, burst_t1_frac=0.5,
                       diurnal_amp=0.3, diurnal_period_s=4.0)
    a = arrival_times(spec, seed=42)
    b = arrival_times(spec, seed=42)
    assert a.dtype == np.float64 and np.array_equal(a, b)
    assert not np.array_equal(a, arrival_times(spec, seed=43))
    assert np.all(np.diff(a) >= 0) and a[0] >= 0 and a[-1] < 8.0
    # round-trips through the manifest dict form unchanged
    assert ArrivalSpec.from_dict(spec.to_dict()) == spec


def test_arrival_burst_envelope_density():
    """The burst window really carries burst_x times the base rate
    (Poisson-thinned, so checked within sampling noise)."""
    spec = ArrivalSpec(rate_hz=400.0, duration_s=10.0, burst_x=4.0,
                       burst_t0_frac=0.2, burst_t1_frac=0.4)
    t = arrival_times(spec, seed=9)
    in_burst = np.sum((t >= 2.0) & (t < 4.0))
    outside = len(t) - in_burst
    # expected: 2 s at 1600 Hz = 3200 vs 8 s at 400 Hz = 3200
    assert in_burst == pytest.approx(3200, abs=5 * np.sqrt(3200))
    assert outside == pytest.approx(3200, abs=5 * np.sqrt(3200))
    # per-second density ratio is the burst multiplier
    assert (in_burst / 2.0) / (outside / 8.0) == pytest.approx(4.0,
                                                               rel=0.15)


def test_arrival_rate_envelope_math():
    spec = ArrivalSpec(rate_hz=100.0, duration_s=10.0, burst_x=6.0,
                       burst_t0_frac=0.5, burst_t1_frac=0.6,
                       diurnal_amp=0.5, diurnal_period_s=10.0)
    assert spec.peak_rate == pytest.approx(100.0 * 1.5 * 6.0)
    r = spec.rate_at(np.array([0.0, 2.5, 5.5, 7.5]))
    assert r[0] == pytest.approx(100.0)          # sin(0) = 0
    assert r[1] == pytest.approx(150.0)          # diurnal crest
    assert r[2] == pytest.approx(600.0 * (1 + 0.5 * np.sin(2 * np.pi
                                                           * 0.55)))
    assert r[3] == pytest.approx(50.0)           # diurnal trough
    assert len(arrival_times(ArrivalSpec(rate_hz=0.0), 1)) == 0


# --------------------------------------- hot-key knob (ops/workload)

def test_hot_key_knob_device_host_equivalence():
    """paxsoak's hot-key-skew knob: the device generator and its host
    twin stay row-for-row identical with the knob engaged, and the
    redirect actually concentrates keys into the hot set."""
    from minpaxos_tpu.ops.workload import propose_batch, propose_batch_host

    g, r, m = 2, 3, 32
    hot_frac = []
    for rnd in (0, 7):
        dev = propose_batch(r, g, m, m, 1, rnd, 123,
                            key_space=1 << 10, hot_pct=30, hot_keys=4)
        host = propose_batch_host(r, g, m, m, 1, rnd, 123,
                                  key_space=1 << 10, hot_pct=30,
                                  hot_keys=4)
        for f in dev._fields:
            assert np.array_equal(np.asarray(getattr(dev, f)),
                                  getattr(host, f)), (f, rnd)
        hot_frac.append(np.mean(host.key_lo[:, 1, :] < 4))
    # skew is real: with hot_pct=30 well over the uniform baseline
    # (4/1024) of keys land in the 4 hot slots
    assert np.mean(hot_frac) > 0.15, hot_frac


# ------------------------------------- EV_PHASE + the paxtop stanza

def test_ev_phase_journal_roundtrip():
    assert W.EVENT_NAMES[W.EV_PHASE] == "phase"
    assert W.PHASE_KIND_IDS["overload"] == W.PHASE_OVERLOAD
    assert W.PHASE_KIND_NAMES[W.PHASE_KIND_IDS["partition"]] == "partition"
    j = W.EventJournal(capacity=16)
    j.record(W.EV_PHASE, subject=2, value=12_000,
             aux=W.PHASE_KIND_IDS["overload"])
    col = j.collect()
    assert W.counts_by_kind(col["events"])["phase"] == 1
    ev = col["events"][-1]
    assert (int(ev[W.EV_SUBJECT]), int(ev[W.EV_VALUE]),
            int(ev[W.EV_AUX])) == (2, 12_000, W.PHASE_OVERLOAD)


def _load_paxtop():
    spec = importlib.util.spec_from_file_location(
        "paxtop_soak_mod", REPO / "tools" / "paxtop.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_paxtop_soak_stanza():
    """The SOAK stanza reads the NEWEST EV_PHASE stamp (phase name
    from the kind table, elapsed from its wall time, planned from the
    journaled duration) and is None when no scenario ever ran."""
    paxtop = _load_paxtop()
    j = W.EventJournal(capacity=16)
    j.record(W.EV_PHASE, subject=0, value=4_000,
             aux=W.PHASE_KIND_IDS["warmup"])
    j.record(W.EV_PHASE, subject=1, value=6_000,
             aux=W.PHASE_KIND_IDS["overload"])
    ev_resp = {"ok": True, "replicas": [
        {"id": 0, "ok": True, "journal": j.collect()}]}
    resp = {"ok": True, "leader": 0, "replicas": [
        {"id": 0, "ok": True, "frontier": 1,
         "metrics": {"counters": {}, "gauges": {}}}]}
    payload = paxtop.snapshot_payload(resp, ev_resp, None, 0.0,
                                      now_wall_ns=time.time_ns())
    soak = payload["soak"]
    assert set(paxtop.SOAK_ROW_KEYS) == set(soak), sorted(soak)
    assert soak["ordinal"] == 1 and soak["phase"] == "overload"
    assert soak["planned_s"] == pytest.approx(6.0)
    assert 0.0 <= soak["elapsed_s"] < 5.0
    # idle cluster: no EV_PHASE anywhere -> stanza is None, key stays
    empty = paxtop.snapshot_payload(
        resp, {"ok": True, "replicas": []}, None, 0.0,
        now_wall_ns=time.time_ns())
    assert empty["soak"] is None and "soak" in paxtop.JSON_PAYLOAD_KEYS


# ------------------------------------------- scorecard join/criteria

def _synthetic_card(warmup_shed=0, overload_shed=500, alarms=(),
                    edges=(), lost=0):
    phases = [
        {"ordinal": 0, "name": "warmup", "kind": "warmup",
         "t0_wall": 100.0, "t1_wall": 108.0,
         "cluster": {"coalesce_admission_rejects": warmup_shed}},
        {"ordinal": 1, "name": "burst", "kind": "overload",
         "t0_wall": 108.0, "t1_wall": 120.0,
         "cluster": {"coalesce_admission_rejects": overload_shed}},
        {"ordinal": 2, "name": "part", "kind": "partition",
         "t0_wall": 120.0, "t1_wall": 134.0,
         "cluster": {"coalesce_admission_rejects": 0}},
        {"ordinal": 3, "name": "heal", "kind": "heal",
         "t0_wall": 134.0, "t1_wall": 142.0,
         "cluster": {"coalesce_admission_rejects": 0}},
    ]
    return {"phases": phases, "alarms": list(alarms),
            "alarm_edges": list(edges),
            "fault_windows": [{"t_install": 122.0, "t_clear": 130.0,
                               "grace_s": 3.0}],
            "exactly_once": {"lost": lost, "acked_unique": 10_000}}


def test_classify_alarms_against_ground_truth():
    from minpaxos_tpu.soak.scenario import classify_alarms

    card = _synthetic_card()
    alarms = [
        # raised mid-partition, cleared after the fault cleared
        {"detector": "frontier_stall", "subject": 2,
         "t_raised": 124.0, "t_cleared": 131.0},
        # raised during warmup: not in any fault window
        {"detector": "p99_burn_rate", "subject": 0,
         "t_raised": 101.0, "t_cleared": 102.0},
        # raised in-window but never cleared
        {"detector": "backlog_growth", "subject": 2,
         "t_raised": 125.0, "t_cleared": None},
    ]
    out = classify_alarms(alarms, card["phases"], card["fault_windows"])
    assert [a["phase"] for a in out] == ["part", "warmup", "part"]
    assert [a["in_fault_window"] for a in out] == [True, False, True]
    assert [a["cleared_after_heal"] for a in out] == [True, True, False]


def test_evaluate_criteria_joined_timeline():
    from minpaxos_tpu.soak.scenario import evaluate_criteria

    good = _synthetic_card(
        alarms=[{"detector": "frontier_stall", "subject": 2,
                 "t_raised": 124.0, "t_cleared": 131.0,
                 "phase": "part", "in_fault_window": True,
                 "cleared_after_heal": True}],
        edges=[{"detector": "p99_burn_rate", "wall_s": 110.0}])
    crit = evaluate_criteria(good)
    assert crit == {"admission_organic": True,
                    "overload_alarm_journaled": True,
                    "partition_detected_in_window": True,
                    # vacuously true: no crash_restart phase in the
                    # synthetic card's manifest (paxdur)
                    "crash_detected_and_attributed": True,
                    "exactly_once": True, "ok": True}
    # shed outside the overload phase is NOT organic
    crit = evaluate_criteria(_synthetic_card(warmup_shed=3,
                                             alarms=good["alarms"],
                                             edges=good["alarm_edges"]))
    assert not crit["admission_organic"] and not crit["ok"]
    # a lost command sinks exactly-once
    crit = evaluate_criteria(_synthetic_card(lost=1,
                                             alarms=good["alarms"],
                                             edges=good["alarm_edges"]))
    assert not crit["exactly_once"] and not crit["ok"]
    # a partition phase with zero watcher alarms is NOT a pass
    crit = evaluate_criteria(_synthetic_card(edges=good["alarm_edges"]))
    assert not crit["partition_detected_in_window"]
    # crash_restart criterion (paxdur), quantified like the chaos
    # campaign's _stall_verdict: a mid-window raise->clear flap does
    # not negate a detection that named the corpse, but an alarm that
    # never clears — or zero alarms at all — sinks it
    crash = _synthetic_card(alarms=list(good["alarms"]),
                            edges=list(good["alarm_edges"]))
    crash["phases"].append(
        {"ordinal": 4, "name": "crash", "kind": "crash_restart",
         "t0_wall": 142.0, "t1_wall": 156.0,
         "cluster": {"coalesce_admission_rejects": 0}})
    crash["manifest"] = {"phases": [
        {"name": "crash", "kind": "crash_restart",
         "crash": {"target": 2}}]}
    assert not evaluate_criteria(crash)["crash_detected_and_attributed"]
    flap = {"detector": "frontier_stall", "subject": 2,
            "t_raised": 144.0, "t_cleared": 146.0, "phase": "crash",
            "in_fault_window": True, "cleared_after_heal": False}
    hit = {"detector": "frontier_stall", "subject": 2,
           "t_raised": 147.0, "t_cleared": 151.0, "phase": "crash",
           "in_fault_window": True, "cleared_after_heal": True}
    crash["alarms"] += [flap, hit]
    assert evaluate_criteria(crash)["crash_detected_and_attributed"]
    crash["alarms"][-1] = dict(hit, t_cleared=None)
    assert not evaluate_criteria(crash)["crash_detected_and_attributed"]


# -------------------------------------- multi-process swarm (real IO)

def test_open_loop_swarm_exactly_once(tmp_path):
    """2 worker processes x 8 sessions of seeded open-loop traffic
    against a real in-process cluster: every injected command acked
    exactly once across shards after the drain (0 lost), duplicates
    absorbed client-side. Same compiled cluster shape as the chaos
    smoke / test_swarm — no new variants."""
    from minpaxos_tpu.chaos.campaign import ChaosCluster
    from minpaxos_tpu.soak.swarm import OpenLoopSwarm

    cluster = ChaosCluster(n=3, store_dir=str(tmp_path))
    swarm = None
    try:
        swarm = OpenLoopSwarm(cluster.maddr, sessions=16, shards=2,
                              retransmit_s=0.5, trace_pow2=None)
        swarm.start()
        res = swarm.run_phase(
            "mixed", ArrivalSpec(rate_hz=150.0, duration_s=3.0), seed=11)
        assert res["sent"] > 200, res  # open loop: ~450 expected
        drain = swarm.drain(20.0)
        final = swarm.stop()
        swarm = None
        assert final["lost"] == 0, (res, drain, final)
        assert final["acked_unique"] == final["sent_unique"] > 0, final
        assert final["dead_sessions"] == 0, final
    finally:
        if swarm is not None:
            swarm.kill()
        cluster.stop()
