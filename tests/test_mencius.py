"""Mencius (models/mencius.py) behavior tests.

Covers the reference's defining behaviors: rotating ownership
(mencius.go:431-432), skip-cede by idle owners (:449-501), explicit
commit transfer, blocking-frontier execution (:744-797), forceCommit
takeover by the successor (:878-897), and conflict-aware out-of-order
execution (:799-876).
"""

import numpy as np
import pytest

from minpaxos_tpu.models.cluster import tree_slice
from minpaxos_tpu.models.mencius import MenciusCluster, init_mencius
from minpaxos_tpu.models.minpaxos import (
    COMMITTED,
    EXECUTED,
    MinPaxosConfig,
    NONE,
)
from minpaxos_tpu.wire.messages import Op

CFG = MinPaxosConfig(n_replicas=3, window=256, inbox=512, exec_batch=128,
                     kv_pow2=10, catchup_rows=64, recovery_rows=32,
                     noop_delay=4)


def test_multi_leader_concurrent_proposals():
    """Every replica proposes into its own slots simultaneously; all
    commit; the interleaved log agrees across replicas; exactly-once."""
    c = MenciusCluster(CFG, ext_rows=128)
    n = 20
    for r in range(3):
        c.propose(ops=[Op.PUT] * n,
                  keys=np.arange(n) + 100 * r,
                  vals=np.arange(n) + 1000 * (r + 1),
                  cmd_ids=np.arange(n) + 100 * r,
                  client_id=r + 1, to=r)
    c.run(12)
    for r in range(3):
        st = tree_slice(c.cs.states, r)
        assert int(np.asarray(st.committed_upto)) >= 3 * n - 3, (
            f"replica {r} frontier "
            f"{int(np.asarray(st.committed_upto))}")
    # ownership: replica r's commands landed in slots == r (mod 3)
    st0 = tree_slice(c.cs.states, 0)
    ops = np.asarray(st0.op)
    clients = np.asarray(st0.client_id)
    base = int(np.asarray(st0.window_base))
    for i in range(3 * n - 3):
        if ops[i - base] == int(Op.PUT):
            assert clients[i - base] == (i % 3) + 1, (
                f"slot {i} written by client {clients[i - base]}")
    # replies exactly-once
    assert len(c.replies) == 3 * n
    assert not [e for e in c.reply_log if e.get("duplicate")]


def test_idle_owners_cede_via_skip():
    """Only replica 0 proposes; replicas 1,2 cede their slots as skips
    so the frontier advances through the interleaved log
    (mencius.go:449-501)."""
    c = MenciusCluster(CFG, ext_rows=128)
    n = 30
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n), vals=np.arange(n) * 7,
              cmd_ids=np.arange(n), client_id=1, to=0)
    c.run(10)
    st0 = tree_slice(c.cs.states, 0)
    upto = int(np.asarray(st0.committed_upto))
    # frontier covers all of replica 0's slots (0,3,...,87) => >= 87
    assert upto >= 3 * (n - 1), f"frontier {upto}"
    # the interleaved idle slots are committed no-ops (skips)
    ops = np.asarray(st0.op)
    status = np.asarray(st0.status)
    base = int(np.asarray(st0.window_base))
    for i in range(upto + 1):
        if i % 3 != 0:
            assert status[i - base] >= COMMITTED
            assert ops[i - base] == int(Op.NONE), f"slot {i} not a skip"
    # and every PUT executed into the KV
    assert len(c.replies) == n


def test_dead_owner_takeover_unblocks_frontier():
    """Kill replica 1; its slots block the frontier until the successor
    (replica 2) takes them over with no-op fills after the stall
    threshold (forceCommit, mencius.go:878-897)."""
    c = MenciusCluster(CFG, ext_rows=128)
    c.kill(1)
    n = 15
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n), vals=np.arange(n) * 5,
              cmd_ids=np.arange(n), client_id=1, to=0)
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n) + 50,
              vals=np.arange(n) * 11, cmd_ids=np.arange(n) + 50,
              client_id=2, to=2)
    c.run(30)  # stall -> takeover sweep -> no-op fill -> commit
    for r in (0, 2):
        st = tree_slice(c.cs.states, r)
        upto = int(np.asarray(st.committed_upto))
        assert upto >= 3 * (n - 1), f"replica {r} blocked at {upto}"
        # replica 1's slots in the committed prefix are no-ops
        ops = np.asarray(st.op)
        base = int(np.asarray(st.window_base))
        for i in range(upto + 1):
            if i % 3 == 1:
                assert ops[i - base] == int(Op.NONE)
    # all real commands executed and replied
    assert len(c.replies) == 2 * n
    assert not [e for e in c.reply_log if e.get("duplicate")]


def test_out_of_order_execution_past_blocked_slot():
    """A blocked frontier (dead owner, pre-takeover) must not stop
    commits with non-conflicting keys from executing early
    (mencius.go:799-876)."""
    cfg = CFG._replace(noop_delay=1000)  # takeover effectively off
    c = MenciusCluster(cfg, ext_rows=128)
    c.kill(1)
    n = 10
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n), vals=np.arange(n) + 7,
              cmd_ids=np.arange(n), client_id=1, to=0)
    c.run(8)
    st0 = tree_slice(c.cs.states, 0)
    upto = int(np.asarray(st0.committed_upto))
    ex_upto = int(np.asarray(st0.executed_upto))
    executed = np.asarray(st0.executed)
    status = np.asarray(st0.status)
    base = int(np.asarray(st0.window_base))
    # the frontier is blocked early (replica 1's first slot can't
    # commit: only 2 of 3 alive and 1 owns slot 1)... skip-cede needs
    # the owner alive, so slot 1 stays NONE and blocks
    assert upto < 3 * (n - 1)
    # but committed slots beyond the frontier below the first gap...
    # slot 0 commits and executes; slots beyond gap at slot 1 cannot
    # (unknown content) — verify the gap barrier held AND that every
    # executed slot's reply arrived despite the stalled exec frontier
    first_gap = next(i for i in range(ex_upto + 1, 3 * n)
                     if status[i - base] == NONE)
    for i in range(3 * n - 3):
        if status[i - base] == EXECUTED:
            assert i < first_gap or executed[i - base]
    # replies for commands committed+executed so far arrived
    assert len(c.replies) >= 1


def test_ooo_executes_nonconflicting_after_gap_commits():
    """Once a gap commits (skip arrives late), committed slots above it
    with disjoint keys execute out of order even while an ACCEPTED
    same-key write below them blocks conflicting ones."""
    # direct kernel drive would be needed for a pure OOO observation;
    # at cluster level we assert the executed bitmap can run ahead of
    # executed_upto after mixed traffic
    c = MenciusCluster(CFG, ext_rows=128)
    for r in range(3):
        c.propose(ops=[Op.PUT] * 8, keys=np.arange(8) + 10 * r,
                  vals=np.arange(8), cmd_ids=np.arange(8) + 10 * r,
                  client_id=r + 1, to=r)
    c.run(12)
    st0 = tree_slice(c.cs.states, 0)
    assert int(np.asarray(st0.executed_upto)) >= 21
    assert len(c.replies) == 24
    assert not [e for e in c.reply_log if e.get("duplicate")]


def snapshot_committed(c, r):
    st = tree_slice(c.cs.states, r)
    upto = int(np.asarray(st.committed_upto))
    base = int(np.asarray(st.window_base))
    if upto < base:
        return {}
    sl = slice(0, upto - base + 1)
    cols = [np.asarray(a)[sl] for a in
            (st.op, st.key_lo, st.val_lo, st.cmd_id, st.client_id)]
    return {base + i: tuple(int(col[i]) for col in cols)
            for i in range(upto - base + 1)}


@pytest.mark.parametrize("seed", [5, 17])
def test_mencius_random_fault_schedule_safety(seed):
    """Randomized kills/revives/multi-leader proposals: Consistency
    (no two replicas disagree on a committed slot) + Stability +
    exactly-once, the same invariants as the MinPaxos matrix."""
    rng = np.random.default_rng(seed)
    c = MenciusCluster(CFG, ext_rows=128)
    stable = {r: {} for r in range(3)}
    agreed = {}
    compared = 0
    next_cmd = 0
    for round_ in range(25):
        action = rng.random()
        alive = np.asarray(c.cs.alive)
        if action < 0.6:
            tgt = int(rng.choice(np.nonzero(alive)[0]))
            m = int(rng.integers(1, 20))
            c.propose(ops=rng.choice([Op.PUT, Op.GET], m),
                      keys=rng.integers(0, 25, m),
                      vals=rng.integers(1, 999, m),
                      cmd_ids=np.arange(next_cmd, next_cmd + m),
                      client_id=1, to=tgt)
            next_cmd += m
        elif action < 0.75 and alive.sum() > 2:
            c.kill(int(rng.choice(np.nonzero(alive)[0])))
        elif not alive.all():
            c.revive(int(rng.choice(np.nonzero(~alive)[0])))
        c.run(int(rng.integers(1, 4)))
        for r in range(3):
            snap = snapshot_committed(c, r)
            for i, entry in snap.items():
                if i in stable[r]:
                    assert stable[r][i] == entry, (
                        f"seed {seed} round {round_} replica {r} slot {i} "
                        f"changed: {stable[r][i]} -> {entry}")
                else:
                    stable[r][i] = entry
                if i in agreed:
                    fr, fe = agreed[i]
                    assert fe == entry, (
                        f"seed {seed} round {round_} replica {r} slot {i}: "
                        f"{fe} vs {entry}")
                    if r != fr:
                        compared += 1
                else:
                    agreed[i] = (r, entry)
    assert not [e for e in c.reply_log if e.get("duplicate")]
    assert compared > 0, "Consistency never compared anything (vacuous)"
