"""Tests for the utility layer (reference src/bitvec, src/bloomfilter,
src/dlog, src/rdtsc — extending the reference's only unit tests,
bloomfilter/bloomfilter_test.go)."""

import numpy as np

from minpaxos_tpu.utils import BitVec, BloomFilter, cputicks, monotonic_ns
from minpaxos_tpu.utils.dlog import dlog


def test_bitvec_scalar():
    bv = BitVec(200)
    assert not bv.get_bit(0)
    bv.set_bit(0)
    bv.set_bit(63)
    bv.set_bit(64)
    bv.set_bit(199)
    assert bv.get_bit(0) and bv.get_bit(63) and bv.get_bit(64) and bv.get_bit(199)
    assert not bv.get_bit(1)
    bv.reset_bit(63)
    assert not bv.get_bit(63)
    assert bv.popcount() == 3
    bv.clear()
    assert bv.popcount() == 0


def test_bitvec_vectorized():
    bv = BitVec(1024)
    idx = np.array([0, 5, 5, 700, 1023])
    bv.set_bits(idx)
    got = bv.get_bits(np.arange(1024))
    assert set(np.nonzero(got)[0].tolist()) == {0, 5, 700, 1023}


def test_bloom_no_false_negatives():
    # Mirrors TestCorrect (bloomfilter_test.go:27-48): zero false negatives.
    rng = np.random.default_rng(42)
    keys = rng.integers(0, 2**63, size=5000, dtype=np.uint64)
    bf = BloomFilter(pow_two=17, num_hashes=4)
    bf.add_many(keys)
    assert bf.check_many(keys).all()


def test_bloom_fp_rate_reasonable():
    # Mirrors TestFPRate (bloomfilter_test.go:8-25).
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 2**63, size=5000, dtype=np.uint64)
    other = rng.integers(0, 2**63, size=5000, dtype=np.uint64)
    bf = BloomFilter(pow_two=17, num_hashes=4)
    bf.add_many(keys)
    fp = bf.check_many(other).mean()
    # m/n ~ 26 bits/key, k=4 => theoretical fp ~ 0.24%; allow slack.
    assert fp < 0.02


def test_clocks_monotone():
    a, b = monotonic_ns(), monotonic_ns()
    assert b >= a
    t0 = cputicks()
    t1 = cputicks()
    assert t1 >= t0


def test_dlog_noop():
    dlog("hello %d", 42)  # must not raise in either mode


def test_conflict_matches_reference_semantics():
    """ops/conflict.py vs a literal port of state.go:53-71 run on
    random batches (incl. masked rows)."""
    import jax
    import numpy as np

    from minpaxos_tpu.ops.conflict import conflict, conflict_batch, is_read
    from minpaxos_tpu.wire.messages import Op

    def ref_conflict(a, b):
        return a[1] == b[1] and (a[0] in (int(Op.PUT), int(Op.DELETE))
                                 or b[0] in (int(Op.PUT), int(Op.DELETE)))

    rng = np.random.default_rng(5)
    for _ in range(20):
        na, nb = rng.integers(1, 12, 2)
        A = [(int(rng.choice([Op.PUT, Op.GET, Op.DELETE])),
              int(rng.integers(0, 6))) for _ in range(na)]
        B = [(int(rng.choice([Op.PUT, Op.GET, Op.DELETE])),
              int(rng.integers(0, 6))) for _ in range(nb)]
        va = rng.random(na) < 0.8
        vb = rng.random(nb) < 0.8
        want = any(ref_conflict(a, b)
                   for a, ka in zip(A, va) if ka
                   for b, kb in zip(B, vb) if kb)
        got = jax.jit(conflict_batch)(
            np.array([a[0] for a in A]), np.zeros(na, np.int32),
            np.array([a[1] for a in A], np.int32),
            np.array([b[0] for b in B]), np.zeros(nb, np.int32),
            np.array([b[1] for b in B], np.int32),
            va, vb)
        assert bool(got) == want
    # elementwise + is_read
    assert bool(conflict(int(Op.GET), 0, 7, int(Op.PUT), 0, 7))
    assert not bool(conflict(int(Op.GET), 0, 7, int(Op.GET), 0, 7))
    assert not bool(conflict(int(Op.PUT), 0, 7, int(Op.PUT), 0, 8))
    assert bool(is_read(np.int32(int(Op.GET))))
