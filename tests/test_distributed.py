"""Distributed-runtime integration: real master + replica servers +
clients over localhost TCP, in-process (threads).

Programmatic equivalents of the reference's shell matrix (SURVEY.md
section 4): run.sh boot, simpletest.sh smoke, checklog.sh follower
kill/revive with -durable, leaderelectiontestmaster.sh leader kill +
master-driven election + client failover.
"""

import os
import time

import numpy as np
import pytest

from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.runtime.client import Client, gen_workload
from minpaxos_tpu.runtime.master import Master, get_leader
from minpaxos_tpu.runtime.replica import ReplicaServer, RuntimeFlags
from minpaxos_tpu.utils.netutil import CONTROL_OFFSET, free_ports

SMALL = dict(window=1 << 10, inbox=1024, exec_batch=512, kv_pow2=12,
             catchup_rows=64, recovery_rows=64)


class Harness:
    """Boot master + N replicas on fresh localhost ports."""

    def __init__(self, tmp_path, n=3, durable=False, thrifty=False,
                 classic=False, mencius=False, flags_overrides=None,
                 cfg_overrides=None):
        self.protocol = ("mencius" if mencius
                         else "classic" if classic else "minpaxos")
        # replica data ports need their +1000 control sibling free too
        self.mport = free_ports(1)[0]
        self.addrs = [("127.0.0.1", p) for p in
                      free_ports(n, sibling_offset=CONTROL_OFFSET)]
        self.master = Master("127.0.0.1", self.mport, n, ping_s=0.3)
        self.master.start()
        # register every replica (the CLI binary's startup step)
        from minpaxos_tpu.runtime.master import register_with_master
        for host, port in self.addrs:
            register_with_master(("127.0.0.1", self.mport), host, port,
                                 timeout_s=5.0)
        self.cfg = MinPaxosConfig(n_replicas=n, explicit_commit=classic,
                                  **{**SMALL, **(cfg_overrides or {})})
        overrides = flags_overrides or {}  # per-replica RuntimeFlags kwargs
        self.flags = lambda i: RuntimeFlags(
            durable=durable, thrifty=thrifty, store_dir=str(tmp_path),
            tick_s=0.001, **overrides.get(i, {}))
        self.servers: dict[int, ReplicaServer] = {}
        for i in range(n):
            self.start_replica(i)
        # let replica 0 self-elect and prepare (read via the published
        # snapshot — replica.state is donated into the jitted step and
        # must never be touched from another thread)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if self.servers[0].snapshot["prepared"]:
                break
            time.sleep(0.05)

    def start_replica(self, i) -> None:
        s = ReplicaServer(i, self.addrs, self.cfg, self.flags(i),
                          protocol=self.protocol)
        s.start()
        self.servers[i] = s

    def kill(self, i) -> None:
        self.servers.pop(i).stop()

    def stop(self) -> None:
        for s in self.servers.values():
            s.stop()
        self.master.stop()

    def client(self, check=True) -> Client:
        return Client(("127.0.0.1", self.mport), check=check)


@pytest.fixture
def harness(tmp_path):
    h = None

    def make(**kw):
        nonlocal h
        h = Harness(tmp_path, **kw)
        return h

    yield make
    if h is not None:
        h.stop()


def test_simpletest_smoke(harness):
    """simpletest.sh: 1000 requests, exactly once."""
    h = harness()
    cli = h.client()
    ops, keys, vals = gen_workload(1000, seed=42)
    stats = cli.run_workload(ops, keys, vals, timeout_s=30)
    assert stats["acked"] == 1000, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_reads_are_served(harness):
    """READ frames (parse-and-dropped by the reference) are served as
    linearizable GETs through the log."""
    h = harness()
    cli = h.client()
    stats = cli.run_workload(np.array([1]), np.array([77]), np.array([123]),
                             timeout_s=15)
    assert stats["acked"] == 1
    cli.read([1000], [77])
    assert cli.wait([1000], timeout_s=10)
    assert cli.replies[1000]["val"] == 123
    cli.close_conn()


def test_follower_kill_revive_durable(harness, tmp_path):
    """checklog.sh: kill follower under load, keep committing, revive
    with the stable store, verify it catches back up."""
    h = harness(durable=True)
    cli = h.client()
    ops, keys, vals = gen_workload(300, seed=1)
    assert cli.run_workload(ops, keys, vals, timeout_s=30)["acked"] == 300
    h.kill(2)
    ops2, keys2, vals2 = gen_workload(300, seed=2)
    cli.replies.clear()
    assert cli.run_workload(ops2, keys2, vals2, timeout_s=30)["acked"] == 300
    # revive from its stable store; leader catch-up heals the gap
    h.start_replica(2)
    deadline = time.monotonic() + 20
    target = h.servers[0].snapshot["frontier"]
    while time.monotonic() < deadline:
        if h.servers[2].snapshot["frontier"] >= target:
            break
        time.sleep(0.1)
    assert h.servers[2].snapshot["frontier"] >= target
    cli.close_conn()


def test_leader_kill_election_failover(harness):
    """leaderelectiontestmaster.sh + client+killprocess.sh: kill the
    leader; master promotes a live replica; the client fails over and
    finishes the workload with no duplicates."""
    h = harness()
    cli = h.client()
    ops, keys, vals = gen_workload(200, seed=3)
    assert cli.run_workload(ops, keys, vals, timeout_s=30)["acked"] == 200
    h.kill(0)
    # master ping loop notices and promotes the highest-frontier replica
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if h.master.leader != 0:
            break
        time.sleep(0.1)
    assert h.master.leader != 0
    cli.replies.clear()
    ops2, keys2, vals2 = gen_workload(200, seed=4)
    stats = cli.run_workload(ops2, keys2, vals2, timeout_s=30)
    assert stats["acked"] == 200, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_kv_saturation_fails_stop(harness):
    """A fixed-capacity KV table that drops an insert must fail-stop
    loudly (ping ok=False + fatal reason), never silently lose an
    acknowledged write (the reference's map grows without limit,
    state.go:33-36 — a bounded table's only honest fallback is
    crashing, which consensus tolerates)."""
    h = harness(cfg_overrides=dict(kv_pow2=3))  # 8 KV slots
    cli = h.client(check=False)
    n = 64  # 64 distinct keys >> 8 slots: guaranteed saturation
    ops = np.full(n, 1, np.int64)  # Op.PUT
    keys = np.arange(n, dtype=np.int64) + 1000
    vals = np.arange(n, dtype=np.int64)
    # keep driving the saturating workload until a replica fail-stops:
    # one bounded run + a fixed poll was timing-flaky (a slow follower
    # may not have stepped its dropped insert yet when the poll ends)
    deadline = time.monotonic() + 60
    fatal = None
    while time.monotonic() < deadline and fatal is None:
        cli.replies.clear()  # else a fully-acked run makes every later
        try:                 # run_workload return without proposing
            cli.run_workload(ops, keys, vals, timeout_s=5)
        except OSError:
            pass  # the proposed-to replica may itself have fail-stopped
        for s in h.servers.values():
            if s.fatal is not None:
                fatal = s.fatal
                break
        time.sleep(0.1)
    assert fatal is not None and "saturated" in fatal, fatal
    # control plane reports the failure (what the master's ping sees)
    import json as _json
    import socket as _socket
    host, port = h.addrs[0]
    with _socket.create_connection((host, port + CONTROL_OFFSET),
                                   timeout=5) as s:
        f = s.makefile("rw")
        f.write(_json.dumps({"m": "ping"}) + "\n")
        f.flush()
        resp = _json.loads(f.readline())
    if resp["fatal"] is not None:  # replica 0 may or may not be first
        assert not resp["ok"] and "saturated" in resp["fatal"]
    cli.close_conn()


def test_thrifty_still_commits(harness):
    h = harness(thrifty=True)
    cli = h.client()
    ops, keys, vals = gen_workload(200, seed=5)
    stats = cli.run_workload(ops, keys, vals, timeout_s=30)
    assert stats["acked"] == 200, stats
    cli.close_conn()


def test_classic_paxos_over_tcp(harness):
    """Classic per-instance Multi-Paxos (server -classic) over the real
    TCP runtime: commits flow only via explicit Commit/CommitShort
    (paxos.go:336-386) and exactly-once holds end-to-end."""
    h = harness(classic=True)
    cli = h.client()
    ops, keys, vals = gen_workload(500, seed=7)
    stats = cli.run_workload(ops, keys, vals, timeout_s=30)
    assert stats["acked"] == 500, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_tot_and_openloop_client_modes(harness, capsys):
    """clienttot -tot (10ms x 50 smoothed buckets) and client-ol-lat
    -ol (paced open-loop with reply-timestamp latency) run against a
    live cluster and print their reports."""
    h = harness()
    from minpaxos_tpu.cli.client import main as cmain

    # -sr bounds the key space below SMALL's 4096-slot KV table: 20000
    # uniform keys over the default 100000 range would saturate it and
    # trip the runtime's fail-stop (which this round made loud — the
    # old silent behavior dropped ~14k acknowledged writes here while
    # the check still passed)
    cmain(["-mport", str(h.mport), "-q", "20000", "-sr", "1500", "-tot",
           "-check", "-timeout", "120"])
    out = capsys.readouterr().out
    assert "ops/s (smoothed)" in out, out
    assert "CHECK OK" in out, out

    cmain(["-mport", str(h.mport), "-q", "400", "-ol", "-ns", "2000000",
           "-batch", "64"])
    out = capsys.readouterr().out
    assert "open-loop" in out and "p50" in out, out


def test_lat_mode_measures_real_roundtrips(harness, capsys):
    """-lat must measure genuine consensus round trips: with 1ms
    protocol ticks and TCP hops, sub-100us medians would mean stale
    replies are being matched (the reused-cmd_id bug)."""
    h = harness()
    from minpaxos_tpu.cli.client import main as cmain

    cmain(["-mport", str(h.mport), "-q", "50", "-lat"])
    out = capsys.readouterr().out
    assert "p50" in out, out
    p50_ms = float(out.split("p50")[1].split("ms")[0])
    assert p50_ms > 0.1, f"implausibly fast serial latency: {out}"


def test_beyond_retention_heal_from_stable_store(harness, tmp_path):
    """VERDICT round-2 item 8a: a peer lagging past the leader's
    retained window cannot be healed by device catch-up rows (they
    slid out); the leader must serve it from the durable log's
    in-memory mirror (_host_catchup). Forces a real slide: window=1024,
    retention=512, and ~1400 commits while the follower is dead."""
    h = harness(durable=True)
    cli = h.client()
    ops, keys, vals = gen_workload(200, seed=11)
    assert cli.run_workload(ops, keys, vals, timeout_s=30)["acked"] == 200
    h.kill(2)
    # enough commits that the leader's window_base slides past the
    # dead follower's frontier (~200): needs > retention (512) of
    # executed slots beyond it
    cli.replies.clear()
    ops2, keys2, vals2 = gen_workload(1400, seed=12)
    assert cli.run_workload(ops2, keys2, vals2,
                            timeout_s=60)["acked"] == 1400
    lead_base = h.servers[0].snapshot["window_base"]
    assert lead_base > 250, (
        f"window never slid (base={lead_base}); test setup is vacuous")
    # revive from its stable store; the ONLY heal path for the
    # beyond-window gap is _host_catchup from the store mirror
    h.start_replica(2)
    target = h.servers[0].snapshot["frontier"]
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline:
        if h.servers[2].snapshot["frontier"] >= target:
            break
        time.sleep(0.2)
    assert h.servers[2].snapshot["frontier"] >= target, (
        f"laggard stuck at {h.servers[2].snapshot['frontier']} < {target}")
    cli.close_conn()


def test_stale_boot_self_election_skipped(harness, tmp_path):
    """A replica 0 whose first tick is delayed (e.g. a minutes-long
    first jit compile on a loaded host) must NOT depose an established
    leader with its empty log when it finally wakes: the boot
    self-election is a cold-start convenience (bareminpaxos.go:286-290),
    not an authority claim. Round-5 wedge hunt: the stale election
    deposed a healthy leader mid-run and froze the cluster at the old
    leader's final catch-up chunk."""
    import json as _json
    import socket as _socket
    import threading as _threading

    h = harness()
    cli = h.client()
    ops, keys, vals = gen_workload(300, seed=3)
    assert cli.run_workload(ops, keys, vals, timeout_s=30)["acked"] == 300
    # establish a non-0 leader, as in test_master_adopts_protocol_leader
    host, port = h.addrs[2]
    with _socket.create_connection((host, port + CONTROL_OFFSET),
                                   timeout=5) as s:
        f = s.makefile("rw")
        f.write(_json.dumps({"m": "be_the_leader"}) + "\n")
        f.flush()
        assert _json.loads(f.readline())["ok"]
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and h.master.leader != 2:
        time.sleep(0.1)
    assert h.master.leader == 2
    # restart replica 0 EMPTY while traffic flows: its boot path
    # enqueues be_the_leader("boot"), which must be recognized as
    # stale (leader traffic already seen / committed prefix exists).
    # The store file must go too — a recovered ex-leader resumes its
    # old role via state restore, which is a different (legitimate)
    # path than the boot self-election under test.
    h.kill(0)
    for f in tmp_path.glob("stable-store-replica0"):
        f.unlink()
    cli.replies.clear()
    ops2, keys2, vals2 = gen_workload(1200, seed=4)
    pump_stats = {}

    def pump():
        c2 = h.client()
        pump_stats.update(c2.run_workload(ops2, keys2, vals2,
                                          timeout_s=60))
        c2.close_conn()

    t = _threading.Thread(target=pump, daemon=True)
    t.start()
    time.sleep(0.3)
    h.start_replica(0)
    t.join(timeout=90)
    assert pump_stats.get("acked") == 1200, pump_stats
    assert pump_stats.get("duplicates") == 0
    # the late riser re-followed instead of deposing
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if h.servers[0].snapshot["leader"] == 2:
            break
        time.sleep(0.1)
    assert h.servers[0].snapshot["leader"] == 2, h.servers[0].snapshot
    assert h.master.leader == 2
    cli.close_conn()


def test_laggard_leader_heals_via_store_served_sweep(harness, tmp_path):
    """A leader elected with a nearly-empty log (revived laggard,
    promoted before it caught up) must heal through its phase-1 sweep
    even for slots that slid out of every follower's window: followers
    serve those ranges from the durable store as COMMIT rows
    (_store_answer_sweep — round-5 fix; previously minpaxos had no
    store path and the cluster wedged at the laggard's first
    unanswerable chunk)."""
    import json as _json
    import socket as _socket

    h = harness(durable=True)
    h.kill(2)  # dies before any traffic: revives with an empty log
    cli = h.client()
    ops, keys, vals = gen_workload(1400, seed=13)
    assert cli.run_workload(ops, keys, vals, timeout_s=60)["acked"] == 1400
    lead_base = h.servers[0].snapshot["window_base"]
    assert lead_base > 250, (
        f"window never slid (base={lead_base}); test setup is vacuous")
    h.start_replica(2)
    # promote the empty laggard IMMEDIATELY (before normal laggard
    # catch-up can close the gap): its sweep now starts at slot 0,
    # far below the up-to-date replicas' window bases
    host, port = h.addrs[2]
    deadline = time.monotonic() + 20
    while True:
        try:
            with _socket.create_connection(
                    (host, port + CONTROL_OFFSET), timeout=5) as s:
                f = s.makefile("rw")
                f.write(_json.dumps({"m": "be_the_leader"}) + "\n")
                f.flush()
                assert _json.loads(f.readline())["ok"]
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.2)
    target = 1399
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        if h.servers[2].snapshot["frontier"] >= target:
            break
        time.sleep(0.2)
    assert h.servers[2].snapshot["frontier"] >= target, (
        f"laggard leader stuck at {h.servers[2].snapshot['frontier']}"
        f" < {target} (sweep not healed from stores)")
    # and it actually serves: fresh client, more commands, exactly-once
    cli2 = h.client()
    ops2, keys2, vals2 = gen_workload(100, seed=14)
    stats = cli2.run_workload(ops2, keys2, vals2, timeout_s=60)
    assert stats["acked"] == 100 and stats["duplicates"] == 0, stats
    cli2.close_conn()
    cli.close_conn()


def test_master_adopts_protocol_leader(harness):
    """If the protocol moves leadership without the master (here: a
    direct be_the_leader control RPC, standing in for a deposal
    election after a spurious promotion), the master must reconcile
    its GetLeader answer from the majority of ping-reported leader
    views — a stale answer strands clients on a rejecting non-leader
    (round-4 verify finding: -lat measured nothing for 100s)."""
    import json as _json
    import socket as _socket

    h = harness()
    assert h.master.leader == 0
    host, port = h.addrs[2]
    with _socket.create_connection((host, port + CONTROL_OFFSET),
                                   timeout=5) as s:
        f = s.makefile("rw")
        f.write(_json.dumps({"m": "be_the_leader"}) + "\n")
        f.flush()
        assert _json.loads(f.readline())["ok"]
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if h.master.leader == 2:
            break
        time.sleep(0.1)
    assert h.master.leader == 2, (
        f"master stuck on {h.master.leader}")
    # and clients routed through the master commit against the new
    # leader directly
    cli = h.client()
    ops, keys, vals = gen_workload(100, seed=77)
    assert cli.run_workload(ops, keys, vals, timeout_s=30)["acked"] == 100
    cli.close_conn()


def test_master_elects_highest_frontier(harness):
    """VERDICT round-2 item 8b: the master must promote the most
    caught-up replica, not the first alive one — a freshly revived
    laggard would have to run the whole committed-state transfer
    before serving (and in the reference's scheme would simply serve
    stale state). Stage: follower 1 lags far behind, leader 0 dies;
    the master must pick 2."""
    h = harness(durable=True)
    cli = h.client()
    ops, keys, vals = gen_workload(200, seed=21)
    assert cli.run_workload(ops, keys, vals, timeout_s=30)["acked"] == 200
    h.kill(1)
    cli.replies.clear()
    ops2, keys2, vals2 = gen_workload(600, seed=22)
    assert cli.run_workload(ops2, keys2, vals2, timeout_s=60)["acked"] == 600
    # revive 1 (far behind), then immediately kill the leader: the
    # master's next election must prefer 2 (frontier ~800) over 1
    h.start_replica(1)
    h.kill(0)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if h.master.leader == 2:
            break
        time.sleep(0.1)
    assert h.master.leader == 2, (
        f"master elected {h.master.leader}; frontiers {h.master.frontiers}")
    # wait for the new leader's prepare majority before proposing (the
    # revived laggard answers the PREPARE only after its store replay)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if h.servers[2].snapshot["prepared"]:
            break
        time.sleep(0.1)
    assert h.servers[2].snapshot["prepared"]
    # and the cluster still serves
    cli.replies.clear()
    ops3, keys3, vals3 = gen_workload(100, seed=23)
    stats = cli.run_workload(ops3, keys3, vals3, timeout_s=40)
    assert stats["acked"] == 100, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_data_plane_survives_master_death(harness):
    """masterkill.sh: the master is control-plane only — killing it
    must not interrupt committed writes for already-connected clients
    (reference masterkill.sh kills port 7087 and nothing else)."""
    h = harness()
    cli = h.client()
    ops, keys, vals = gen_workload(300, seed=9)
    assert cli.run_workload(ops[:100], keys[:100], vals[:100],
                            timeout_s=30)["acked"] == 100
    h.master.stop()  # data plane must not notice
    cli.replies.clear()
    stats = cli.run_workload(ops[100:], keys[100:], vals[100:],
                             timeout_s=30)
    assert stats["acked"] == 200, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_cpuprofile_captures_protocol_thread(harness):
    """-cpuprofile parity (server.go:41-51 pprof): cProfile is
    per-thread, so the PROTOCOL thread must enable it — wired on the
    main thread it would profile an idle sleep loop and dump nothing."""
    import cProfile
    import pstats

    prof = cProfile.Profile()
    h = harness(flags_overrides={0: {"profile": prof}})
    cli = h.client(check=False)
    ops, keys, vals = gen_workload(50, seed=11)
    assert cli.run_workload(ops, keys, vals, timeout_s=60)["acked"] == 50
    assert h.servers[0].stop(), "protocol thread must join"
    h.servers.pop(0)
    stats = pstats.Stats(prof)
    profiled = {fn[2] for fn in stats.stats}
    assert "_device_tick" in profiled, sorted(profiled)[:20]
    cli.close_conn()


def test_multiclient_rr_drives_all_mencius_owners(harness):
    """The -e leaderless round-robin client (reference client.go:19-31)
    drives EVERY Mencius owner concurrently — the protocol's intended
    workload (a single hinted proposer makes every other owner cede
    each slot). Exactly-once must hold across the N connections and
    every owner must actually serve proposals."""
    from minpaxos_tpu.runtime.client import MultiClient

    h = harness(mencius=True)
    mc = MultiClient(("127.0.0.1", h.mport), check=True, mode="rr")
    ops, keys, vals = gen_workload(300, seed=91)
    stats = mc.run_workload(ops, keys, vals, timeout_s=60)
    assert stats["acked"] == 300, stats
    assert stats["duplicates"] == 0
    served = [h.servers[r].stats["proposals"] for r in range(3)]
    assert all(s > 0 for s in served), served
    mc.close()


def test_multiclient_fast_mode_first_reply_wins(harness):
    """The -f fast mode (reference client.go -f) fans every command to
    all replicas; non-leaders reject, the leader's reply wins, and the
    per-connection books see no duplicates."""
    from minpaxos_tpu.runtime.client import MultiClient

    h = harness()
    mc = MultiClient(("127.0.0.1", h.mport), check=True, mode="fast")
    ops, keys, vals = gen_workload(200, seed=92)
    stats = mc.run_workload(ops, keys, vals, timeout_s=60)
    assert stats["acked"] == 200, stats
    assert stats["duplicates"] == 0
    mc.close()


def test_mencius_over_tcp(harness):
    """Mencius as a real TCP server protocol (server -m): the
    reference compiled mencius but commented it out of server.go:58-79
    — here it runs. One client proposes to replica 0; the idle owners
    cede their interleaved slots via wire SKIP frames and every
    command commits exactly-once."""
    h = harness(mencius=True)
    cli = h.client()
    ops, keys, vals = gen_workload(400, seed=13)
    stats = cli.run_workload(ops, keys, vals, timeout_s=60)
    assert stats["acked"] == 400, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_mencius_tcp_dead_owner_takeover_and_revive(harness, tmp_path):
    """Kill an idle owner: its slots stop ceding and the frontier
    blocks until the takeover sweep (forceCommit, mencius.go:878-897)
    no-op-fills them over TCP. Revive it from the durable store and
    check it heals back to the cluster frontier (replay + takeover)."""
    h = harness(mencius=True, durable=True)
    cli = h.client()
    ops, keys, vals = gen_workload(200, seed=14)
    assert cli.run_workload(ops, keys, vals, timeout_s=60)["acked"] == 200
    h.kill(2)
    ops2, keys2, vals2 = gen_workload(200, seed=15)
    cli.replies.clear()
    stats = cli.run_workload(ops2, keys2, vals2, timeout_s=60)
    assert stats["acked"] == 200, stats  # commits despite the dead owner
    h.start_replica(2)
    deadline = time.monotonic() + 30
    target = h.servers[0].snapshot["frontier"]
    while time.monotonic() < deadline:
        if h.servers[2].snapshot["frontier"] >= target:
            break
        time.sleep(0.1)
    assert h.servers[2].snapshot["frontier"] >= target, (
        h.servers[2].snapshot, target)
    cli.close_conn()


def test_classic_paxos_leader_kill_election(harness):
    """Classic per-instance Paxos shares the election machinery but
    commits only via explicit Commit/CommitShort — a new leader must
    finish the old leader's in-flight instances through the
    per-instance phase-1 sweep (paxos.go:388-442) before serving."""
    h = harness(classic=True)
    cli = h.client()
    ops, keys, vals = gen_workload(200, seed=21)
    assert cli.run_workload(ops, keys, vals, timeout_s=30)["acked"] == 200
    h.kill(0)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if h.master.leader != 0:
            break
        time.sleep(0.1)
    assert h.master.leader != 0
    cli.replies.clear()
    ops2, keys2, vals2 = gen_workload(200, seed=22)
    stats = cli.run_workload(ops2, keys2, vals2, timeout_s=40)
    assert stats["acked"] == 200, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_mencius_proposer_kill_failover(harness):
    """Kill the replica clients propose to (mencius has no leader, but
    the master still hints one): the master promotes another replica,
    the client fails over, and the dead owner's slots are taken over —
    commits continue exactly-once."""
    h = harness(mencius=True)
    cli = h.client()
    ops, keys, vals = gen_workload(150, seed=31)
    assert cli.run_workload(ops, keys, vals, timeout_s=60)["acked"] == 150
    h.kill(0)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if h.master.leader != 0:
            break
        time.sleep(0.1)
    assert h.master.leader != 0
    cli.replies.clear()
    ops2, keys2, vals2 = gen_workload(150, seed=32)
    stats = cli.run_workload(ops2, keys2, vals2, timeout_s=60)
    assert stats["acked"] == 150, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_majority_loss_stalls_then_resumes(harness, tmp_path):
    """Kill BOTH followers (majority lost): nothing may commit — then
    revive one and the cluster must resume and finish the workload
    exactly-once. The stall phase is the safety half of the spec: a
    minority leader accepting writes silently would be the bug."""
    h = harness(durable=True)
    cli = h.client()
    ops, keys, vals = gen_workload(100, seed=41)
    assert cli.run_workload(ops, keys, vals, timeout_s=30)["acked"] == 100
    before = h.servers[0].snapshot["frontier"]
    h.kill(1)
    h.kill(2)
    cli.replies.clear()
    ops2, keys2, vals2 = gen_workload(100, seed=42)
    stats = cli.run_workload(ops2, keys2, vals2, timeout_s=6)
    assert stats["acked"] == 0, stats  # no quorum -> no commits
    assert h.servers[0].snapshot["frontier"] == before
    h.start_replica(1)  # majority restored (its store is fresh: healed
    # by the leader's catch-up rows)
    stats = cli.run_workload(ops2, keys2, vals2, timeout_s=40)
    assert stats["acked"] == 100, stats
    assert stats["duplicates"] == 0
    cli.close_conn()


def test_chaos_follower_churn_exactly_once(harness):
    """Randomized kill/revive churn of followers under continuous
    load (the TCP-runtime cousin of tests/test_safety_random.py):
    whatever the interleaving of socket deaths, redials, store replays
    and catch-up, every command acks exactly once."""
    rng = np.random.default_rng(5150)
    h = harness(durable=True)
    cli = h.client()
    total = 0
    for phase in range(4):
        victim = int(rng.integers(1, 3))  # churn followers only
        if victim in h.servers:
            h.kill(victim)
        n = int(rng.integers(80, 160))
        ops, keys, vals = gen_workload(n, conflict_pct=30, seed=60 + phase)
        cli.replies.clear()
        stats = cli.run_workload(ops, keys, vals, timeout_s=40)
        assert stats["acked"] == n, (phase, stats)
        assert stats["duplicates"] == 0, (phase, stats)
        total += n
        if victim not in h.servers:
            h.start_replica(victim)
        time.sleep(0.2)
    # final convergence: both followers alive again, frontiers meet
    deadline = time.monotonic() + 30
    target = h.servers[0].snapshot["frontier"]
    while time.monotonic() < deadline:
        if all(h.servers[i].snapshot["frontier"] >= target
               for i in (1, 2) if i in h.servers):
            break
        time.sleep(0.1)
    for i in (1, 2):
        assert h.servers[i].snapshot["frontier"] >= target
    cli.close_conn()


def test_mencius_chaos_owner_churn_exactly_once(harness):
    """Owner kill/revive churn for the Mencius TCP path: each dead
    owner forces takeover no-op fills; each revival forces pull-based
    healing (store replay + takeover sweeps + store-served commits).
    Exactly-once must hold throughout."""
    rng = np.random.default_rng(6001)
    h = harness(mencius=True, durable=True)
    cli = h.client()
    for phase in range(3):
        victim = int(rng.integers(1, 3))  # keep the hinted proposer up
        if victim in h.servers:
            h.kill(victim)
        n = int(rng.integers(60, 120))
        ops, keys, vals = gen_workload(n, conflict_pct=30, seed=80 + phase)
        cli.replies.clear()
        stats = cli.run_workload(ops, keys, vals, timeout_s=60)
        assert stats["acked"] == n, (phase, stats)
        assert stats["duplicates"] == 0, (phase, stats)
        if victim not in h.servers:
            h.start_replica(victim)
        time.sleep(0.3)
    cli.close_conn()


def test_multiclient_bar_one_and_wait_less(harness):
    """clienttot's -barOne (send to all replicas but the last,
    clienttot/client.go:31, :76-78) and -waitLess (wait for all but
    one partition, :32, :191-199): last replica serves no proposals,
    every command still acks exactly-once."""
    from minpaxos_tpu.runtime.client import MultiClient

    h = harness()
    mc = MultiClient(("127.0.0.1", h.mport), check=True, mode="rr",
                     bar_one=True)
    assert len(mc.clients) == 2  # 3 replicas, last excluded
    ops, keys, vals = gen_workload(300, seed=21)
    stats = mc.run_workload(ops, keys, vals, timeout_s=60)
    assert stats["acked"] == 300 and stats["duplicates"] == 0, stats
    # the excluded replica never saw a client proposal
    assert h.servers[2].stats["proposals"] == 0
    mc.close()
    # -waitLess: the driver returns once all but one partition
    # finished; the straggler's tail may be uncounted (that IS the
    # semantics — tolerate one slow replica), but nothing duplicates
    mc2 = MultiClient(("127.0.0.1", h.mport), check=True, mode="rr",
                      wait_less=True)
    ops2, keys2, vals2 = gen_workload(300, seed=22)
    stats2 = mc2.run_workload(ops2, keys2, vals2, timeout_s=60)
    per_part = 300 // len(mc2.clients) + 1
    assert stats2["acked"] >= 300 - per_part, stats2
    assert stats2["duplicates"] == 0
    mc2.close()
