"""Classic per-instance Multi-Paxos (models/paxos.py) tests.

The protocol's defining behaviors vs MinPaxos, from the reference:
commits travel ONLY as explicit Commit/CommitShort (paxos.go:522-575),
never as the Accept LastCommitted piggyback; instances commit at their
own ballots (paxos.go:57-70); one ToInfinity phase-1 round then elision
(paxos.go:421-442).
"""

import numpy as np
import pytest

from minpaxos_tpu.models.cluster import Cluster, tree_slice
from minpaxos_tpu.models.minpaxos import (
    ACCEPTED,
    COMMITTED,
    MinPaxosConfig,
    MsgBatch,
    init_replica,
    replica_step_impl,
)
from minpaxos_tpu.models.paxos import classic_config
from minpaxos_tpu.wire.messages import MsgKind, Op

CFG = classic_config(n_replicas=3, window=256, inbox=512, exec_batch=128,
                     kv_pow2=10)


def _accept_rows(cfg, n, ballot, last_committed):
    b = MsgBatch.empty(cfg.inbox)
    return b._replace(
        kind=b.kind.at[:n].set(int(MsgKind.ACCEPT)),
        src=b.src.at[:n].set(0),
        ballot=b.ballot.at[:n].set(ballot),
        inst=b.inst.at[:n].set(np.arange(n)),
        last_committed=b.last_committed.at[:n].set(last_committed),
        op=b.op.at[:n].set(int(Op.PUT)),
        key_lo=b.key_lo.at[:n].set(np.arange(n)),
        val_lo=b.val_lo.at[:n].set(np.arange(n) * 2),
    )


def test_classic_follower_ignores_accept_piggyback():
    """The piggybacked LastCommitted must NOT commit anything in
    classic mode (it does in MinPaxos — that's the protocols' defining
    difference); an explicit COMMIT_SHORT must."""
    bal = 16  # ballot of leader 0
    for explicit, expect_commit in ((True, False), (False, True)):
        cfg = MinPaxosConfig(n_replicas=3, window=256, inbox=64,
                             exec_batch=16, kv_pow2=8,
                             explicit_commit=explicit)
        st = init_replica(cfg, me=1)
        st = st._replace(default_ballot=np.int32(bal))
        st, _, _ = replica_step_impl(cfg, st, _accept_rows(cfg, 8, bal, 7))
        upto = int(np.asarray(st.committed_upto))
        if expect_commit:
            assert upto == 7, "MinPaxos piggyback must commit"
        else:
            assert upto == -1, "classic follower committed from piggyback"
            assert int(np.asarray(st.status)[0]) == ACCEPTED
            # now the explicit frontier broadcast arrives
            cs = MsgBatch.empty(cfg.inbox)
            cs = cs._replace(
                kind=cs.kind.at[0].set(int(MsgKind.COMMIT_SHORT)),
                src=cs.src.at[0].set(0),
                ballot=cs.ballot.at[0].set(bal),
                last_committed=cs.last_committed.at[0].set(7),
            )
            st, _, _ = replica_step_impl(cfg, st, cs)
            assert int(np.asarray(st.committed_upto)) == 7
            assert int(np.asarray(st.status)[0]) >= COMMITTED


def test_classic_end_to_end_commit_and_reply():
    c = Cluster(CFG, ext_rows=256)
    c.elect(0)
    c.run(3)
    c.propose(ops=[Op.PUT, Op.PUT, Op.GET], keys=[1, 2, 1],
              vals=[10, 20, 0], cmd_ids=[0, 1, 2], client_id=7)
    c.run(5)
    assert c.replies[(7, 0)]["value"] == 10
    assert c.replies[(7, 2)]["value"] == 10 and c.replies[(7, 2)]["found"]
    # followers converged through explicit commits only
    for r in range(3):
        st = tree_slice(c.cs.states, r)
        assert int(np.asarray(st.committed_upto)) == 2
    dups = [e for e in c.reply_log if e.get("duplicate")]
    assert not dups


def test_classic_leader_failover():
    c = Cluster(CFG, ext_rows=256)
    c.elect(0)
    c.run(3)
    n = 40
    c.propose(ops=[Op.PUT] * n, keys=np.arange(n), vals=np.arange(n) * 9,
              cmd_ids=np.arange(n), client_id=3)
    c.run(4)
    c.kill(0)
    c.elect(1)
    c.run(25)
    m = 10
    c.propose(ops=[Op.PUT] * m, keys=np.arange(m) + 100,
              vals=np.arange(m) + 500, cmd_ids=np.arange(m) + n,
              client_id=3, to=1)
    c.run(8)
    st1 = tree_slice(c.cs.states, 1)
    assert int(np.asarray(st1.committed_upto)) >= n + m - 1
    # old values survived the failover (phase-1 sweep re-drove them)
    snap_ops = np.asarray(st1.op)
    snap_vals = np.asarray(st1.val_lo)
    base = int(np.asarray(st1.window_base))
    for i in range(n):
        assert snap_vals[i - base] == i * 9, f"slot {i} lost its value"


def test_classic_mixed_ballot_instances_commit():
    """Per-instance ballots: after a failover, re-driven instances and
    new instances carry different ballots, and both commit — the
    per-instance bookkeeping classic paxos keeps (paxos.go:57-70)."""
    c = Cluster(CFG, ext_rows=256)
    c.elect(0)
    c.run(3)
    c.propose(ops=[Op.PUT] * 5, keys=np.arange(5), vals=np.arange(5),
              cmd_ids=np.arange(5), client_id=1)
    c.run(4)
    c.elect(1)  # higher ballot, same membership
    c.run(15)
    c.propose(ops=[Op.PUT] * 5, keys=np.arange(5) + 50,
              vals=np.arange(5) + 50, cmd_ids=np.arange(5) + 5,
              client_id=1, to=1)
    c.run(6)
    st = tree_slice(c.cs.states, 1)
    assert int(np.asarray(st.committed_upto)) >= 9
    ballots = np.asarray(st.ballot)[:10]
    # slots 0-4 committed under leader 0's era keep their ORIGINAL
    # ballot (committed slots answer the sweep with COMMIT rows, never
    # get re-driven); slots 5-9 carry leader 1's strictly higher ballot
    # — the per-instance coexistence classic paxos allows and the
    # global-ballot mode forbids
    old = set(ballots[:5].tolist())
    new = set(ballots[5:].tolist())
    assert len(old) == 1 and len(new) == 1, (old, new)
    assert min(new) > max(old), f"expected mixed ballots, got {ballots}"
    # every committed slot's value is intact
    vals = np.asarray(st.val_lo)[:10]
    want = list(range(5)) + [50 + i for i in range(5)]
    assert vals.tolist() == want
