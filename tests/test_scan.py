"""Tests for ops/scan.py parallel primitives."""

import numpy as np
import jax.numpy as jnp

from minpaxos_tpu.ops.scan import (
    commit_frontier,
    exclusive_segmented_scan_max,
    segmented_scan_max,
)


def _oracle_seg_max(values, seg_start):
    out = np.empty_like(values)
    cur = None
    for i, (v, s) in enumerate(zip(values, seg_start)):
        cur = v if (s or cur is None) else max(cur, v)
        out[i] = cur
    return out


def test_segmented_scan_max_random():
    rng = np.random.default_rng(0)
    for n in (1, 2, 7, 64, 1000):
        vals = rng.integers(-100, 100, n).astype(np.int32)
        seg = rng.random(n) < 0.2
        seg[0] = True
        got = np.asarray(segmented_scan_max(jnp.asarray(vals), jnp.asarray(seg)))
        assert (got == _oracle_seg_max(vals, seg)).all()


def test_exclusive_segmented_scan_max():
    vals = jnp.asarray(np.array([5, 1, 9, 2, 3, 8], dtype=np.int32))
    seg = jnp.asarray(np.array([True, False, False, True, False, False]))
    got = np.asarray(exclusive_segmented_scan_max(vals, seg, jnp.int32(-1)))
    assert (got == np.array([-1, 5, 5, -1, 2, 3])).all()


def test_commit_frontier():
    c = jnp.asarray(np.array([1, 1, 1, 0, 1, 1], dtype=bool))
    assert int(commit_frontier(c, jnp.int32(0))) == 2
    assert int(commit_frontier(c, jnp.int32(3))) == 2
    assert int(commit_frontier(c, jnp.int32(4))) == 5
    allc = jnp.ones(8, dtype=bool)
    assert int(commit_frontier(allc, jnp.int32(0))) == 7
    none = jnp.zeros(8, dtype=bool)
    assert int(commit_frontier(none, jnp.int32(0))) == -1
