"""paxwatch: event-journal rings + anchor alignment, schema-v6
reserved-pid pins, SLO/anomaly detector units on synthetic series
(stall fire/no-fire boundary + attribution, churn budget, burn-rate
math, backlog slope), HealthWatcher raise/clear edges, retention-layer
bounds under a simulated week-long run, and the paxtop --once --json
stable key schema (OBSERVABILITY.md documents it)."""

import importlib.util
import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from minpaxos_tpu.obs import watch as W
from minpaxos_tpu.obs.recorder import (
    WATCH_PID,
    FlightRecorder,
    chrome_trace,
    validate_chrome_trace,
)

REPO = Path(__file__).resolve().parents[1]


# ---------------------------------------------------------- journal


def test_event_ring_wraparound_keeps_newest():
    r = W.EventRing(capacity=4)
    for i in range(10):
        r.record(1000 + i, 2000 + i, W.EV_ELECTION, 0, i, 0, 0, 0)
    rows = r.snapshot()
    assert rows.shape == (4, W.N_EVENT_FIELDS)
    assert rows[:, W.EV_SUBJECT].tolist() == [6, 7, 8, 9]  # newest 4
    assert r.total == 10 and r.dropped == 6


def test_journal_per_thread_rings_and_counts():
    j = W.EventJournal(capacity=64)
    j.record(W.EV_ELECTION, subject=0)

    def other():
        j.record(W.EV_CLIENT_FAILOVER, subject=1)

    t = threading.Thread(target=other)
    t.start()
    t.join()
    # two writer threads -> two rings, both collected
    assert j.events_total() == 2
    assert j.counts_by_kind() == {"election": 1, "client_failover": 1}
    rows = j.snapshot()
    assert rows.shape[0] == 2
    # merged snapshot is mono-sorted
    assert rows[0, W.EV_MONO] <= rows[1, W.EV_MONO]
    # default severities applied per kind
    by_kind = {int(r[W.EV_KIND]): int(r[W.EV_SEV]) for r in rows}
    assert by_kind[W.EV_ELECTION] == W.SEV_INFO
    assert by_kind[W.EV_CLIENT_FAILOVER] == W.SEV_WARN


def test_journal_disabled_records_nothing():
    j = W.EventJournal(enabled=False)
    j.record(W.EV_FATAL, subject=0)
    assert j.events_total() == 0
    assert j.collect()["events"] == []


def test_align_event_collections_cross_process_anchors():
    """Two processes whose monotonic clocks disagree by a known skew:
    after alignment their events land on one timeline in true order
    (the paxtrace anchor math, applied to the mono column)."""
    skew = 5_000_000_000  # process B's mono clock runs 5 s behind
    wall0 = 1_700_000_000_000_000_000
    a = {"anchor": {"mono_ns": 100, "wall_ns": wall0},
         "events": [[50, wall0 - 50, W.EV_ELECTION, 0, 0, 0, 0, 0]]}
    b = {"anchor": {"mono_ns": 100 - skew, "wall_ns": wall0},
         "events": [[75 - skew, wall0 - 25, W.EV_CHAOS_INSTALL, 1, 1,
                     0, 0, 0]]}
    rows = W.align_event_collections([a, b])
    assert rows.shape[0] == 2
    # B's event (25 ns before the anchor) lands AFTER A's (50 ns
    # before), in A's monotonic domain
    assert rows[0, W.EV_KIND] == W.EV_ELECTION
    assert rows[1, W.EV_KIND] == W.EV_CHAOS_INSTALL
    assert rows[1, W.EV_MONO] - rows[0, W.EV_MONO] == 25


# ------------------------------------------------------- schema v6


def test_schema_v6_watch_pid_pinned_both_directions():
    j = W.EventJournal(capacity=16)
    j.record(W.EV_LEADER_CHANGE, subject=1, aux=0)
    j.record(W.EV_ALARM, subject=0, value=900, aux=W.DET_STALL)
    events = W.event_chrome_events(j.snapshot(), tid=0)
    assert events and all(e["pid"] == WATCH_PID for e in events)
    assert events[1]["name"] == "alarm:frontier_stall"
    assert all(e["ph"] == "i" and e["cat"] == "paxwatch"
               for e in events)
    # merged with recorder ticks: valid
    rec = FlightRecorder(8)
    rec.record(10_000, 0, 1, 4, 4, 10, 0, 1, 2, 3, 0, 4, 5, 6, 9_000)
    merged = chrome_trace(rec.to_events(pid=0) + events)
    assert validate_chrome_trace(merged) == []
    # a paxwatch event off the reserved pid fails
    bad = chrome_trace([dict(events[0], pid=3)])
    assert any("paxwatch" in e for e in validate_chrome_trace(bad))
    # a non-watch event squatting on the reserved pid fails
    squat = chrome_trace([{"name": "tick", "cat": "tick", "ph": "X",
                           "ts": 1.0, "dur": 1.0, "pid": WATCH_PID,
                           "tid": 0}])
    assert any(str(WATCH_PID) in e for e in validate_chrome_trace(squat))


# ------------------------------------------------- synthetic series


def _resp(tip_by_rid: dict, leader=0, proposals=0, elections=None,
          executed=None, hist=None):
    """A master stats fan-out response for one sample instant."""
    replicas = []
    for rid, fr in tip_by_rid.items():
        cnt = {"proposals": proposals if rid == leader else 0,
               "elections": (elections or {}).get(rid, 0)}
        mx = {"counters": cnt, "gauges": {}}
        if hist is not None:
            mx["histograms"] = {"tick_wall_ms": hist[rid]}
        replicas.append({
            "id": rid, "ok": True, "frontier": fr,
            "executed": (executed or {}).get(rid, fr),
            "metrics": mx})
    return {"ok": True, "leader": leader, "replicas": replicas}


def _series(resps, dt=0.25, slo_ms=None):
    return [W.flatten_cluster_stats(r, slo_ms=slo_ms, t_wall=i * dt)
            for i, r in enumerate(resps)]


def test_stall_fires_and_boundary():
    """Flat tip + in-flight load for >= stall_s fires; the same series
    one sample short of the window, or with the tip moving just past
    the slack, does not."""
    # leader committed up to 100 then froze; 64 admitted-but-uncommitted
    frozen = _resp({0: 100, 1: 100, 2: 100}, proposals=165)
    samples = _series([frozen] * 6)  # 1.25 s of flatness
    a = W.stall_alarm(samples, stall_s=1.0, slack_slots=8)
    assert a is not None and a["detector"] == "frontier_stall"
    assert a["evidence"]["in_flight"] == 64
    # window one sample short of stall_s: no fire
    assert W.stall_alarm(samples[:4], stall_s=1.0) is None
    # tip crawling exactly at the slack boundary: slack+1 advance over
    # the window = not a stall
    crawl = [_resp({0: 100 + 3 * i, 1: 100 + 3 * i, 2: 100 + 3 * i},
                   proposals=200) for i in range(6)]
    assert W.stall_alarm(_series(crawl), stall_s=1.0,
                         slack_slots=8) is None
    # no in-flight load and no arrivals: a quiet cluster is not stalled
    quiet = _resp({0: 100, 1: 100, 2: 100}, proposals=90)
    assert W.stall_alarm(_series([quiet] * 6), stall_s=1.0) is None


def test_stall_attribution_minority_vs_majority():
    # one laggard follower (minority): blame it
    lag1 = _resp({0: 500, 1: 500, 2: 380}, proposals=600)
    a = W.stall_alarm(_series([lag1] * 6), stall_s=1.0)
    assert a["subject"] == 2 and "lags the tip" in a["evidence"]["why"]
    # both followers starved together (majority): blame the leader —
    # the isolated-leader signature (each follower one in-flight batch
    # behind when the piggyback stream stopped)
    maj = _resp({0: 500, 1: 436, 2: 436}, proposals=600)
    a = W.stall_alarm(_series([maj] * 6), stall_s=1.0)
    assert a["subject"] == 0
    assert "leader is cut off" in a["evidence"]["why"]
    # every frontier flat and LEVEL: still the leader
    lvl = _resp({0: 500, 1: 500, 2: 500}, proposals=600)
    a = W.stall_alarm(_series([lvl] * 6), stall_s=1.0)
    assert a["subject"] == 0


def test_churn_budget_boundary():
    def at(n_elections):
        resps = [_resp({0: 10 * i, 1: 10 * i, 2: 10 * i},
                       elections={1: 0}) for i in range(9)]
        # elections ramp linearly to n_elections on replica 1
        for i, r in enumerate(resps):
            r["replicas"][1]["metrics"]["counters"]["elections"] = \
                round(n_elections * i / 8)
        return _series(resps, dt=0.5)  # 4 s window

    assert W.churn_alarm(at(3), window_s=3.0, budget=3) is None
    a = W.churn_alarm(at(6), window_s=3.0, budget=3)
    assert a is not None and a["subject"] == 1
    assert a["evidence"]["elections"] > 3


def test_backlog_growth_slope():
    # backlog on replica 2 grows 500 slots/s; frontiers keep moving so
    # the stall detector stays quiet but execution is drowning
    resps = [_resp({0: 1000 + 200 * i, 1: 1000 + 200 * i,
                    2: 1000 + 200 * i},
                   executed={2: 1000 + 75 * i}) for i in range(9)]
    s = _series(resps, dt=0.5)
    a = W.backlog_alarm(s, window_s=3.0, slope_per_s=200.0,
                        min_backlog=64)
    assert a is not None and a["subject"] == 2
    assert a["evidence"]["slope_per_s"] > 200
    # flat backlog: quiet
    flat = [_resp({0: 1000, 1: 1000, 2: 1000},
                  executed={2: 900}) for _ in range(9)]
    assert W.backlog_alarm(_series(flat, dt=0.5), window_s=3.0,
                           slope_per_s=200.0) is None


def test_burn_rate_math():
    """bad/total over the window divided by the budget: 200 of 1000
    ticks over the SLO against a 1% budget = burn 20x (alarm at 10x);
    5 of 1000 = 0.5x (quiet). The histogram derivation counts a
    bucket as bad only when its LOWER edge clears the SLO."""
    bounds = [1.0, 10.0, 50.0, 100.0]

    def hist(total, bad):
        # counts: [<=1, (1,10], (10,50], (50,100], >100]; SLO 50 ->
        # bad buckets are (50,100] and >100
        return {"bounds": bounds,
                "counts": [0, total - bad, 0, bad, 0],
                "count": total}

    def series(bad_per_k):
        resps = []
        for i in range(9):
            h = {rid: hist(1000 * i // 8, bad_per_k * i // 8)
                 for rid in range(3)}
            resps.append(_resp({0: 10 * i, 1: 10 * i, 2: 10 * i},
                               hist=h))
        return _series(resps, dt=0.5, slo_ms=50.0)

    a = W.burn_alarm(series(200), window_s=3.0, slo_ms=50.0,
                     budget_frac=0.01, burn_x=10.0, min_ticks=50)
    assert a is not None
    assert abs(a["evidence"]["bad_frac"] - 0.2) < 0.02
    assert a["evidence"]["burn"] >= 15
    assert W.burn_alarm(series(5), window_s=3.0, slo_ms=50.0,
                        budget_frac=0.01, burn_x=10.0,
                        min_ticks=50) is None
    # under min_ticks: no verdict from a starved histogram
    assert W.burn_alarm(series(200)[:2], window_s=0.4, slo_ms=50.0,
                        min_ticks=5000) is None


def test_hist_bad_lower_edge_is_conservative():
    h = {"bounds": [1.0, 10.0, 50.0], "counts": [1, 2, 4, 8],
         "count": 15}
    r = _resp({0: 5}, hist={0: h})
    s = W.flatten_cluster_stats(r, slo_ms=10.0)
    # bad = buckets with lower edge >= 10: (10,50] (4) + >50 (8)
    assert s["hist_bad"] == 12 and s["hist_total"] == 15


# ------------------------------------------------------ watcher edge


def test_health_watcher_raise_and_clear_journaled():
    frozen = _resp({0: 100, 1: 100, 2: 100}, proposals=165)
    moving = [_resp({0: 100 + 50 * i, 1: 100 + 50 * i, 2: 100 + 50 * i},
                    proposals=165) for i in range(20)]
    w = W.HealthWatcher(slo=W.SLO(stall_s=1.0))
    t = 0.0
    for _ in range(6):  # freeze long enough to raise
        w.poll_once(frozen, t_wall=t)
        t += 0.25
    assert len(w.alarms) == 1
    assert w.alarms[0]["detector"] == "frontier_stall"
    assert w.alarms[0]["t_cleared"] is None
    for r in moving:  # heal: tip advances, alarm clears
        w.poll_once(r, t_wall=t)
        t += 0.25
    assert w.alarms[0]["t_cleared"] is not None
    # raise + clear journaled with the detector id in aux
    rows = w.journal.snapshot()
    kinds = rows[:, W.EV_KIND].tolist()
    assert kinds == [W.EV_ALARM, W.EV_ALARM_CLEAR]
    assert all(int(r[W.EV_AUX]) == W.DET_STALL for r in rows)
    s = w.summary()
    assert s["alarm_counts"] == {"frontier_stall": 1}
    assert s["events"] == {"alarm": 1, "alarm_clear": 1}


# -------------------------------------------------------- retention


def test_health_series_week_long_run_stays_bounded(tmp_path):
    """Simulated long run: ~2 days of 1 Hz samples (compressed into
    one loop) against a 256 KB bound — the file must stay near the
    bound via compaction, the coarse tiers must cover the whole span,
    and the percentiles must be exact over a known bucket."""
    path = tmp_path / "watch.jsonl"
    hs = W.HealthSeries(str(path), raw_keep_s=60.0, coarse_s=30.0,
                        max_bytes=256 << 10, max_coarse=64)
    n = 180_000  # 50 h at 1 Hz
    for i in range(n):
        hs.append({"t": float(i), "tip": i * 3, "in_flight": i % 7,
                   "replicas": {"0": {"backlog": i % 11}}})
    hs.close()
    size = path.stat().st_size
    assert size < (256 << 10) * 1.25, size  # bounded (one append tail)
    assert hs.appended == n
    # raw recent retained at full resolution
    assert len(hs._raw) >= 59
    assert hs._raw[-1][0] == float(n - 1)
    # coarse history covers (almost) the whole span, bucket count
    # bounded by the pairwise merge
    assert len(hs.coarse) <= 64
    assert hs.coarse[0]["t0"] == 0.0
    assert hs.summary()["span_s"] >= n - 120
    # reload after an explicit compaction: the rewritten file
    # round-trips exactly (between compactions the append-only log
    # legitimately retains already-folded raw lines)
    hs.compact()
    hs.close()
    doc = W.load_series(str(path))
    assert len(doc["raw"]) == len(hs._raw)
    assert len(doc["coarse"]) == len(hs.coarse)
    assert doc["raw"][-1]["tip"] == (n - 1) * 3


def test_health_series_coarse_percentiles_exact(tmp_path):
    hs = W.HealthSeries(str(tmp_path / "s.jsonl"), raw_keep_s=10.0,
                        coarse_s=100.0)
    vals = list(range(100))
    for i in vals:
        hs.append({"t": float(i), "x": float(i)})
    hs.append({"t": 1000.0, "x": 0.0})  # expire the first bucket
    hs.close()
    assert hs.coarse, "no coarse bucket closed"
    st = hs.coarse[0]["stats"]["x"]
    arr = sorted(vals[:st["n"]])
    assert st["max"] == arr[-1]
    assert st["p50"] == arr[min(int(0.50 * len(arr)), len(arr) - 1)]
    assert st["p99"] == arr[min(int(0.99 * len(arr)), len(arr) - 1)]


# --------------------------------------------- paxtop stable schema


def _load_paxtop():
    spec = importlib.util.spec_from_file_location(
        "paxtop_mod", REPO / "tools" / "paxtop.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_paxtop_json_schema_keys_pinned():
    """The --once --json document is a STABLE schema: response /
    derived / events / health at the top, the derived-row and
    event-row key sets exactly as published (OBSERVABILITY.md).
    Additions are fine; this test catches removals/renames."""
    paxtop = _load_paxtop()
    j = W.EventJournal(capacity=16)
    j.record(W.EV_CHAOS_INSTALL, subject=0, value=7)
    j.record(W.EV_ALARM, subject=0, value=900, aux=W.DET_STALL)
    resp = {"ok": True, "leader": 0, "alive": [True], "n": 1,
            "replicas": [{
                "id": 0, "ok": True, "protocol": "minpaxos",
                "frontier": 42, "executed": 40,
                "metrics": {"counters": {"dispatches": 10,
                                         "full_steps": 10},
                            "gauges": {"committed": 43},
                            "histograms": {"tick_wall_ms":
                                           {"p50": 0.5, "p99": 2.0}}},
                "scalars": {"executed": 40}}]}
    ev_resp = {"ok": True, "replicas": [
        {"id": 0, "ok": True, "journal": j.collect()}]}
    payload = paxtop.snapshot_payload(resp, ev_resp, None, 0.0,
                                      now_wall_ns=time.time_ns())
    assert set(paxtop.JSON_PAYLOAD_KEYS) == set(payload)
    row = payload["derived"][0]
    assert set(paxtop.DERIVED_ROW_KEYS) == set(row), \
        sorted(set(paxtop.DERIVED_ROW_KEYS) ^ set(row))
    assert len(payload["events"]) == 2
    for ev in payload["events"]:
        assert set(paxtop.EVENT_ROW_KEYS) == set(ev), sorted(ev)
    # HEALTH: the newest WARN+ event (the alarm) is the stanza
    assert payload["health"]["0"]["kind"] == "alarm:frontier_stall"
    assert row["health"]["severity"] == "alert"
    # serializes (the shipped tool prints it as one JSON line)
    json.dumps(payload)


def test_paxtop_health_ignores_info_events():
    paxtop = _load_paxtop()
    j = W.EventJournal(capacity=16)
    j.record(W.EV_ELECTION, subject=0)  # info: not a health stanza
    ev_resp = {"ok": True, "replicas": [
        {"id": 0, "ok": True, "journal": j.collect()}]}
    events = paxtop._derive_events(ev_resp, time.time_ns())
    assert paxtop._derive_health(events) == {}


def test_paxtop_health_survives_info_event_storm():
    """An active alert must not vanish from HEALTH just because newer
    info events pushed it past the 64-row display tail."""
    paxtop = _load_paxtop()
    j = W.EventJournal(capacity=256)
    j.record(W.EV_STORE_CORRUPT, subject=0, value=3)  # the alert
    for q in range(100):  # churn wave of info events after it
        j.record(W.EV_PEER_UP, subject=q % 3)
    ev_resp = {"ok": True, "replicas": [
        {"id": 0, "ok": True, "journal": j.collect()}]}
    resp = {"ok": True, "leader": 0, "replicas": [
        {"id": 0, "ok": True, "frontier": 1, "executed": 1,
         "metrics": {"counters": {}, "gauges": {}}}]}
    payload = paxtop.snapshot_payload(resp, ev_resp, None, 0.0,
                                      now_wall_ns=time.time_ns())
    assert len(payload["events"]) == 64  # pane tail stays bounded
    assert payload["health"]["0"]["kind"] == "store_corrupt"


def test_burn_alarm_slo_above_histogram_range():
    """An SLO declared above the histogram's top edge: over-SLO ticks
    can only land in the overflow bucket, which must count BAD — the
    burn detector must not go blind exactly there."""
    h = {"bounds": [1.0, 10.0, 50.0], "counts": [0, 800, 0, 200],
         "count": 1000}
    s = W.flatten_cluster_stats(_resp({0: 5}, hist={0: h}),
                                slo_ms=6000.0)
    assert s["hist_bad"] == 200 and s["hist_total"] == 1000


# --------------------------------------------------- campaign math


def test_stall_verdict_window_join():
    """_stall_verdict joins the watcher's wall-clock alarms against
    the fired chaos events' wall marks (the CHAOS.json ground-truth
    timeline satellite)."""
    from minpaxos_tpu.chaos.campaign import _stall_verdict

    class FakeWatcher:
        alarms = [{"detector": "frontier_stall", "subject": 0,
                   "t_raised": 105.0, "t_cleared": 108.2,
                   "evidence": {"why": "x"}}]

    marks = [(5.0, 104.0, "install"), (9.0, 108.0, "clear")]
    v = _stall_verdict(FakeWatcher(), marks, expected_subject=0)
    assert v["fired_in_window"] and v["attributed"] and v["cleared"]
    # raised before the install: not the injected fault's detection
    FakeWatcher.alarms = [dict(FakeWatcher.alarms[0], t_raised=90.0)]
    v = _stall_verdict(FakeWatcher(), marks, expected_subject=0)
    assert not v["fired_in_window"]
    # wrong subject: detected but misattributed
    FakeWatcher.alarms = [dict(FakeWatcher.alarms[0], t_raised=105.0,
                               subject=2)]
    v = _stall_verdict(FakeWatcher(), marks, expected_subject=0)
    assert v["fired_in_window"] and not v["attributed"]
