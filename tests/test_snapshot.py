"""Durable snapshots, log truncation, and recovery (PR 20 paxdur).

The stable store's snapshot contract (runtime/stable.py
``take_snapshot`` / ``_replay``) has four load-bearing claims these
tests pin:

* **Equivalence** — replaying the newest snapshot + the redo suffix
  above it reconstructs BYTE-IDENTICAL applied state to replaying the
  full log it truncated (the ISSUE's pinned property);
* **Bounded disk** — the second snapshot onward actually shrinks the
  file, and what was truncated is exactly the redo records at/below
  the previous snapshot's frontier;
* **Fallback ladder** — a corrupt newest snapshot (bit rot, torn
  segment-swap tail) falls back to the PREVIOUS retained snapshot plus
  a longer replay, never to garbage and never to data loss that peers
  cannot re-send;
* **Kill-point safety** — a crash at ANY byte boundary during the
  post-swap append stream leaves a file that reopens without error
  into a self-consistent prefix, and converges back once peers re-send
  the lost records.

The replica-level tests drive threadless servers (the
tests/test_pipeline.py harness pattern: the test owns drain/tick, so
runs are deterministic) through the same trace with and without
snapshots, and through the SNAP_META/SNAP_ROWS wire install path.
"""

from __future__ import annotations

import os
import struct

import numpy as np
import pytest

from minpaxos_tpu.runtime.stable import (
    MAGIC,
    MAGIC_V1,
    REC_FRONTIER,
    REC_SLOTS,
    REC_SNAPSHOT,
    SNAP_DT,
    StableStore,
)

# ----------------------------------------------------- store helpers


def _append(s: StableStore, lo: int, hi: int, key_mod: int = 8) -> None:
    """Committed PUT slots for inst in [lo, hi) — key = inst % key_mod,
    val = inst * 100 + 7, so the applied state is derivable from the
    slot range alone."""
    n = hi - lo
    inst = np.arange(lo, hi)
    s.append_slots(inst, np.full(n, 16), np.full(n, 4), np.ones(n),
                   inst % key_mod, inst * 100 + 7, inst, np.zeros(n))


def _applied(pairs: np.ndarray, rec: np.ndarray,
             upto: int) -> np.ndarray:
    """Reference replay: snapshot pairs + redo records in inst order,
    PUTs only, up to ``upto`` — returned as key-sorted SNAP_DT rows so
    equality is a tobytes() comparison."""
    kv = {int(k): int(v) for k, v in zip(pairs["key"], pairs["val"])}
    rec = rec[np.argsort(rec["inst"], kind="stable")]
    for r in rec:
        if int(r["inst"]) > upto:
            break
        if int(r["op"]) == 1 and int(r["client_id"]) >= 0:
            kv[int(r["key"])] = int(r["val"])
    out = np.zeros(len(kv), SNAP_DT)
    for i, k in enumerate(sorted(kv)):
        out["key"][i], out["val"][i] = k, kv[k]
    return out


def _store_applied(s: StableStore) -> np.ndarray:
    base = s.base
    pairs = s.snapshot_pairs if base >= 0 else np.zeros(0, SNAP_DT)
    rec = s.read_range(base + 1, s.committed_prefix())
    return _applied(pairs, rec, s.committed_prefix())


def _records(path) -> list[tuple[int, int, int, int]]:
    """Parse the v2 file framing: (offset, rtype, payload_len,
    payload_offset) per record — so tests can target a specific record
    for corruption without hardcoding byte offsets."""
    data = open(path, "rb").read()
    assert data[:8] == MAGIC
    out, pos = [], 8
    while pos + 5 <= len(data):
        rtype, plen = struct.unpack_from("<BI", data, pos)
        body = pos + 5 + 4
        if body + plen > len(data):
            break
        out.append((pos, rtype, plen, body))
        pos = body + plen
    return out


# ------------------------------------------- equivalence + bounding


def test_snapshot_plus_suffix_replay_byte_equals_full_log(tmp_path):
    """The pinned property: a store that snapshotted (twice — so the
    log was actually truncated) replays to byte-identical applied
    state and committed prefix as a full-log twin fed the same
    appends."""
    full, snap = tmp_path / "full", tmp_path / "snap"
    a = StableStore(str(full), sync=True)
    b = StableStore(str(snap), sync=True)
    for lo in (0, 40, 80):
        _append(a, lo, lo + 40)
        _append(b, lo, lo + 40)
        a.append_frontier(lo + 39)
        b.append_frontier(lo + 39)
        st = _store_applied(b)
        keys, vals = st["key"], st["val"]
        assert b.take_snapshot(keys, vals, lo + 39, wall_ns=1) != -1
    assert b.snapshots_taken == 3 and b.truncated_bytes > 0
    a.close()
    b.close()
    # disk is bounded: the snapshotted file dropped the redo records
    # at/below the PREVIOUS snapshot's frontier
    assert os.path.getsize(snap) < os.path.getsize(full)
    ra, rb = StableStore(str(full)), StableStore(str(snap))
    assert ra.base == -1 and rb.base == 119  # newest retained snapshot
    assert rb.snap_frontier == 119  # taken at the final frontier
    assert ra.committed_prefix() == rb.committed_prefix() == 119
    assert _store_applied(ra).tobytes() == _store_applied(rb).tobytes()
    # the suffix above the replay base is identical record-for-record
    np.testing.assert_array_equal(ra.read_range(80, 119),
                                  rb.read_range(80, 119))
    ra.close()
    rb.close()


def test_first_snapshot_truncates_nothing_second_truncates(tmp_path):
    """Two snapshots are retained for the fallback ladder, so the
    first one cannot free disk; the second frees exactly the records
    at/below the first's frontier."""
    path = tmp_path / "store"
    s = StableStore(str(path), sync=True)
    _append(s, 0, 64)
    s.append_frontier(63)
    st = _store_applied(s)
    s.take_snapshot(st["key"], st["val"], 63, wall_ns=1)
    assert s.truncated_bytes == 0  # everything still retained
    _append(s, 64, 128)
    s.append_frontier(127)
    st = _store_applied(s)
    freed = s.take_snapshot(st["key"], st["val"], 127, wall_ns=2)
    assert freed > 0 and s.truncated_bytes == freed
    # records at/below the previous snapshot's frontier are gone from
    # disk but the in-RAM mirror still serves them (live catch-up)
    assert len(s.read_range(0, 63)) == 64
    s.close()
    r = StableStore(str(path))
    assert len(r.read_range(0, 63)) == 0
    assert len(r.read_range(64, 127)) == 64
    assert r.committed_prefix() == 127
    r.close()


# --------------------------------------------------- fallback ladder


def test_bitflipped_newest_snapshot_falls_back_to_previous(tmp_path):
    """A flipped byte in the newest snapshot's payload fails its CRC;
    replay must land on the PREVIOUS snapshot with the (longer) redo
    suffix — same applied state, one corrupt record counted."""
    path = tmp_path / "store"
    s = StableStore(str(path), sync=True)
    for lo in (0, 32, 64):
        _append(s, lo, lo + 32)
        s.append_frontier(lo + 31)
        st = _store_applied(s)
        s.take_snapshot(st["key"], st["val"], lo + 31, wall_ns=1)
    want = _store_applied(s).tobytes()
    s.close()
    snaps = [r for r in _records(path) if r[1] == REC_SNAPSHOT]
    assert len(snaps) == 2  # two retained: frontier 63 and 95
    raw = bytearray(path.read_bytes())
    raw[snaps[-1][3] + 20] ^= 0x01  # newest snapshot, inside a pair
    path.write_bytes(bytes(raw))
    r = StableStore(str(path))
    assert r.corrupt_records == 1
    assert r.snap_frontier == 63 and r.base == 63  # the previous one
    # the redo suffix (63, 95] survived the fallback: prefix + state
    # fully recover without any peer help
    assert r.committed_prefix() == 95
    assert _store_applied(r).tobytes() == want
    r.close()


def test_torn_snapshot_tail_recovers_previous_and_heals(tmp_path):
    """Truncating mid-newest-snapshot (a tear across the segment-swap
    tail) must reopen on the previous snapshot; the lost suffix then
    converges back through ordinary re-appends (peer re-sends)."""
    path = tmp_path / "store"
    s = StableStore(str(path), sync=True)
    for lo in (0, 32):
        _append(s, lo, lo + 32)
        s.append_frontier(lo + 31)
        st = _store_applied(s)
        s.take_snapshot(st["key"], st["val"], lo + 31, wall_ns=1)
    want = _store_applied(s).tobytes()
    s.close()
    snaps = [r for r in _records(path) if r[1] == REC_SNAPSHOT]
    with open(path, "r+b") as f:  # cut INTO the newest snapshot record
        f.truncate(snaps[-1][3] + snaps[-1][2] // 2)
    r = StableStore(str(path))
    assert r.snap_frontier == 31 and r.base == 31
    assert r.committed_prefix() == 31  # the suffix was torn off too
    _append(r, 32, 64)  # peers re-send the lost records
    r.append_frontier(63)
    r.flush()
    assert r.committed_prefix() == 63
    assert _store_applied(r).tobytes() == want
    r.close()
    r2 = StableStore(str(path))  # and the healed file replays clean
    assert r2.committed_prefix() == 63
    assert _store_applied(r2).tobytes() == want
    r2.close()


def test_stale_tmp_from_died_swap_is_discarded(tmp_path):
    """A crash between the segment fsync and the os.replace leaves a
    complete-looking .tmp; reopen must discard it — the original file
    is still the authoritative one."""
    path = tmp_path / "store"
    s = StableStore(str(path), sync=True)
    _append(s, 0, 16)
    s.append_frontier(15)
    s.flush()
    want = _store_applied(s).tobytes()
    s.close()
    (tmp_path / "store.tmp").write_bytes(MAGIC + b"\x03\xff\xff\xff\xff")
    r = StableStore(str(path))
    assert not os.path.exists(tmp_path / "store.tmp")
    assert r.committed_prefix() == 15
    assert _store_applied(r).tobytes() == want
    r.close()


def test_truncation_kill_point_sweep(tmp_path):
    """Crash-at-every-boundary: for every truncation point in a
    snapshotted-then-appended file, reopen must (a) not raise, (b)
    recover a self-consistent prefix whose every record matches the
    original, and (c) converge back to the full state once the
    original appends are replayed on top."""
    path = tmp_path / "store"
    s = StableStore(str(path), sync=True)
    for lo in (0, 16):
        _append(s, lo, lo + 16)
        s.append_frontier(lo + 15)
        st = _store_applied(s)
        s.take_snapshot(st["key"], st["val"], lo + 15, wall_ns=1)
    _append(s, 32, 48)  # post-swap append stream (the torn region)
    s.append_frontier(47)
    s.flush()
    want = _store_applied(s).tobytes()
    full = s.read_range(0, 47)
    s.close()
    size = os.path.getsize(path)
    work = tmp_path / "cut"
    data = open(path, "rb").read()
    for cut in list(range(len(MAGIC), size, 7)) + [size - 1]:
        work.write_bytes(data[:cut])
        r = StableStore(str(work))  # must never raise
        # recovered records are a subset byte-equal to the originals
        got = r.read_range(0, 47)
        by_inst = {int(x["inst"]): x for x in full}
        for x in got:
            assert x == by_inst[int(x["inst"])], cut
        assert r.committed_prefix() <= 47
        assert r.snap_frontier in (-1, 15, 31), cut
        # convergence: replay every original record + frontier on top
        _append(r, 0, 48)
        r.append_frontier(47)
        assert r.committed_prefix() == 47, cut
        assert _store_applied(r).tobytes() == want, cut
        r.close()


# ------------------------------------------------------ v1/v2 compat


def test_v1_store_refuses_snapshot_and_stays_v1(tmp_path):
    """Pre-CRC (MPXL0001) files have no integrity framing to protect a
    snapshot record, so take_snapshot must refuse (-1) and leave the
    file byte-identical; replay and v1 appends keep working."""
    path = tmp_path / "store"
    from minpaxos_tpu.runtime.stable import SLOT_DT
    rec = np.zeros(4, SLOT_DT)
    rec["inst"] = np.arange(4)
    rec["ballot"], rec["status"], rec["op"] = 16, 4, 1
    rec["key"], rec["val"] = np.arange(4) % 8, np.arange(4) * 100 + 7
    payload = rec.tobytes()
    with open(path, "wb") as f:
        f.write(MAGIC_V1)
        f.write(struct.pack("<BI", REC_SLOTS, len(payload)) + payload)
        f.write(struct.pack("<BI", REC_FRONTIER, 4) + struct.pack("<i", 3))
    s = StableStore(str(path))
    assert not s.crc_framing and s.committed_prefix() == 3
    before = open(path, "rb").read()
    st = _store_applied(s)
    assert s.take_snapshot(st["key"], st["val"], 3, wall_ns=1) == -1
    assert s.snapshots_taken == 0
    assert open(path, "rb").read() == before
    _append(s, 4, 8)
    s.append_frontier(7)
    s.close()
    r = StableStore(str(path))  # still v1, still consistent
    assert not r.crc_framing and r.committed_prefix() == 7
    r.close()


# ------------------------------------------- replica-level recovery

jax = pytest.importorskip("jax")

from minpaxos_tpu.models.minpaxos import MinPaxosConfig  # noqa: E402
from minpaxos_tpu.ops.kvstore import LIVE  # noqa: E402
from minpaxos_tpu.ops.packed import join_i64  # noqa: E402
from minpaxos_tpu.runtime.replica import (  # noqa: E402
    CONTROL,
    ReplicaServer,
    RuntimeFlags,
)
from minpaxos_tpu.runtime.transport import FROM_CLIENT, FROM_PEER  # noqa: E402
from minpaxos_tpu.wire.messages import MsgKind, Op, make_batch  # noqa: E402

# same shapes as tests/test_pipeline.py, so the jitted step's compile
# cache is shared across the files within one pytest process
CFG = MinPaxosConfig(n_replicas=1, window=128, inbox=16, exec_batch=8,
                     kv_pow2=8, catchup_rows=8, recovery_rows=8,
                     gossip_ticks=1)
CID = 7


def _mk_server(tmp_path, name: str, **over) -> ReplicaServer:
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    flags = RuntimeFlags(pipeline=False, durable=True, store_dir=str(d),
                         **over)
    return ReplicaServer(0, [("127.0.0.1", 7077)], CFG, flags)


def _elect(srv: ReplicaServer) -> None:
    srv.queue.put((CONTROL, 0, "be_the_leader", None))
    for _ in range(20):
        if srv._drain(0.001):
            srv._become_leader()
        srv._device_tick(srv.inbox)
        if srv.snapshot["prepared"]:
            return
    raise AssertionError(f"never prepared: {srv.snapshot}")


def _trace(n_frames: int, seed: int) -> list[np.ndarray]:
    rng = np.random.default_rng(seed)
    rows = CFG.inbox
    return [make_batch(
        MsgKind.PROPOSE,
        cmd_id=(1000 + f * rows + np.arange(rows)).astype(np.int32),
        op=np.full(rows, int(Op.PUT), np.uint8),
        key=rng.integers(0, 40, rows).astype(np.int64),
        val=rng.integers(1, 1 << 20, rows).astype(np.int64),
        timestamp=0) for f in range(n_frames)]


def _feed(srv: ReplicaServer, frames: list[np.ndarray],
          extra_ticks: int = 12) -> None:
    for frame in frames:
        srv.queue.put((FROM_CLIENT, CID, MsgKind.PROPOSE, frame))
    for _ in range(3 * len(frames) + extra_ticks):
        srv._drain(0.001)
        srv._device_tick(srv.inbox)
    srv._flush_inflight()


def _live_pairs(srv: ReplicaServer) -> np.ndarray:
    """The device KV table's live (key, val) pairs, key-sorted."""
    kv = srv.state.kv
    live = np.asarray(kv.slot) == LIVE
    keys = join_i64(np.asarray(kv.key_hi)[live],
                    np.asarray(kv.key_lo)[live])
    v = np.asarray(kv.val)
    vals = join_i64(v[live, 0], v[live, 1])
    out = np.zeros(len(keys), SNAP_DT)
    order = np.argsort(keys, kind="stable")
    out["key"], out["val"] = keys[order], vals[order]
    return out


def test_replica_recovery_from_snapshot_equals_full_log(tmp_path):
    """End-to-end restart equivalence: a replica that snapshotted (and
    truncated) mid-trace recovers byte-identical applied KV state and
    frontier to a twin that kept its full log — through the real
    _recover_from_store path, not a store-level simulation."""
    trace = _trace(6, seed=23)
    frontiers = {}
    for name, with_snap in (("snap", True), ("full", False)):
        srv = _mk_server(tmp_path, name, snapshots=with_snap)
        try:
            _elect(srv)
            _feed(srv, trace[:3])
            if with_snap:
                for _ in range(2):  # second one actually truncates
                    srv._take_snapshot(int(srv.snapshot["executed"]))
                assert srv.store.snapshots_taken == 2
            _feed(srv, trace[3:])
            frontiers[name] = int(srv.snapshot["frontier"])
        finally:
            srv.store.close()
    assert frontiers["snap"] == frontiers["full"] == 6 * CFG.inbox - 1
    rec = {}
    for name in ("snap", "full"):
        srv = _mk_server(tmp_path, name)
        assert srv.store.recovered
        srv._recover_from_store()
        rec[name] = srv
    try:
        assert rec["snap"].store.base >= 0  # replayed snapshot+suffix
        assert rec["full"].store.base == -1  # replayed the whole log
        # window_base is a slide cursor, not applied state — its
        # replay-time value depends on replay chunking and catches up
        # on the next live ticks, so only its validity is pinned
        for srv in rec.values():
            assert int(srv.state.window_base) <= \
                int(srv.state.committed_upto) + 1
        for field in ("committed_upto", "executed_upto"):
            assert int(getattr(rec["snap"].state, field)) == \
                int(getattr(rec["full"].state, field)), field
        assert _live_pairs(rec["snap"]).tobytes() == \
            _live_pairs(rec["full"]).tobytes()
    finally:
        rec["snap"].store.close()
        rec["full"].store.close()


def test_wire_snapshot_install_on_wiped_replica(tmp_path):
    """The SNAP_META/SNAP_ROWS catch-up path: a replica with no log at
    all installs a donor's snapshot through its real drain loop — KV
    pairs into the device table, cursors to frontier+1, and the
    snapshot into its OWN store so its next restart replays from it."""
    donor = _mk_server(tmp_path, "donor")
    try:
        _elect(donor)
        _feed(donor, _trace(4, seed=31))
        donor._take_snapshot(int(donor.snapshot["executed"]))
        fr = donor.store.snap_frontier
        pairs = donor.store.snapshot_pairs
        assert fr == 4 * CFG.inbox - 1 and len(pairs) > 0
        donor_state = _live_pairs(donor).tobytes()
    finally:
        donor.store.close()

    rx = _mk_server(tmp_path, "wiped")
    try:
        meta = make_batch(MsgKind.SNAP_META, leader_id=1, frontier=fr,
                          count=len(pairs), seq=1)
        rx.queue.put((FROM_PEER, 1, MsgKind.SNAP_META, meta))
        # ship the pairs in two frames to exercise reassembly
        mid = len(pairs) // 2
        for ch in (pairs[:mid], pairs[mid:]):
            rows = make_batch(MsgKind.SNAP_ROWS, frontier=fr,
                              key=np.ascontiguousarray(ch["key"]),
                              val=np.ascontiguousarray(ch["val"]))
            rx.queue.put((FROM_PEER, 1, MsgKind.SNAP_ROWS, rows))
        rx._drain(0.001)
        assert rx.snapshot["frontier"] == fr
        assert int(rx.state.committed_upto) == fr
        assert int(rx.state.window_base) == fr + 1
        assert _live_pairs(rx).tobytes() == donor_state
        # installed into its own store: base moved (wire-install is
        # the one live rebase) and a restart replays from it
        assert rx.store.snap_frontier == fr and rx.store.base == fr
    finally:
        rx.store.close()

    back = _mk_server(tmp_path, "wiped")
    try:
        assert back.store.recovered
        back._recover_from_store()
        assert _live_pairs(back).tobytes() == donor_state
        assert int(back.state.committed_upto) == fr
    finally:
        back.store.close()
