"""On-device workload generator + device-resident measured loop.

Two properties carry the PR-8 acceptance criteria:

* host/device workload equivalence — the jnp Threefry generator and
  the independent NumPy host injector produce BYTE-IDENTICAL proposal
  rows from the same (seed, round) across shards, rounds, and leader
  modes, and the stream is pinned against golden values so it can
  never silently drift (bench runs must stay comparable across
  sessions and jax versions);
* resident/legacy loop equivalence — the device-resident measured
  loop (donated buffers, on-device latency histogram, two-scalar
  readback) commits exactly what the host-in-the-loop legacy path
  commits, lands in an identical state, and its histogram reproduces
  the host-side latency percentiles bit-for-bit, with the drain
  leaving zero uncommitted slots.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.ops.workload import (
    propose_batch,
    propose_batch_host,
    threefry2x32,
    threefry2x32_host,
)
from minpaxos_tpu.parallel.sharded import (
    DONATION,
    LATENCY_BINS,
    ShardedCluster,
    shard_cursors,
    sharded_run_resident,
)

SMALL = MinPaxosConfig(
    n_replicas=3, window=256, inbox=256, exec_batch=64, kv_pow2=10,
    catchup_rows=16, recovery_rows=16)


def batches_equal(a, b) -> bool:
    return all(np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f)))
               for f in a._fields)


# ------------------------------------------------- threefry equivalence


def test_threefry_device_matches_host_reference():
    """Same key/counter -> identical uint32 lanes, elementwise, for a
    spread of keys including wraparound-heavy ones."""
    c0 = np.arange(64, dtype=np.uint32)
    c1 = np.arange(64, dtype=np.uint32) * np.uint32(2654435761)
    for k0, k1 in ((0, 0), (1, 0), (7, 42), (0xFFFFFFFF, 0x12345678)):
        d0, d1 = threefry2x32(jnp.uint32(k0), jnp.uint32(k1),
                              jnp.asarray(c0), jnp.asarray(c1))
        h0, h1 = threefry2x32_host(k0, k1, c0, c1)
        assert np.array_equal(np.asarray(d0), h0)
        assert np.array_equal(np.asarray(d1), h1)


def test_threefry_golden_pin():
    """The stream is pinned: these values were produced by this
    implementation AND verified against jax._src.prng.threefry_2x32
    (key [7, 42], counter 0..7). If this test starts failing, bench
    runs are no longer comparable with recorded artifacts."""
    h0, h1 = threefry2x32_host(7, 42, np.arange(4, dtype=np.uint32),
                               np.arange(4, 8, dtype=np.uint32))
    assert h0.tolist() == [2626804800, 2398813549, 2223630828, 3945575549]
    assert h1.tolist() == [592614780, 124672495, 3815937248, 2652798884]


def test_workload_rows_device_host_identical_across_rounds_shards():
    """The acceptance property: same seed => byte-identical [G, R, M]
    proposal rows, across rounds and shards, for both the
    single-leader and the Mencius every-owner addressing modes."""
    for leader in (0, 2, -1):
        for rnd in (0, 1, 17, 4096):
            dev = propose_batch(5, 4, 32, jnp.int32(20), jnp.int32(leader),
                                jnp.int32(rnd), jnp.int32(99), 1 << 10)
            host = propose_batch_host(5, 4, 32, 20, leader, rnd, 99, 1 << 10)
            assert batches_equal(dev, host), (leader, rnd)


def test_workload_rows_format_and_gating():
    """Row format invariants the protocol step relies on: int32
    columns, rows past ``count`` are dead (kind 0), keys live in
    [0, key_space), only the addressed replica gets live rows, and
    cmd_id encodes (round, row) for exactly-once auditing."""
    g, r, m, count, rnd = 3, 5, 16, 9, 7
    b = propose_batch_host(r, g, m, count, 1, rnd, 0, 1 << 8)
    for f in b._fields:
        assert getattr(b, f).dtype == np.int32, f
    assert (b.kind[:, 1, :count] != 0).all()
    assert (b.kind[:, 1, count:] == 0).all()
    assert (b.kind[:, [0, 2, 3, 4], :] == 0).all()
    assert (b.key_lo >= 0).all() and (b.key_lo < (1 << 8)).all()
    # keys are DISTINCT within a (shard, round): duplicate keys in one
    # exec batch serialize the KV claim loop (the 199 vs 122 ms/round
    # regression this schedule exists to avoid — PERF.md)
    for sh in range(g):
        assert len(np.unique(b.key_lo[sh, 1, :count])) == count
    assert np.array_equal(b.cmd_id[:, 1, :count],
                          np.broadcast_to(rnd * m + np.arange(count),
                                          (g, count)))
    assert np.array_equal(b.client_id[:, 1, :count],
                          np.broadcast_to(np.arange(g)[:, None], (g, count)))


def test_workload_distinct_rounds_distinct_rows():
    """Counter-based: different rounds (and different seeds) give
    different key material — the generator cannot silently replay."""
    a = propose_batch_host(3, 2, 16, 16, 0, 0, 0)
    b = propose_batch_host(3, 2, 16, 16, 0, 1, 0)
    c = propose_batch_host(3, 2, 16, 16, 0, 0, 1)
    assert not np.array_equal(a.key_lo, b.key_lo)
    assert not np.array_equal(a.key_lo, c.key_lo)
    # shards draw distinct streams too
    assert not np.array_equal(a.key_lo[0], a.key_lo[1])


# --------------------------------------- resident loop: exact equivalence


def _run_legacy(sc, dispatches=3, k=6, p=24):
    """The pre-resident measured loop: per-dispatch history readback
    + host latency reconstruction (bench.py BENCH_RESIDENT=0)."""
    from bench import _latency_rounds

    u0, c0 = shard_cursors(sc.cfg, sc.leader, sc.ss)
    U, C = [np.asarray(u0)[None].copy()], [np.asarray(c0)[None].copy()]
    for _ in range(dispatches):
        u, c = sc.run_fused(k, p)
        U.append(u)
        C.append(c)
    for _ in range(6):
        u, c = sc.run_fused(k, 0)
        U.append(u)
        C.append(c)
        if (u[-1] >= c[-1] - 1).all():
            break
    return _latency_rounds(np.concatenate(U), np.concatenate(C), 1.0)


def _run_resident(sc, dispatches=3, k=6, p=24):
    sc.begin_resident()
    for _ in range(dispatches):
        committed, in_flight = sc.run_resident(k, p)
    for _ in range(6):
        committed, in_flight = sc.run_resident(k, 0)
        if in_flight == 0:
            break
    return sc.end_resident(), committed, in_flight


def test_resident_loop_equals_legacy_loop():
    """BENCH_RESIDENT=0 vs =1 acceptance pin, at test scale: identical
    committed results AND identical final cluster state from the same
    seed, with the device histogram reproducing the host-side latency
    sample and percentiles exactly."""
    sc_a = ShardedCluster(SMALL, 2, ext_rows=32, key_space=1 << 8, seed=5)
    sc_a.elect(0)
    p50, p99, n, unc = _run_legacy(sc_a)

    sc_b = ShardedCluster(SMALL, 2, ext_rows=32, key_space=1 << 8, seed=5)
    sc_b.elect(0)
    hist, committed, in_flight = _run_resident(sc_b)

    assert unc == 0 and in_flight == 0  # both drained exactly
    assert committed == sc_a.committed()[0]
    # byte-identical end states: same proposal stream, same rounds
    la, lb = jax.tree_util.tree_leaves(sc_a.ss), jax.tree_util.tree_leaves(
        sc_b.ss)
    for xa, xb in zip(la, lb):
        assert np.array_equal(np.asarray(xa), np.asarray(xb))
    # exact latency sample: reconstruct from the histogram
    assert int(hist.sum()) == n
    sample = np.repeat(np.arange(1, hist.size + 1), hist)
    assert float(np.percentile(sample, 50)) == p50
    assert float(np.percentile(sample, 99)) == p99
    assert hist[-1] == 0  # no overflow at test scale


def test_resident_determinism_pin():
    """Two fresh runs, same seed -> identical committed totals and
    identical latency histograms (the artifact-metrics determinism
    pin); a different seed changes the stream but not the totals."""
    runs = []
    for seed in (3, 3, 4):
        sc = ShardedCluster(SMALL, 2, ext_rows=32, key_space=1 << 8,
                            seed=seed)
        sc.elect(0)
        hist, committed, in_flight = _run_resident(sc)
        assert in_flight == 0
        runs.append((committed, hist.tolist(),
                     np.asarray(sc.ss.states.kv.key_lo).copy()))
    assert runs[0][0] == runs[1][0] == runs[2][0]
    assert runs[0][1] == runs[1][1]
    assert np.array_equal(runs[0][2], runs[1][2])
    # different seed: same protocol progress, different key material
    assert not np.array_equal(runs[0][2], runs[2][2])


def test_resident_latency_histogram_matches_hand_computed():
    """First dispatch from idle: slots proposed in round r commit at
    the propose->accept->ack pipeline depth, and the histogram's total
    equals the committed count exactly (no censoring, no padding).
    (Shape/k chosen to share the equality tests' compiled dispatch —
    tier-1 budget discipline.)"""
    sc = ShardedCluster(SMALL, 2, ext_rows=32, key_space=1 << 8)
    sc.elect(0)
    sc.begin_resident()
    committed, in_flight = sc.run_resident(6, 16)
    for _ in range(4):
        committed, in_flight = sc.run_resident(6, 0)
        if in_flight == 0:
            break
    hist = sc.end_resident()
    assert in_flight == 0
    assert int(hist.sum()) == committed
    lats = np.nonzero(hist)[0] + 1
    # the commit pipeline is 3 message deliveries -> every slot commits
    # in exactly 3 rounds under the lock-step pod composition
    assert lats.tolist() == [3], hist[:8]


def test_resident_histogram_overflow_bin_reports_tail():
    """A latency beyond the bin range lands in the LAST bin (counted,
    never dropped): feed a tiny hist so the 3-round pipeline overflows."""
    sc = ShardedCluster(SMALL, 2, ext_rows=32, key_space=1 << 8)
    sc.elect(0)
    sc.begin_resident(lat_bins=2)
    committed, in_flight = sc.run_resident(6, 16)
    for _ in range(4):
        committed, in_flight = sc.run_resident(6, 0)
        if in_flight == 0:
            break
    hist = sc.end_resident()
    assert int(hist.sum()) == committed
    assert hist[-1] == committed  # all 3-round latencies overflow 2 bins


def test_resident_buffers_are_donated():
    """The donation contract the bench artifact stamps (DONATION):
    round state and both bookkeeping buffers are consumed by the
    dispatch — in-place update, no per-dispatch allocation of the big
    tree. (jax marks donated inputs as deleted.)"""
    assert DONATION["sharded_run_resident"] is True
    sc = ShardedCluster(SMALL, 2, ext_rows=32, key_space=1 << 8)
    sc.elect(0)
    sc.begin_resident()
    old_ballot = sc.ss.states.ballot
    old_inj = sc._inject_round
    old_hist = sc._lat_hist
    sc.run_resident(6, 8)
    assert old_ballot.is_deleted()
    assert old_inj.is_deleted()
    assert old_hist.is_deleted()


def test_resident_hist_default_bins():
    sc = ShardedCluster(SMALL, 1, ext_rows=8, key_space=1 << 8)
    sc.elect(0)
    sc.begin_resident()
    assert sc._lat_hist.shape == (LATENCY_BINS,)
    assert sc.resident_hist().sum() == 0


def test_host_injected_rows_commit_identically():
    """Feeding propose_batch_host's rows from the HOST (sharded_step,
    one round at a time) commits exactly the slots the device
    generator commits inside the fused scan — the generator really is
    the host injector's row format."""
    from minpaxos_tpu.models.cluster import ClusterState  # noqa: F401
    from minpaxos_tpu.parallel.sharded import sharded_step

    g, p, k = 2, 16, 6
    sc_dev = ShardedCluster(SMALL, g, ext_rows=p, key_space=1 << 8, seed=9)
    sc_dev.elect(0)
    sc_dev.run_fused(k, p)

    sc_host = ShardedCluster(SMALL, g, ext_rows=p, key_space=1 << 8, seed=9)
    sc_host.elect(0)
    for t in range(k):
        ext = propose_batch_host(SMALL.n_replicas, g, p, p, 0,
                                 sc_host._seed, 9, 1 << 8)
        ext = jax.tree_util.tree_map(jnp.asarray, ext)
        sc_host._seed += 1
        sc_host.ss, _, _, _ = sharded_step(SMALL, sc_host.ss, ext,
                                           sc_host._step_impl)
    for xa, xb in zip(jax.tree_util.tree_leaves(sc_dev.ss),
                      jax.tree_util.tree_leaves(sc_host.ss)):
        assert np.array_equal(np.asarray(xa), np.asarray(xb))


@pytest.mark.slow
def test_mencius_resident_loop_commits_and_drains():
    """The resident loop is protocol-generic: Mencius (leader -1,
    every owner proposing) commits, drains exactly, and samples
    latencies on device too. (slow: its own protocol compile — the
    tier-1 870 s budget is already tight; run with -m slow.)"""
    cfg = SMALL._replace(inbox=512, catchup_rows=64, noop_delay=8)
    sc = ShardedCluster(cfg, 2, ext_rows=8, protocol="mencius",
                        key_space=1 << 8)
    sc.begin_resident()
    committed, in_flight = sc.run_resident(8, 8)
    for _ in range(6):
        committed, in_flight = sc.run_resident(8, 0)
        if in_flight == 0:
            break
    hist = sc.end_resident()
    assert committed > 0
    assert in_flight == 0
    assert hist.sum() > 0
