"""Flexible-quorum (q1, q2) behavior tests (PR 16).

Flexible Paxos (PAPERS.md 1608.06696): safety needs only that every
phase-1 quorum intersects every phase-2 quorum — q1 + q2 > n for
threshold systems — not that both be majorities. These tests pin the
three contracts the config fields introduce:

* **default identity**: an EXPLICIT (q1, q2) = (majority, majority)
  compiles byte-identically to the 0-sentinel default — verified
  against the very same PR-15 golden digests test_kernel_golden.py
  pins, for all three protocols.
* **threshold semantics**: commits land at exactly q2 live acceptors
  (where a majority config stalls), and elections complete at exactly
  q1 promises (and not below).
* **fast path** (Fast Flexible Paxos, 2008.02671): broadcast client
  proposals commit exactly-once with cross-replica agreement even when
  divergent follower slot assignments force the value-fingerprint
  fallback to the classic path.

Plus the host-side gate: non-intersecting configs must be refused by
construction (verify/quorum.py), with the refutation witness in the
error.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from minpaxos_tpu.models.cluster import Cluster, tree_slice
from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.verify.quorum import validate_config_quorums
from minpaxos_tpu.wire.messages import Op
from tests.test_kernel_golden import _KW, FIXTURE, PROTOCOLS, _drive

# the golden scenario's shape (n=5), with quorums made explicit: at
# n=5 the majority is 3, so (3, 3) must resolve to the exact
# thresholds the 0-sentinel default compiles
_MAJ = _KW["n_replicas"] // 2 + 1


@pytest.mark.parametrize("protocol", PROTOCOLS)
def test_explicit_majority_matches_golden_digests(protocol):
    """(q1, q2) = (majority, majority) spelled out is byte-identical
    to the recorded default: every per-step full-state digest of the
    golden scenario matches the PR-15 fixture unmodified."""
    with open(FIXTURE) as f:
        golden = json.load(f)
    got = _drive(protocol, extra_cfg={"q1": _MAJ, "q2": _MAJ})
    want = golden[protocol]
    assert len(got) == len(want)
    for i, (g, w) in enumerate(zip(got, want)):
        assert g == w, (
            f"{protocol}: explicit (q1, q2) = ({_MAJ}, {_MAJ}) diverged "
            f"from the 0-sentinel default at step {i} — the sentinel "
            f"resolution is no longer an identity")


def _boot5(q1: int = 0, q2: int = 0) -> Cluster:
    c = Cluster(MinPaxosConfig(**dict(_KW, q1=q1, q2=q2)), ext_rows=8)
    c.elect(0)
    c.run(3)
    assert bool(np.asarray(tree_slice(c.cs.states, 0).prepared))
    return c


def _put_batch(c: Cluster, n: int, client: int, to=None):
    c.propose(ops=[Op.PUT] * n, keys=list(range(n)),
              vals=[k * 7 for k in range(n)], cmd_ids=list(range(n)),
              client_id=client, to=to)


def test_commit_at_q2_survives_majority_loss():
    """n=5, (q1, q2) = (4, 2): with three non-leaders dead (2 live <
    majority), a q2-sized vote set still commits — the very acks the
    flexible config removes from the critical path."""
    c = _boot5(q1=4, q2=2)
    for r in (2, 3, 4):
        c.kill(r)
    _put_batch(c, 8, client=1)
    c.run(6)
    assert len(c.replies) == 8
    for i in range(8):
        assert c.replies[(1, i)]["value"] == i * 7
    assert int(np.asarray(tree_slice(c.cs.states, 0).committed_upto)) >= 7


def test_majority_config_stalls_where_q2_commits():
    """Control for the previous test: the SAME scenario under an
    explicit majority config (q2=3) must stall — 2 live replicas
    cannot assemble 3 votes, so nothing commits and nothing replies."""
    c = _boot5(q1=_MAJ, q2=_MAJ)
    for r in (2, 3, 4):
        c.kill(r)
    _put_batch(c, 8, client=1)
    c.run(6)
    assert not c.replies
    assert int(np.asarray(tree_slice(c.cs.states, 0).committed_upto)) < 7


def test_leader_change_requires_q1_promises():
    """n=5, q1=4: an election with only 3 replicas alive must NOT
    complete (3 < q1); after reviving a fourth, the same candidate's
    next Prepare round gathers q1 promises and prepares."""
    cfg = MinPaxosConfig(**dict(_KW, q1=4, q2=2))
    c = Cluster(cfg, ext_rows=8)
    c.kill(3)
    c.kill(4)
    c.elect(1)
    c.run(4)
    st1 = tree_slice(c.cs.states, 1)
    assert not bool(np.asarray(st1.prepared)), (
        "prepared with 3 promises under q1=4 — phase-1 gate is not "
        "taking cfg.quorum1")
    c.revive(3)
    c.elect(1)  # fresh Prepare round reaches the revived replica
    c.run(4)
    st1 = tree_slice(c.cs.states, 1)
    assert bool(np.asarray(st1.prepared))


def test_fast_path_broadcast_commits_exactly_once():
    """n=3 fast path: unicast rows put the leader's slot cursor AHEAD
    of the followers', so the immediately-broadcast batch gets
    divergent follower assignments — their fast-acks fail the leader's
    value-fingerprint check and the classic ACCEPT path must converge
    everything. Contract: every proposal commits exactly once, GETs
    observe the writes, and all replicas agree on the committed log."""
    cfg = MinPaxosConfig(n_replicas=3, window=256, inbox=512,
                         exec_batch=128, kv_pow2=10, fast_path=True)
    c = Cluster(cfg, ext_rows=256)
    c.elect(0)
    c.run(3)
    # unicast advances the leader's crt_inst; the broadcast lands on
    # followers still at the old cursor -> fingerprint mismatch path
    c.propose(ops=[Op.PUT] * 10, keys=list(range(10)),
              vals=[k + 100 for k in range(10)],
              cmd_ids=list(range(10)), client_id=1, to=0)
    c.propose(ops=[Op.PUT] * 10, keys=list(range(10, 20)),
              vals=[k + 100 for k in range(10, 20)],
              cmd_ids=list(range(10, 20)), client_id=1, to=-1)
    c.run(8)
    assert len(c.replies) == 20
    assert not [e for e in c.reply_log if e.get("duplicate")]
    for i in range(20):
        assert c.replies[(1, i)]["value"] == i + 100
    # reads observe every write (broadcast too: the happy 1-RTT shape)
    c.propose(ops=[Op.GET] * 20, keys=list(range(20)), vals=[0] * 20,
              cmd_ids=list(range(20, 40)), client_id=1, to=-1)
    c.run(8)
    for i in range(20):
        rep = c.replies[(1, 20 + i)]
        assert rep["found"] and rep["value"] == i + 100
    # cross-replica agreement on the co-resident committed prefix
    frontiers, bases, logs = [], [], []
    for r in range(3):
        st = tree_slice(c.cs.states, r)
        frontiers.append(int(np.asarray(st.committed_upto)))
        bases.append(int(np.asarray(st.window_base)))
        logs.append((np.asarray(st.op), np.asarray(st.key_lo),
                     np.asarray(st.cmd_id), np.asarray(st.client_id)))
    assert min(frontiers) == max(frontiers) >= 39
    lo, hi = max(bases), min(frontiers) + 1
    assert hi - lo > 0
    for r in range(1, 3):
        for a, b in zip(logs[0], logs[r]):
            np.testing.assert_array_equal(
                a[lo - bases[0]:hi - bases[0]],
                b[lo - bases[r]:hi - bases[r]])


def test_non_intersecting_config_refused():
    """q1 + q2 <= n must be refused at construction with the witness
    pair in the error — before any kernel could compile it."""
    bad = MinPaxosConfig(**dict(_KW, q1=2, q2=2))  # 4 <= 5
    with pytest.raises(ValueError, match="witness"):
        validate_config_quorums(bad)
    with pytest.raises(ValueError, match="non-intersecting"):
        Cluster(bad, ext_rows=8)
    # certified pairs construct fine (no kernel run: just the gate)
    for q1, q2 in ((4, 2), (2, 4), (5, 1), (1, 5)):
        validate_config_quorums(MinPaxosConfig(**dict(_KW, q1=q1, q2=q2)))


def test_fast_path_requires_unanimous_fast_quorum():
    """The kernel's index-tiebreak phase-1 adoption is only safe at
    q_fast = n (models/minpaxos.py field note): any smaller explicit
    fast quorum must be refused even though the GENERAL Fast Flexible
    Paxos condition might hold for it."""
    bad = MinPaxosConfig(**dict(_KW, fast_path=True, q_fast=4))
    with pytest.raises(ValueError, match="q_fast"):
        validate_config_quorums(bad)
    validate_config_quorums(
        MinPaxosConfig(**dict(_KW, fast_path=True)))  # qf defaults to n
