"""paxmc: bounded model checker + static quorum certificates.

Three layers, matching VERIFY.md:

* quorum certificates (verify/quorum.py) — proofs re-derive, refuted
  pairs carry checkable witnesses, the golden ledger re-proves;
* the shared invariant predicates (verify/invariants.py) — each fires
  on a seeded violation and stays quiet on clean artifacts;
* the explorer (verify/mc.py) — a healthy small-bound run drains
  exhaustively with zero violations, a seeded broken-quorum mutant
  yields a minimal counterexample whose replay reproduces a REAL
  invariant failure through the same predicates, and the trace
  serializes losslessly (JSON round-trip + chaos FaultPlan schedule).
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

from minpaxos_tpu.chaos.plan import FaultPlan
from minpaxos_tpu.verify import invariants
from minpaxos_tpu.verify.quorum import (
    Certificate,
    certify_grid,
    certify_threshold,
    majority,
    verify_certificate,
)
from minpaxos_tpu.wire.messages import Op

REPO = Path(__file__).resolve().parents[1]


# ------------------------------------------------ quorum certificates


def test_majority_family_proves_for_every_legal_n():
    for n in range(1, 17):
        cert = certify_threshold(n, majority(n), majority(n))
        assert cert.intersects and cert.witness is None
        assert verify_certificate(cert), (n, cert)


def test_flexible_pair_proves_and_refutes():
    ok = certify_threshold(5, 4, 2)  # |Q1|+|Q2| = 6 > 5
    assert ok.intersects and verify_certificate(ok)
    bad = certify_threshold(4, 2, 2)  # 4 <= 4: the silent killer
    assert not bad.intersects
    a, b = bad.witness
    assert len(a) == 2 and len(b) == 2 and not set(a) & set(b)
    assert verify_certificate(bad)


def test_degenerate_thresholds_refused():
    with pytest.raises(ValueError):
        certify_threshold(3, 0, 2)
    with pytest.raises(ValueError):
        certify_threshold(3, 2, 4)


def test_tampered_certificate_fails_verification():
    bad = certify_threshold(4, 2, 2)
    forged = Certificate("threshold", bad.n, bad.q1, bad.q2, True,
                         "trust me")
    assert not verify_certificate(forged)
    # a refutation whose witness sets overlap is no refutation
    overlap = Certificate("threshold", 4, 2, 2, False, "bogus",
                          witness=((0, 1), (1, 2)))
    assert not verify_certificate(overlap)


def test_grid_certificates():
    rc = certify_grid(3, 4, "row", "col")
    assert rc.intersects and verify_certificate(rc)
    rr = certify_grid(3, 4, "row", "row")
    assert not rr.intersects and verify_certificate(rr)
    a, b = rr.witness
    assert not set(a) & set(b)
    one = certify_grid(1, 4, "row", "row")  # a single row: same set
    assert one.intersects and verify_certificate(one)


def test_quorum_golden_ledger_reproves():
    """Every ledger entry is a certificate, not trust: re-prove all of
    them (the quorum-certificate pass does the same on every lint)."""
    from minpaxos_tpu.analysis.quorum_golden import (
        GOLDEN_GRIDS, GOLDEN_MAX_N, GOLDEN_THRESHOLDS,
        THRESHOLD_FORMULAS)

    for n, pairs in GOLDEN_THRESHOLDS.items():
        for q1, q2 in pairs:
            cert = certify_threshold(n, q1, q2)
            assert cert.intersects and verify_certificate(cert), (n, q1, q2)
    for rows, cols, q1, q2 in GOLDEN_GRIDS:
        cert = certify_grid(rows, cols, q1, q2)
        assert cert.intersects and verify_certificate(cert), (rows, cols)
    for label, f in THRESHOLD_FORMULAS.items():
        for n in range(1, GOLDEN_MAX_N + 1):
            assert (f(n), f(n)) in GOLDEN_THRESHOLDS[n], (label, n)
    # the kernels' own threshold is a certified family member
    assert majority(7) == THRESHOLD_FORMULAS["n // 2 + 1"](7)


# ------------------------------------------- shared invariant suite


def _recs(entries):
    """[(inst, op, key, val, cmd, cli), ...] -> slot records."""
    cols = list(zip(*entries)) if entries else [[]] * 6
    return invariants.make_records(*[np.asarray(c) for c in cols])


def test_slot_agreement_detects_divergence_and_holes():
    report = invariants.CheckReport()
    a = _recs([(0, int(Op.PUT), 7, 70, 0, 1), (1, int(Op.PUT), 8, 80, 1, 1)])
    b = _recs([(0, int(Op.PUT), 7, 71, 0, 1), (1, int(Op.PUT), 8, 80, 1, 1)])
    invariants.check_slot_agreement({0: a, 1: b}, {0: 1, 1: 1}, report)
    assert not report.ok
    assert any("DIVERGENCE" in v and "slot 0" in v and "field val" in v
               for v in report.violations), report.violations
    # a hole below both frontiers is itself a violation
    report = invariants.CheckReport()
    short = _recs([(1, int(Op.PUT), 8, 80, 1, 1)])
    invariants.check_slot_agreement({0: a, 1: short}, {0: 1, 1: 1}, report)
    assert not report.ok and any("present on both" in v
                                 for v in report.violations)


def test_slot_agreement_quiet_on_matching_prefixes():
    report = invariants.CheckReport()
    a = _recs([(0, int(Op.PUT), 7, 70, 0, 1), (1, int(Op.PUT), 8, 80, 1, 1)])
    b = _recs([(0, int(Op.PUT), 7, 70, 0, 1)])
    invariants.check_slot_agreement({0: a, 1: b}, {0: 1, 1: 0}, report)
    assert report.ok and report.compared_slots == 1


def test_validity_flags_invented_and_mismatched_writes():
    ops = np.asarray([int(Op.PUT)])
    keys = np.asarray([7])
    vals = np.asarray([70])
    report = invariants.CheckReport()
    invariants.check_validity(
        _recs([(0, int(Op.PUT), 7, 70, 0, 1)]), ops, keys, vals, report)
    assert report.ok
    report = invariants.CheckReport()
    invariants.check_validity(  # cmd_id 5 never proposed
        _recs([(0, int(Op.PUT), 7, 70, 5, 1)]), ops, keys, vals, report)
    assert any("never proposed" in v for v in report.violations)
    report = invariants.CheckReport()
    invariants.check_validity(  # value differs from the workload's
        _recs([(0, int(Op.PUT), 7, 99, 0, 1)]), ops, keys, vals, report)
    assert any("does not match" in v for v in report.violations)
    report = invariants.CheckReport()
    invariants.check_validity(  # no-op fill is exempt by design
        _recs([(0, int(Op.NONE), 0, 0, 0, -1)]), ops, keys, vals, report)
    assert report.ok


def test_frontier_monotonic_flags_backward():
    report = invariants.CheckReport()
    invariants.check_frontier_monotonic({0: [3, 5, 4]}, report)
    assert any("BACKWARD" in v for v in report.violations)
    report = invariants.CheckReport()
    invariants.check_frontier_monotonic({0: [-1, 0, 0, 7]}, report)
    assert report.ok


class _FakeStore:
    """Duck-typed StableStore: just committed_prefix + read_range."""

    def __init__(self, rec: np.ndarray, prefix: int):
        self._rec, self._prefix = rec, prefix

    def committed_prefix(self) -> int:
        return self._prefix

    def read_range(self, lo: int, hi: int) -> np.ndarray:
        m = (self._rec["inst"] >= lo) & (self._rec["inst"] <= hi)
        return self._rec[m]


def test_check_cluster_runs_validity_on_every_store():
    """Code-review regression: the chaos prover certifies validity
    too — an invented write (cmd_id outside the workload) in ANY
    replica's log fails check_cluster, matching the model checker."""
    ops = np.asarray([int(Op.PUT)])
    keys = np.asarray([7])
    vals = np.asarray([70])
    good = _recs([(0, int(Op.PUT), 7, 70, 0, 1)])
    invented = _recs([(0, int(Op.PUT), 7, 70, 0, 1),
                      (1, int(Op.PUT), 9, 90, 42, 1)])  # cmd 42: never sent
    report = invariants.check_cluster(
        {0: _FakeStore(invented, 1), 1: _FakeStore(good, 0)},
        workload=(ops, keys, vals))
    assert any("never proposed" in v for v in report.violations), \
        report.violations
    clean = invariants.check_cluster(
        {0: _FakeStore(good, 0), 1: _FakeStore(good, 0)},
        workload=(ops, keys, vals))
    assert clean.ok, clean.violations


def test_chaos_check_module_is_the_same_suite():
    """The byte-for-byte contract: chaos.check re-exports the verify
    predicates, it does not fork them."""
    from minpaxos_tpu.chaos import check as chaos_check

    assert chaos_check.check_cluster is invariants.check_cluster
    assert chaos_check.check_linearizable is invariants.check_linearizable
    assert chaos_check.CheckReport is invariants.CheckReport


# --------------------------------------------------- the explorer


def _mc():
    from minpaxos_tpu.verify import mc

    return mc


def test_mutant_config_overrides_majority_without_touching_payload():
    mc = _mc()
    healthy = mc.model_config("minpaxos")
    mutant = mc.model_config("minpaxos", majority_override=1)
    assert healthy.majority == 2 and mutant.majority == 1
    # tuple payloads are EQUAL — which is exactly why the explorer jits
    # via per-instance closures instead of shared static-argnum caches
    assert tuple(healthy) == tuple(mutant)


def test_healthy_tiny_bounds_drain_clean():
    """A small exhaustive run per protocol: drains, zero violations.
    (The full smoke bounds run in tools/mc.py --smoke under tier-1;
    this pins the library API + a real multi-replica commit path.)"""
    mc = _mc()
    b = mc.Bounds(max_depth=4, drops=1, dups=0, internal=1, elections=0,
                  n_cmds=1, propose_to=(0,))
    res = mc.Explorer("minpaxos", b).run()
    assert res.ok and res.drained, res.to_dict()
    assert res.states > 50 and res.max_depth_seen == 4
    d = res.to_dict()
    assert d["ok"] and d["invariants_checked"] == [
        "slot-agreement", "validity", "frontier-monotonic"]


def test_mutant_broken_quorum_yields_replayable_counterexample():
    """Acceptance: a seeded non-intersecting quorum (q=1 at N=3 — the
    exact class the quorum-certificate pass guards against) must
    produce a split-brain counterexample, minimal under BFS, whose
    replay re-derives a REAL invariant failure via the shared
    predicates."""
    mc = _mc()
    b = mc.Bounds(max_depth=6, drops=2, dups=0, internal=1, elections=1,
                  electable=(1,), n_cmds=2, propose_to=(0, 1))
    res = mc.Explorer("minpaxos", b, majority_override=1).run()
    assert res.counterexample is not None, res.to_dict()
    ce = res.counterexample
    assert any("DIVERGENCE" in v for v in ce.report["violations"])
    assert len(ce.trace) <= 5  # BFS: minimal in action count
    # replay through a fresh explorer reproduces the same violation
    reproduced, report = mc.replay_counterexample(ce.to_dict())
    assert reproduced and not report.ok
    assert any("DIVERGENCE" in v for v in report.violations)
    # JSON round-trip is lossless
    ce2 = mc.Counterexample.from_dict(
        json.loads(json.dumps(ce.to_dict())))
    assert ce2.trace == ce.trace and ce2.protocol == ce.protocol
    # and the FaultPlan projection is an installable chaos schedule
    fp = mc.counterexample_faultplan(ce)
    plan = FaultPlan.from_dict(fp["plan"])
    assert plan.n == 3 and not plan.is_noop()
    assert fp["events"][0][1] == "install" and fp["events"][1][1] == "clear"


def test_replay_rejects_foreign_formats():
    mc = _mc()
    with pytest.raises(ValueError):
        mc.replay_counterexample({"format": "not-a-ce", "trace": []})


def test_committed_fixture_is_current_format():
    """The checked-in counterexample fixtures replay through
    tests/test_safety_random.py; here: the format tag stays pinned so
    a format change must migrate the fixtures in the same PR."""
    fixtures = sorted((REPO / "tests/fixtures").glob("mc_*.json"))
    assert fixtures, "the seeded-mutant fixture must stay checked in"
    mc = _mc()
    for p in fixtures:
        doc = json.loads(p.read_text())
        assert doc["format"] == mc.CE_FORMAT, p
