#!/usr/bin/env python
"""trend — the cross-PR throughput/latency trajectory, as markdown.

The bench artifacts were stamped for exactly this (`measured_this_run`,
`resident`, `shape`, mtimes), but nothing ever read them side by side:
every session that wanted the regression view re-opened BENCH_*.json by
hand. This tool prints it once: per committed accelerator artifact
(`BENCH_r*.json` driver captures, `BENCH_LADDER_CPU.json`,
`BENCH_TCP.json`) the headline throughput, quorum p50/p99, platform and
shape — plus verification coverage from the model-checker artifacts
(`MC.json`/`MC_FLEX.json`: refined edges, fair lassos, mutant
self-tests), the paxsoak scenario scorecard (`SOAK.json`: per-phase
throughput / latency / admission shed / alarm classification from the
committed chaos-under-load run) and the repo-growth trajectory from
`PROGRESS.jsonl` (per driver round: commits, LoC). Report-only: reads the committed
artifacts, writes nothing, imports no JAX — safe to run anywhere,
cheap enough to paste into a PR description.

    python tools/trend.py              # markdown tables on stdout
    python tools/trend.py --json      # machine form

Driver captures (`BENCH_r*.json`) are best-effort parses: some rounds
crashed mid-write (r01), some hold only a replayed prior record in a
truncated tail (r05) — rows from a replay are labeled `replay`, rows
with no parseable record report their error instead of a number, and
nothing is ever silently skipped.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _balanced_json(text: str, start: int) -> dict | None:
    """Parse the {...} object starting at ``start`` by brace matching
    (tolerates trailing garbage; returns None on truncation)."""
    depth = 0
    in_str = esc = False
    for i in range(start, len(text)):
        c = text[i]
        if esc:
            esc = False
        elif c == "\\":
            esc = True
        elif c == '"':
            in_str = not in_str
        elif not in_str:
            if c == "{":
                depth += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    try:
                        return json.loads(text[start:i + 1])
                    except json.JSONDecodeError:
                        return None
    return None


def _extract_record(cap: dict) -> tuple[dict | None, str]:
    """(bench record, provenance) from one BENCH_r*.json driver
    capture: the `parsed` record when the driver got one, else the
    last parseable JSON line of the captured tail, else an embedded
    `"record":` replay inside a truncated tail (labeled as such)."""
    rec = cap.get("parsed")
    if isinstance(rec, dict) and "value" in rec:
        return rec, "live"
    tail = cap.get("tail") or ""
    for ln in reversed([l for l in tail.splitlines()
                        if l.strip().startswith("{")]):
        try:
            rec = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if "value" in rec:
            return rec, "live"
    i = tail.find('"record":')
    if i >= 0:
        j = tail.find("{", i)
        rec = _balanced_json(tail, j) if j >= 0 else None
        if isinstance(rec, dict) and "value" in rec:
            return rec, "replay"
    return None, "unparseable"


def _fmt(v, nd=1):
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:,.{nd}f}"
    return f"{v:,}" if isinstance(v, int) else str(v)


def _row_from_record(name: str, rec: dict, provenance: str,
                     mtime: float) -> dict:
    # a record whose own headline is the error stanza may still carry
    # a replayed prior value at top level (bench.py replay_marks)
    value = rec.get("value")
    if rec.get("error") and not value and rec.get("replayed_value"):
        value, provenance = rec["replayed_value"], "replay"
    shape = rec.get("shape") or {}
    return {
        "artifact": name,
        "provenance": provenance,
        "platform": rec.get("platform"),
        "resident": rec.get("resident", False),
        # flexible quorums (PR 16): absent on pre-PR-16 artifacts
        "q1": rec.get("q1"),
        "q2": rec.get("q2"),
        "inst_per_sec": value,
        "p50_ms": rec.get("p50_quorum_decision_ms",
                          rec.get("p50_quorum_decision_ms_censored")),
        "p99_ms": rec.get("p99_quorum_decision_ms"),
        "concurrent": rec.get("concurrent_instances"),
        "shape": (f"g={shape.get('n_shards')} w={shape.get('window')} "
                  f"p={shape.get('proposals')} "
                  f"k={shape.get('rounds_per_dispatch')}"
                  if shape else "-"),
        "error": (rec.get("error") or "")[:60] or None,
        "mtime_utc": time.strftime("%Y-%m-%d", time.gmtime(mtime)),
    }


def collect_bench_rows(repo: Path = REPO) -> list[dict]:
    rows = []
    for path in sorted(glob.glob(str(repo / "BENCH_r*.json"))):
        name = os.path.basename(path)
        try:
            cap = json.load(open(path))
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"artifact": name, "provenance": "unreadable",
                         "error": repr(e)[:60]})
            continue
        rec, prov = _extract_record(cap)
        if rec is None:
            rows.append({"artifact": name, "provenance": prov,
                         "error": f"rc={cap.get('rc')}, no record in tail"})
            continue
        rows.append(_row_from_record(name, rec, prov,
                                     os.path.getmtime(path)))
    lad = repo / "BENCH_LADDER_CPU.json"
    if lad.exists():
        try:
            rec = json.load(open(lad))
            rows.append(_row_from_record(lad.name, rec, "live",
                                         os.path.getmtime(lad)))
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"artifact": lad.name, "provenance": "unreadable",
                         "error": repr(e)[:60]})
    return rows


def collect_tcp_row(repo: Path = REPO) -> dict | None:
    path = repo / "BENCH_TCP.json"
    if not path.exists():
        return None
    try:
        rec = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        return None
    return {
        "artifact": path.name,
        "ops_per_sec": rec.get("ops_per_sec"),
        "serial_p50_ms": rec.get("serial_p50_ms"),
        "serial_p99_ms": rec.get("serial_p99_ms"),
        "stage_tail": _stage_tail(rec.get("serial_traced")),
        "stage_tail_baseline": _stage_tail(
            (rec.get("serial_cadence_baseline") or {}).get("serial_traced")),
        # flexible-quorum paired A/B (PR 16): commit-stage p99 at N=5,
        # majority (q2=3) vs flexible (q1=4, q2=2)
        "flex_commit_p99_ms": (
            rec.get("flex_quorum_ab") or {}).get("commit_p99_ms"),
        "mtime_utc": time.strftime(
            "%Y-%m-%d", time.gmtime(os.path.getmtime(path))),
    }


def _stage_tail(traced: dict | None) -> dict | None:
    """The tail-trajectory row (ISSUE 15): commit / exec_wait p99 and
    their share of the traced end-to-end p99, from a serial leg's
    embedded paxtrace stage table — so the tail's WHERE is tracked
    across PRs like throughput, not just its size."""
    if not isinstance(traced, dict):
        return None
    stages = traced.get("stages") or {}
    total = (traced.get("total_ms") or {}).get("p99")
    commit = (stages.get("commit") or {}).get("p99")
    exec_wait = (stages.get("exec_wait") or {}).get("p99")
    if total is None or commit is None or exec_wait is None:
        return None
    # share of the tail owned by commit+exec_wait, from the
    # tail-command stage MEANS (per-stage p99s are order statistics
    # of different commands — their sum can exceed the total p99);
    # fall back to the p99 ratio for pre-PR-12 artifacts without the
    # tail stanza
    means = (traced.get("tail") or {}).get("stage_means_ms") or {}
    mean_total = sum(means.values())
    if mean_total:
        share = (means.get("commit", 0.0)
                 + means.get("exec_wait", 0.0)) / mean_total
    else:
        share = (commit + exec_wait) / total if total else None
    return {
        "commit_p99_ms": round(commit, 3),
        "exec_wait_p99_ms": round(exec_wait, 3),
        "total_p99_ms": round(total, 3),
        "commit_exec_share": round(share, 3) if share is not None else None,
        "worst_stage": (traced.get("tail") or {}).get("worst_stage"),
    }


def collect_health_rows(repo: Path = REPO) -> list[dict]:
    """paxwatch health evidence from committed artifacts: per
    CHAOS.json campaign run the live-detector alarm counts, the
    cluster event-journal kinds, and the stall-schedule live verdict
    (fired-in-window / attributed / cleared); plus any PAXWATCH*.jsonl
    retention series (raw/coarse coverage). Parsed directly — no
    minpaxos import, same zero-dependency contract as the rest of
    this tool."""
    rows: list[dict] = []
    chaos = repo / "CHAOS.json"
    if chaos.exists():
        try:
            doc = json.load(open(chaos))
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"artifact": chaos.name, "error": repr(e)[:60]})
            doc = {"runs": []}
        for r in doc.get("runs", []):
            w = r.get("watch") or {}
            stall = w.get("stall") or {}
            rows.append({
                "artifact": chaos.name,
                "run": f"{r.get('schedule')}@{r.get('seed')}",
                "alarms": w.get("alarm_counts") or {},
                "events": r.get("cluster_events") or {},
                "stall_live": (
                    None if not stall else
                    f"fired={stall.get('fired_in_window')} "
                    f"attributed={stall.get('attributed')} "
                    f"cleared={stall.get('cleared')}"),
                "faults": r.get("faults_injected"),
                "ok": r.get("ok"),
            })
    for path in sorted(glob.glob(str(repo / "PAXWATCH*.jsonl"))):
        raw = coarse = bad = 0
        try:
            for ln in open(path, encoding="utf-8"):
                try:
                    d = json.loads(ln)
                except json.JSONDecodeError:
                    bad += 1
                    continue
                raw += "raw" in d
                coarse += "coarse" in d
        except OSError as e:
            rows.append({"artifact": os.path.basename(path),
                         "error": repr(e)[:60]})
            continue
        rows.append({"artifact": os.path.basename(path),
                     "run": "series", "raw_samples": raw,
                     "coarse_buckets": coarse, "torn_lines": bad,
                     "bytes": os.path.getsize(path)})
    return rows


def collect_verify_rows(repo: Path = REPO) -> list[dict]:
    """Verification evidence from the committed model-checker
    artifacts: per MC.json / MC_FLEX.json run the state/transition
    totals, paxref refinement coverage (edges held to the abstract
    spec), liveness verdicts (fair lassos found — 0 on healthy legs),
    and which seeded mutants the self-tests re-found. Trended so a
    PR that quietly shrinks coverage (fewer refined edges, a skipped
    mutant) shows up next to the throughput row it bought."""
    rows: list[dict] = []
    for name in ("MC.json", "MC_FLEX.json"):
        path = repo / name
        if not path.exists():
            continue
        try:
            doc = json.load(open(path))
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"artifact": name, "error": repr(e)[:60]})
            continue
        runs = doc.get("runs") or []
        refine = doc.get("refine") or {}
        liveness = doc.get("liveness") or {}
        live_legs = liveness.get("legs") or []
        mutants = {
            "quorum": (doc.get("mutant_self_test") or {}).get("found"),
            "flex": (doc.get("flex_mutant_self_test") or {}).get("found"),
            "refine": (doc.get("refine_mutant_self_test")
                       or {}).get("found"),
            "lasso": (doc.get("lasso_mutant_self_test") or {}).get("found"),
        }
        rows.append({
            "artifact": name,
            "ok": doc.get("ok"),
            "runs": len(runs),
            "states": sum(r.get("states") or 0 for r in runs),
            "transitions": sum(r.get("transitions") or 0 for r in runs),
            # MC_FLEX stamps refined_edges at top level (every sweep
            # run is refinement-checked); MC.json under "refine"
            "refined_edges": (doc.get("refined_edges")
                              if doc.get("refined_edges") is not None
                              else refine.get("edges_checked")),
            "liveness_legs": len(live_legs),
            "fair_lassos": sum(l.get("fair_lassos") or 0
                               for l in live_legs),
            "mutants_found": " ".join(
                f"{k}:{'y' if v else 'n'}" for k, v in mutants.items()
                if v is not None) or None,
            "wall_s": doc.get("wall_s"),
            "mtime_utc": time.strftime(
                "%Y-%m-%d", time.gmtime(os.path.getmtime(path))),
        })
    return rows


def collect_soak_rows(repo: Path = REPO) -> dict | None:
    """paxsoak scorecard (SOAK.json, tools/soak.py --full): the
    per-phase join — offered vs acked throughput, client latency
    percentiles, admission-gate shed, retransmits, and the detector
    alarms classified against the ground-truth fault windows — plus
    the exactly-once totals and the acceptance criteria stanza. One
    committed artifact, rendered as one table."""
    path = repo / "SOAK.json"
    if not path.exists():
        return None
    try:
        card = json.load(open(path))
    except (OSError, json.JSONDecodeError) as e:
        return {"artifact": path.name, "error": repr(e)[:60]}
    alarms = card.get("alarms") or []
    rows = []
    for p in card.get("phases") or []:
        cl = p.get("client") or {}
        cu = p.get("cluster") or {}
        lat = cl.get("lat_ms") or {}
        dur = p.get("t1_wall", 0) - p.get("t0_wall", 0)
        ph_alarms = [a for a in alarms if a.get("phase") == p.get("name")]
        rows.append({
            "phase": p.get("name"), "kind": p.get("kind"),
            "dur_s": round(dur, 1),
            "sent": cl.get("sent"), "acked": cl.get("acked"),
            "acked_per_s": (round(cl.get("acked", 0) / dur, 1)
                            if dur > 0 else None),
            "retransmits": cl.get("retransmits"),
            "shed": cu.get("coalesce_admission_rejects"),
            "committed": cu.get("committed_slots"),
            "p50_ms": lat.get("p50"), "p99_ms": lat.get("p99"),
            "p999_ms": lat.get("p999"),
            "alarms_in_window": sum(
                1 for a in ph_alarms if a.get("in_fault_window")),
            "alarms_outside": sum(
                1 for a in ph_alarms if not a.get("in_fault_window")),
        })
    return {
        "artifact": path.name,
        "name": card.get("name"),
        "rows": rows,
        "exactly_once": card.get("exactly_once") or {},
        "criteria": card.get("criteria") or {},
        "alarm_counts": (card.get("watch") or {}).get("alarm_counts"),
        "wall_s": card.get("wall_s"),
        "mtime_utc": time.strftime(
            "%Y-%m-%d", time.gmtime(os.path.getmtime(path))),
    }


def collect_durability_rows(repo: Path = REPO) -> list[dict]:
    """paxdur durability evidence from the committed artifacts: per
    durable CHAOS.json run the snapshot/truncation counts, redo-log
    bytes freed vs the final on-disk size (is truncation actually
    bounding disk), and the worst recovery wall from EV_RECOVERY;
    plus the SOAK.json crash_restart verdict (snapshot/recovery event
    totals and the crash-attribution criterion). Trended per PR so a
    change that quietly stops snapshots from engaging — or makes
    recovery walltime blow up — shows in the same table as the
    throughput it bought."""
    rows: list[dict] = []
    chaos = repo / "CHAOS.json"
    if chaos.exists():
        try:
            doc = json.load(open(chaos))
        except (OSError, json.JSONDecodeError) as e:
            rows.append({"artifact": chaos.name, "error": repr(e)[:60]})
            doc = {"runs": []}
        for r in doc.get("runs", []):
            d = r.get("durability")
            if not d:
                continue
            lb = d.get("log_bytes") or {}
            rows.append({
                "artifact": chaos.name,
                "run": f"{r.get('schedule')}@{r.get('seed')}",
                "snapshots": d.get("snapshots"),
                "truncations": d.get("truncations"),
                "bytes_freed": d.get("bytes_freed"),
                "log_bytes_final_max": (max(lb.values())
                                        if lb else None),
                "recovery_ms": d.get("recovery_ms_max"),
                "ok": r.get("ok"),
            })
    soak_p = repo / "SOAK.json"
    if soak_p.exists():
        try:
            card = json.load(open(soak_p))
        except (OSError, json.JSONDecodeError):
            card = None
        ec = (card or {}).get("event_counts") or {}
        if ec.get("snapshot") or ec.get("recovery"):
            rows.append({
                "artifact": soak_p.name,
                "run": card.get("name"),
                "snapshots": ec.get("snapshot", 0),
                "truncations": ec.get("truncate", 0),
                "bytes_freed": None,
                "log_bytes_final_max": None,
                "recovery_ms": None,
                "ok": (card.get("criteria")
                       or {}).get("crash_detected_and_attributed"),
            })
    return rows


def collect_progress(repo: Path = REPO) -> list[dict]:
    """Last PROGRESS.jsonl sample per driver round: commits and LoC at
    round end — the repo-growth axis the bench trajectory rides on."""
    path = repo / "PROGRESS.jsonl"
    if not path.exists():
        return []
    last: dict[int, dict] = {}
    for ln in path.read_text().splitlines():
        try:
            d = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if "round" in d:
            last[int(d["round"])] = d
    return [
        {"round": r, "commits": d.get("commits"), "loc": d.get("loc"),
         "wall_h": round((d.get("wall_s") or 0) / 3600.0, 1)}
        for r, d in sorted(last.items())
    ]


def _fmt_counts(d: dict | None) -> str:
    if not d:
        return "-"
    return " ".join(f"{k}:{v}" for k, v in sorted(d.items()))


def render_markdown(bench, tcp, progress, health=None, verify=None,
                    soak=None, durability=None) -> str:
    out = ["## Cross-PR bench trajectory (device loop)", ""]
    hdr = ("| artifact | when | platform | resident | inst/s | p50 ms "
           "| p99 ms | concurrent | shape | note |")
    out += [hdr, "|" + "---|" * 10]
    for r in bench:
        note = r.get("error") or (
            "replay" if r.get("provenance") == "replay" else "")
        shape = r.get("shape", "-")
        if r.get("q1") and r.get("q2"):
            shape = f"{shape} q={r['q1']}/{r['q2']}"
        out.append(
            f"| {r['artifact']} | {r.get('mtime_utc', '-')} "
            f"| {r.get('platform', '-')} "
            f"| {'y' if r.get('resident') else 'n'} "
            f"| {_fmt(r.get('inst_per_sec'))} | {_fmt(r.get('p50_ms'), 2)} "
            f"| {_fmt(r.get('p99_ms'), 2)} | {_fmt(r.get('concurrent'))} "
            f"| {shape} | {note} |")
    if tcp:
        out += ["", "## TCP runtime (BENCH_TCP.json)", "",
                "| artifact | when | ops/s | serial p50 ms | serial p99 ms |",
                "|" + "---|" * 5,
                f"| {tcp['artifact']} | {tcp['mtime_utc']} "
                f"| {_fmt(tcp['ops_per_sec'])} "
                f"| {_fmt(tcp['serial_p50_ms'], 2)} "
                f"| {_fmt(tcp['serial_p99_ms'], 2)} |"]
        rows = [("event-driven", tcp.get("stage_tail")),
                ("cadence baseline", tcp.get("stage_tail_baseline"))]
        if any(st for _, st in rows):
            out += ["", "### Serial tail attribution (paxtrace stage "
                    "table, p99 ms)", "",
                    "| leg | commit | exec_wait | total | commit+exec "
                    "share | worst stage |", "|" + "---|" * 6]
            for label, st in rows:
                if not st:
                    continue
                share = st.get("commit_exec_share")
                out.append(
                    f"| {label} | {_fmt(st['commit_p99_ms'], 2)} "
                    f"| {_fmt(st['exec_wait_p99_ms'], 2)} "
                    f"| {_fmt(st['total_p99_ms'], 2)} "
                    f"| {f'{share:.0%}' if share is not None else '-'} "
                    f"| {st.get('worst_stage') or '-'} |")
        flex = tcp.get("flex_commit_p99_ms")
        if flex:
            out += ["", "### Flexible-quorum A/B (serial N=5, commit "
                    "stage p99 ms)", "",
                    "| majority (q2=3) | flexible (q1=4, q2=2) |",
                    "|" + "---|" * 2,
                    f"| {_fmt(flex.get('majority_q2_3'), 2)} "
                    f"| {_fmt(flex.get('flex_q1_4_q2_2'), 2)} |"]
    if health:
        out += ["", "## Cluster health (paxwatch artifacts)", "",
                "| artifact | run | ok | alarms | stall live | faults "
                "| events |", "|" + "---|" * 7]
        for h in health:
            if h.get("error"):
                out.append(f"| {h['artifact']} | - | - | - | - | - "
                           f"| {h['error']} |")
            elif h.get("run") == "series":
                out.append(
                    f"| {h['artifact']} | series "
                    f"| - | raw={h['raw_samples']} "
                    f"coarse={h['coarse_buckets']} | - | - "
                    f"| {_fmt(h['bytes'])} B |")
            else:
                out.append(
                    f"| {h['artifact']} | {h['run']} "
                    f"| {'y' if h.get('ok') else 'n'} "
                    f"| {_fmt_counts(h.get('alarms'))} "
                    f"| {h.get('stall_live') or '-'} "
                    f"| {_fmt(h.get('faults'))} "
                    f"| {_fmt_counts(h.get('events'))} |")
    if verify:
        out += ["", "## Verification coverage (paxmc/paxref artifacts)", "",
                "| artifact | when | ok | runs | states | transitions "
                "| refined edges | liveness legs | fair lassos "
                "| mutants re-found | wall s |", "|" + "---|" * 11]
        for v in verify:
            if v.get("error"):
                out.append(f"| {v['artifact']} | - | - | - | - | - | - "
                           f"| - | - | - | {v['error']} |")
                continue
            out.append(
                f"| {v['artifact']} | {v.get('mtime_utc', '-')} "
                f"| {'y' if v.get('ok') else 'n'} | {_fmt(v.get('runs'))} "
                f"| {_fmt(v.get('states'))} | {_fmt(v.get('transitions'))} "
                f"| {_fmt(v.get('refined_edges'))} "
                f"| {_fmt(v.get('liveness_legs'))} "
                f"| {_fmt(v.get('fair_lassos'))} "
                f"| {v.get('mutants_found') or '-'} "
                f"| {_fmt(v.get('wall_s'))} |")
    if soak:
        out += ["", "## Soak scenario (paxsoak SOAK.json)", ""]
        if soak.get("error"):
            out += [f"{soak['artifact']}: {soak['error']}"]
        else:
            eo = soak.get("exactly_once") or {}
            crit = soak.get("criteria") or {}
            out += [
                f"`{soak['artifact']}` run `{soak.get('name')}` "
                f"({soak.get('mtime_utc', '-')}): "
                f"acked {_fmt(eo.get('acked_unique'))}"
                f"/{_fmt(eo.get('sent_unique'))} unique, "
                f"lost {_fmt(eo.get('lost'))}, "
                f"dup {_fmt(eo.get('duplicates'))}, "
                f"criteria " + " ".join(
                    f"{k}:{'y' if v else 'n'}"
                    for k, v in sorted(crit.items())), "",
                "| phase | kind | dur s | sent | acked | acked/s "
                "| retx | shed | p50 ms | p99 ms | p999 ms "
                "| alarms in/out window |",
                "|" + "---|" * 12]
            for r in soak.get("rows") or []:
                out.append(
                    f"| {r['phase']} | {r['kind']} | {r['dur_s']} "
                    f"| {_fmt(r['sent'])} | {_fmt(r['acked'])} "
                    f"| {_fmt(r['acked_per_s'])} "
                    f"| {_fmt(r['retransmits'])} | {_fmt(r['shed'])} "
                    f"| {_fmt(r['p50_ms'], 1)} | {_fmt(r['p99_ms'], 1)} "
                    f"| {_fmt(r['p999_ms'], 1)} "
                    f"| {r['alarms_in_window']}/{r['alarms_outside']} |")
    if durability:
        out += ["", "## Durability (paxdur: CHAOS.json durable runs + "
                "SOAK.json)", "",
                "| artifact | run | ok | snapshots | truncations "
                "| bytes freed | final log max | recovery ms |",
                "|" + "---|" * 8]
        for d in durability:
            if d.get("error"):
                out.append(f"| {d['artifact']} | - | - | - | - | - | - "
                           f"| {d['error']} |")
                continue
            out.append(
                f"| {d['artifact']} | {d.get('run', '-')} "
                f"| {'y' if d.get('ok') else 'n'} "
                f"| {_fmt(d.get('snapshots'))} "
                f"| {_fmt(d.get('truncations'))} "
                f"| {_fmt(d.get('bytes_freed'))} "
                f"| {_fmt(d.get('log_bytes_final_max'))} "
                f"| {_fmt(d.get('recovery_ms'))} |")
    if progress:
        out += ["", "## Repo growth (PROGRESS.jsonl, per driver round)", "",
                "| round | commits | LoC | wall h |", "|" + "---|" * 4]
        out += [f"| {p['round']} | {_fmt(p['commits'])} | {_fmt(p['loc'])} "
                f"| {p['wall_h']} |" for p in progress]
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "trend", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--json", action="store_true",
                    help="emit the collected rows as JSON instead of "
                         "markdown")
    ap.add_argument("--repo", default=str(REPO),
                    help="repo root holding the artifacts")
    args = ap.parse_args(argv)
    repo = Path(args.repo)
    bench = collect_bench_rows(repo)
    tcp = collect_tcp_row(repo)
    progress = collect_progress(repo)
    health = collect_health_rows(repo)
    verify = collect_verify_rows(repo)
    soak = collect_soak_rows(repo)
    durability = collect_durability_rows(repo)
    if args.json:
        print(json.dumps({"bench": bench, "tcp": tcp,
                          "progress": progress, "health": health,
                          "verify": verify, "soak": soak,
                          "durability": durability},
                         indent=1))
    else:
        print(render_markdown(bench, tcp, progress, health, verify,
                              soak, durability))
    return 0


if __name__ == "__main__":
    sys.exit(main())
