#!/usr/bin/env python
"""paxmon CI smoke: recorder-overhead guard + paxtop end-to-end check.

Run by tools/run_tier1.sh right after paxlint (no JAX import, cold in
a few seconds). Two gates (three with ``--resident``, which needs a
JAX boot and is therefore wired in LATER in run_tier1.sh, after the
shape-ladder smoke has paid the backend init):

1. **Recorder-overhead guard** — the observability layer is
   default-ON in the runtime, so its hot-path cost is a standing
   contract: one fully-instrumented tick body (counter advances +
   two histogram observes + one flight-recorder ring write) is
   microbenchmarked against the same body with instrumentation off.
   The delta must stay in the noise next to the runtime's 300-900 us
   device-dispatch floor; the gate fails at 30 us/tick — an order of
   magnitude above the measured few-us cost, an order below the floor
   — so only a real regression (accidental allocation, lock on the
   advance path, O(capacity) record) trips CI.

2. **paxtop smoke** — boots a real in-process master, registers a
   control-plane-only replica stub (a JSON-lines socket server backed
   by a REAL MetricsRegistry + FlightRecorder seeded with all four
   dispatch regimes), then runs ``tools/paxtop.py --once --json`` as
   a subprocess and the master ``trace`` fan-out, validating the
   merged Chrome trace against the trace-event schema. Every hop a
   production paxtop uses — master fan-out verb, control socket,
   trace merge, schema — is exercised without compiling a kernel.

3. **paxray resident-telemetry gate** (``--resident``) — the ISSUE-9
   overhead contract: the device-resident measured loop with the
   paxray telemetry ring armed must (a) land in a byte-identical
   protocol state vs telemetry-off, (b) keep the dispatch wall within
   2% of telemetry-off (min-of-N walls, interleaved A/B so host noise
   hits both sides; one automatic re-measure at double iterations
   before failing), and (c) produce a merged host+device Chrome trace
   that validates, with the device rounds under the reserved pid.

Exit status: 0 = all gates pass, 1 = failure (fails the build).
"""

from __future__ import annotations

import json
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from minpaxos_tpu.obs.metrics import MetricsRegistry  # noqa: E402
from minpaxos_tpu.obs.recorder import (  # noqa: E402
    KIND_NAMES,
    FlightRecorder,
    chrome_trace,
    validate_chrome_trace,
)
from minpaxos_tpu.obs.trace import (  # noqa: E402
    ST_COMMIT,
    ST_DECODE,
    ST_DRAIN,
    ST_EXEC,
    ST_ORIGIN,
    ST_REPLY_RECV,
    ST_REPLY_SER,
    ST_SEND,
    TraceSink,
    analyze_collections,
    span_events,
)
from minpaxos_tpu.obs.watch import (  # noqa: E402
    EV_ALARM,
    EV_CHAOS_INSTALL,
    EV_CLIENT_FAILOVER,
    EV_ELECTION,
    EV_LEADER_CHANGE,
    EV_NARROW_FALLBACK,
    EV_STORE_CORRUPT,
    DET_STALL,
    EventJournal,
    align_event_collections,
    event_chrome_events,
)
from minpaxos_tpu.runtime.master import (  # noqa: E402
    Master,
    cluster_events,
    cluster_stats,
    cluster_trace,
    register_with_master,
)
from minpaxos_tpu.utils.netutil import CONTROL_OFFSET, free_ports  # noqa: E402

# generous noise bound (seconds/tick): ~10x the measured cost on a
# slow shared core, ~10-30x under the dispatch floor it rides next to
OVERHEAD_BOUND_S = 30e-6
N_ITERS = 20000


def _tick_body(x: float) -> float:
    """Stand-in per-tick host work, identical in both loops."""
    return x * 1.0000001 + 0.25


def overhead_guard() -> bool:
    reg = MetricsRegistry("smoke")
    tick_inc = 1  # wall-honesty spelling, as the runtime advances it
    c_ticks = reg.counter("ticks")
    c_disp = reg.counter("dispatches")
    h_tick = reg.histogram("tick_wall_ms")
    h_step = reg.histogram("device_step_ms")
    rec = FlightRecorder(4096)

    # warm both paths (allocator, bytecode caches), then measure.
    # The record call carries the schema-v2 pipelined row (enqueue /
    # readback / overlap split + the readback timestamp): the overhead
    # contract covers the 15-field write the pipelined runtime
    # actually performs.
    for instrumented in (False, True):
        x = 1.0
        for i in range(2000):
            x = _tick_body(x)
            if instrumented:
                c_ticks.inc(tick_inc)
                rec.record(i, i % 4, 1, 8, 8, i, 0, 5, 30, 270, 60,
                           20, 30, 10, i)

    x = 1.0
    t0 = time.perf_counter()
    for _ in range(N_ITERS):
        x = _tick_body(x)
    base_s = time.perf_counter() - t0

    x = 1.0
    t0 = time.perf_counter()
    for i in range(N_ITERS):
        x = _tick_body(x)
        c_ticks.inc(tick_inc)
        c_disp.inc()
        h_tick.observe(0.7)
        h_step.observe(0.4)
        rec.record(i, i % 4, 1, 8, 8, i, 0, 5, 30, 270, 60, 20, 30, 10, i)
    inst_s = time.perf_counter() - t0

    per_tick = (inst_s - base_s) / N_ITERS
    ok = per_tick < OVERHEAD_BOUND_S
    print(f"[obs_smoke] recorder+registry overhead: "
          f"{per_tick * 1e6:.2f} us/tick over {N_ITERS} ticks "
          f"(bound {OVERHEAD_BOUND_S * 1e6:.0f} us) — "
          f"{'ok' if ok else 'FAIL'}", flush=True)
    assert c_ticks.value == N_ITERS + 2000 and rec.total == N_ITERS + \
        2000, "guard loops did not run instrumented"
    return ok


def trace_overhead_guard() -> bool:
    """paxtrace hot-path budget (ISSUE 12): the per-command cost of
    tracing-on must stay under 30 us — an order of magnitude under
    the serial path's millisecond scale, so a tracing-on serial p50
    stays within noise of tracing-off. Measured the way the runtime
    actually pays it: one vectorized sampling hash per 512-command
    batch plus span stamps for the sampled commands (1-in-16 at the
    default exponent), against the same loop with tracing off."""
    import numpy as np

    sink_on = TraceSink(enabled=True, sample_pow2=4, ring_capacity=8192)
    sink_off = TraceSink(enabled=False, sample_pow2=4)
    batches = [np.arange(i * 512, (i + 1) * 512, dtype=np.int64)
               for i in range(8)]
    n_cmds = 512 * len(batches)
    reps = 40

    def run(sink) -> float:
        t0 = time.perf_counter()
        for _ in range(reps):
            for ids in batches:
                if sink.enabled:
                    # the replica drain path: one hash + stamps
                    sink.stamp_batch(ST_DRAIN, ids, 1, 2, aux=0)
        return time.perf_counter() - t0

    run(sink_on), run(sink_off)  # warm allocator/bytecode
    off_s = run(sink_off)
    on_s = run(sink_on)
    per_cmd = (on_s - off_s) / (n_cmds * reps)
    ok = per_cmd < OVERHEAD_BOUND_S
    stamped = sink_on.spans_total()
    print(f"[obs_smoke] paxtrace overhead: {per_cmd * 1e6:.3f} us/command "
          f"({stamped} spans stamped over {n_cmds * reps} commands, "
          f"bound {OVERHEAD_BOUND_S * 1e6:.0f} us) — "
          f"{'ok' if ok else 'FAIL'}", flush=True)
    assert stamped > 0, "guard loop never stamped a span"
    return ok


#: paxwatch journal budget (seconds/event): the journal is default-ON
#: in the runtime, but its events are RARE (elections, failovers,
#: fault installs — not per-tick), so the bound is tighter than the
#: recorder's: one ring write + two clock reads must stay under 5 us.
JOURNAL_BOUND_S = 5e-6


def journal_overhead_guard() -> bool:
    """paxwatch event-journal cost: one journal.record (tls ring
    lookup + two clock reads + one slice assign) measured against the
    same loop without it — the ISSUE-13 <=5 us/event contract."""
    j = EventJournal(capacity=4096)

    x = 1.0
    for i in range(2000):  # warm allocator/bytecode + the tls ring
        x = _tick_body(x)
        j.record(EV_ELECTION, subject=0, value=i)

    x = 1.0
    t0 = time.perf_counter()
    for _ in range(N_ITERS):
        x = _tick_body(x)
    base_s = time.perf_counter() - t0

    x = 1.0
    t0 = time.perf_counter()
    for i in range(N_ITERS):
        x = _tick_body(x)
        j.record(EV_ELECTION, subject=0, value=i)
    inst_s = time.perf_counter() - t0

    per_event = (inst_s - base_s) / N_ITERS
    ok = per_event < JOURNAL_BOUND_S
    print(f"[obs_smoke] paxwatch journal overhead: "
          f"{per_event * 1e6:.2f} us/event over {N_ITERS} events "
          f"(bound {JOURNAL_BOUND_S * 1e6:.0f} us) — "
          f"{'ok' if ok else 'FAIL'}", flush=True)
    assert j.events_total() == N_ITERS + 2000, \
        "guard loop did not journal"
    return ok


def _seed_journal() -> EventJournal:
    """A journal holding one of each loud-path event, as a live
    replica's EVENTS verb would serve them."""
    j = EventJournal(capacity=256)
    j.record(EV_ELECTION, subject=0, value=-1)
    j.record(EV_LEADER_CHANGE, subject=0, value=0, aux=-1)
    j.record(EV_CHAOS_INSTALL, subject=0, value=1234)
    j.record(EV_NARROW_FALLBACK, subject=0, value=1)
    j.record(EV_STORE_CORRUPT, subject=0, value=3)
    j.record(EV_CLIENT_FAILOVER, subject=2, value=1)
    j.record(EV_ALARM, subject=0, value=900, aux=DET_STALL)
    return j


def _seed_trace_sink() -> TraceSink:
    """A sink holding complete span chains for 8 commands, as a live
    replica's TRACESPANS verb would serve them (cluster-side stages;
    two commands additionally carry the client-side SEND/REPLY_RECV
    so the merge path is covered too)."""
    sink = TraceSink(enabled=True, sample_pow2=0, ring_capacity=256)
    ring = sink.ring()
    from minpaxos_tpu.obs.trace import trace_id_for

    t = 2_000_000_000
    for cmd in range(8):
        tid = trace_id_for(cmd)
        t += 5_000_000
        ring.record(tid, ST_SEND, t, t + 100_000, cmd)
        ring.record(tid, ST_ORIGIN, t, t, cmd)
        ring.record(tid, ST_DECODE, t + 300_000, t + 400_000, cmd)
        ring.record(tid, ST_DRAIN, t + 900_000, t + 900_000, 10 + cmd)
        ring.record(tid, ST_COMMIT, t + 2_400_000, t + 2_400_000, cmd)
        ring.record(tid, ST_EXEC, t + 2_600_000, t + 2_600_000, 12 + cmd)
        ring.record(tid, ST_REPLY_SER, t + 2_600_000, t + 2_700_000, cmd)
        ring.record(tid, ST_REPLY_RECV, t + 3_000_000, t + 3_000_000, cmd)
    return sink


def _seed_replica_obs() -> tuple[MetricsRegistry, FlightRecorder]:
    """A registry + recorder as a live replica would carry, with every
    dispatch regime represented so the trace smoke covers all four —
    and both pipeline modes: even rows are serial (overlap_us = 0),
    odd rows are pipelined (host phases hidden under the next
    dispatch's compute), so the end-to-end trace leg exercises the
    schema-v2 enqueue/readback/overlap fields."""
    reg = MetricsRegistry("replica0")
    tick_inc = 1
    reg.counter("ticks").inc(40 * tick_inc)
    reg.counter("dispatches").inc(30)
    reg.counter("full_steps").inc(20)
    reg.counter("fused_dispatches").inc(6)
    reg.counter("narrow_steps").inc(4)
    reg.counter("idle_skips").inc(10)
    reg.counter("fused_substeps").inc(42)
    reg.counter("pipelined_ticks").inc(12)
    reg.gauge("committed").set(1234)
    h = reg.histogram("tick_wall_ms")
    for v in (0.4, 0.7, 1.5, 3.0, 9.0):
        h.observe(v)
    rec = FlightRecorder(256)
    t = 1_000_000_000
    for i, kind in enumerate([0, 1, 2, 3] * 6):
        t += 2_000_000
        rec.record(t, kind, 3 if kind == 1 else 1, 8, 12, 100 + i, 2,
                   15, 40, 760, 250 if i % 2 else 0, 120, 90, 40,
                   t - 300_000)
    return reg, rec


def _fake_replica_control(ctl_sock: socket.socket, reg, rec,
                          stop: threading.Event, sink=None,
                          journal=None) -> None:
    """Answer ping/stats/trace/tracespans/events on a control socket
    exactly like runtime/replica.py's control plane (JSON lines)."""
    def serve(conn):
        f = conn.makefile("rw")
        try:
            for line in f:
                req = json.loads(line)
                m = req.get("m")
                if m == "tracespans" and sink is not None:
                    resp = {"ok": True, "id": 0, "trace": sink.collect()}
                elif m == "events" and journal is not None:
                    resp = {"ok": True, "id": 0,
                            "journal": journal.collect()}
                elif m == "ping":
                    resp = {"ok": True, "frontier": 123, "leader": 0,
                            "stats": reg.counters(), "fatal": None}
                elif m == "stats":
                    resp = {"ok": True, "id": 0, "protocol": "minpaxos",
                            "leader": 0, "frontier": 123,
                            "window_base": 0, "executed": 121,
                            "work_pending": False,
                            "metrics": reg.snapshot(),
                            "scalars": {"executed": 121}, "fatal": None}
                elif m == "trace":
                    last = req.get("last")
                    evs = rec.to_events(
                        pid=0, last=int(last) if last else None)
                    if journal is not None:
                        evs += event_chrome_events(journal.snapshot(),
                                                   tid=0)
                    resp = {"ok": True, "id": 0, "recorder": True,
                            "events": evs}
                else:
                    resp = {"ok": False, "error": f"unknown {m}"}
                f.write(json.dumps(resp) + "\n")
                f.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    while not stop.is_set():
        try:
            conn, _ = ctl_sock.accept()
        except OSError:
            return
        threading.Thread(target=serve, args=(conn,), daemon=True).start()


def paxtop_smoke() -> bool:
    # ONE selection holds all four ports (both + their +1000 siblings)
    # simultaneously: separate calls could hand the replica a control
    # port equal to the already-released master port (CI flake)
    mport, dport = free_ports(2, sibling_offset=CONTROL_OFFSET)
    master = Master("127.0.0.1", mport, 1, ping_s=30.0)
    master.start()
    reg, rec = _seed_replica_obs()
    sink = _seed_trace_sink()
    journal = _seed_journal()
    # the runtime registers these fn-gauges in ReplicaServer.__init__;
    # paxtop's TRACE column reads them out of the stats snapshot
    reg.fn_gauge("trace_spans", sink.spans_total)
    reg.fn_gauge("trace_dropped", sink.spans_dropped)
    reg.fn_gauge("events", journal.events_total)
    ctl = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    ctl.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    ctl.bind(("127.0.0.1", dport + CONTROL_OFFSET))
    ctl.listen(8)
    stop = threading.Event()
    threading.Thread(target=_fake_replica_control,
                     args=(ctl, reg, rec, stop, sink, journal),
                     daemon=True).start()
    ok = True
    try:
        register_with_master(("127.0.0.1", mport), "127.0.0.1", dport,
                             timeout_s=10.0)

        # master stats fan-out reaches the replica's registry
        stats = cluster_stats(("127.0.0.1", mport))
        r0 = stats["replicas"][0]
        assert r0["ok"] and r0["metrics"]["counters"]["dispatches"] == 30, r0

        # master trace fan-out merges a schema-valid Chrome trace
        # showing all four dispatch regimes AND both pipeline modes
        # (schema v2: enqueue/readback child phases, overlap_us args
        # + counter track — the pipelined-mode leg of this smoke)
        tr = cluster_trace(("127.0.0.1", mport), last=64)
        errs = validate_chrome_trace(tr["trace"])
        assert not errs, errs[:5]
        evs = tr["trace"]["traceEvents"]
        kinds = {e["args"]["kind"] for e in evs if e.get("cat") == "tick"}
        assert kinds == set(KIND_NAMES), kinds
        phase_names = {e["name"] for e in evs if e.get("cat") == "phase"}
        assert {"enqueue", "readback"} <= phase_names, phase_names
        assert "device_step" not in phase_names, phase_names
        overlaps = {e["args"]["overlap_us"] for e in evs
                    if e.get("cat") == "tick"}
        assert 0 in overlaps and max(overlaps) > 0, overlaps
        assert any(e["name"] == "overlap_us" for e in evs
                   if e.get("ph") == "C")

        # the shipped tool, as a real subprocess: --once --json
        out = subprocess.run(
            [sys.executable, str(REPO / "tools/paxtop.py"),
             "-mport", str(mport), "--once", "--json"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        payload = json.loads(out.stdout)
        row = payload["derived"][0]
        assert row["ok"] and row["dispatches"] == 30, row
        assert abs(sum(row["mix_pct"].values()) - 100.0) < 1e-6, row
        assert row["trace_spans"] == sink.spans_total(), row
        # paxwatch panes in the same snapshot: the EVENTS tail and the
        # HEALTH column (newest WARN-or-worse event per replica — the
        # seeded journal ends on an alarm)
        assert {"response", "derived", "events", "health"} <= \
            set(payload), sorted(payload)
        assert len(payload["events"]) == journal.events_total()
        assert payload["events"][-1]["kind"] == "alarm:frontier_stall"
        assert row["health"]["kind"] == "alarm:frontier_stall", row
        print("[obs_smoke] paxtop --once --json + trace fan-out + "
              "EVENTS/HEALTH panes: ok", flush=True)

        # paxwatch EVENTS fan-out leg: the master verb, anchor-aligned
        # merge, and the schema-v6 instant events validating alongside
        # the recorder ticks (reserved-pid contract both directions)
        ev = cluster_events(("127.0.0.1", mport))
        assert ev["ok"] and ev["replicas"][0]["ok"], ev
        jrn = ev["replicas"][0]["journal"]
        assert jrn["total"] == journal.events_total(), jrn["total"]
        rows_aligned = align_event_collections([jrn])
        merged = chrome_trace(rec.to_events(pid=0)
                              + event_chrome_events(rows_aligned))
        errs = validate_chrome_trace(merged)
        assert not errs, errs[:5]
        tr2 = cluster_trace(("127.0.0.1", mport), last=64)
        watch_evs = [e for e in tr2["trace"]["traceEvents"]
                     if e.get("cat") == "paxwatch"]
        assert len(watch_evs) == journal.events_total(), len(watch_evs)
        assert validate_chrome_trace(tr2["trace"]) == []
        print("[obs_smoke] cluster_events fan-out + merged v6 event "
              "track: ok", flush=True)

        # the shipped watcher, as a real subprocess against the same
        # stub cluster: one sample + detector evaluation + event counts
        out = subprocess.run(
            [sys.executable, str(REPO / "tools/paxwatch.py"),
             "-mport", str(mport), "--once", "--json"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        w = json.loads(out.stdout)
        assert {"sample", "alarms", "events", "slo"} <= set(w), sorted(w)
        assert w["sample"]["alive"] == 1 and w["sample"]["tip"] == 123, w
        assert w["events"].get("alarm") == 1, w["events"]
        print("[obs_smoke] paxwatch --once --json: ok", flush=True)

        # paxtrace leg: tools/tail.py --once --json (a real
        # subprocess, no JAX import there either) through the master's
        # TRACESPANS fan-out, stage-sum consistency, and the merged
        # schema-v5 trace (recorder ticks + command-span tracks)
        out = subprocess.run(
            [sys.executable, str(REPO / "tools/tail.py"),
             "-mport", str(mport), "--once", "--json"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        tail = json.loads(out.stdout)
        table = tail["stage_table"]
        assert table["n_traced"] == 8, table
        assert table["tail"]["worst_stage"] == "commit", table["tail"]
        for d in tail["per_trace"]:
            assert abs(sum(d["stages"].values()) - d["total_ms"]) < 1e-9
        table2, decomp, chains = analyze_collections([sink.collect()])
        merged = chrome_trace(rec.to_events(pid=0)
                              + span_events(decomp, chains))
        errs = validate_chrome_trace(merged)
        assert not errs, errs[:5]
        assert table2["n_traced"] == 8

        # the paxtop contract, pinned hard: importing tail.py's (and
        # paxwatch.py's) whole module graph must not pull in JAX (a
        # transitive jax import would make every invocation pay
        # backend init — paxwatch is meant to sit on week-long runs)
        for tool in ("tools/tail.py", "tools/paxwatch.py"):
            probe = subprocess.run(
                [sys.executable, "-c",
                 "import sys, runpy; "
                 f"runpy.run_path({str(REPO / tool)!r}, "
                 "run_name='probe'); "
                 "assert 'jax' not in sys.modules, "
                 f"'jax leaked onto the {tool} import path'"],
                capture_output=True, text=True, timeout=60)
            assert probe.returncode == 0, (tool, probe.stderr)
        print("[obs_smoke] tail --once --json + merged command-span "
              "trace + no-jax import pins: ok", flush=True)
    except AssertionError as e:
        print(f"[obs_smoke] paxtop smoke FAILED: {e}", file=sys.stderr,
              flush=True)
        ok = False
    finally:
        stop.set()
        try:
            ctl.close()
        except OSError:
            pass
        master.stop()
    return ok


def resident_telemetry_smoke() -> bool:
    """paxray gate: telemetry on/off parity + <=2% dispatch-wall
    overhead + merged-trace validation, against the REAL resident
    loop on a small shape (the only JAX-touching leg of this tool —
    run via ``--resident`` after something else paid the backend
    boot)."""
    import jax
    import numpy as np

    from minpaxos_tpu.models.minpaxos import MinPaxosConfig
    from minpaxos_tpu.obs.recorder import (
        DEVICE_PID,
        chrome_trace,
        device_round_events,
    )
    from minpaxos_tpu.parallel.sharded import ShardedCluster

    # p sized so the step kernels dominate the dispatch wall: the
    # telemetry cost is a fixed ~dozen scalar ops per round (XLA-CPU
    # thunk overhead, invariant in p), so the gate must measure it
    # against a realistic amount of per-round work, not a toy round
    g, p, k = 2, 64, 16
    cfg = MinPaxosConfig(n_replicas=3, window=256, inbox=256,
                         exec_batch=64, kv_pow2=10, catchup_rows=16,
                         recovery_rows=16)

    def boot(tel_rounds: int) -> ShardedCluster:
        sc = ShardedCluster(cfg, g, ext_rows=p, key_space=1 << 8, seed=7)
        sc.elect(0)
        sc.begin_resident(telemetry_rounds=tel_rounds)
        sc.run_resident(k, p)  # warm/compile this variant
        return sc

    t0 = time.perf_counter()
    sc_off, sc_on = boot(0), boot(16 * k)
    print(f"[obs_smoke] resident compile (both variants): "
          f"{time.perf_counter() - t0:.1f}s", flush=True)

    def measure(iters: int) -> tuple[float, float, list[dict]]:
        """Interleaved A/B min-of-iters dispatch walls (s), order
        alternating per iteration so shared-host interference cannot
        systematically tax one side; the min is the noise-free
        estimate. Returns the ON side's dispatch log for the trace
        leg."""
        off_w, on_w, disp = [], [], []

        def one_off():
            t0 = time.perf_counter()
            sc_off.run_resident(k, p)
            off_w.append(time.perf_counter() - t0)

        def one_on():
            r0, n0 = sc_on._seed, time.monotonic_ns()
            t0 = time.perf_counter()
            sc_on.run_resident(k, p)
            on_w.append(time.perf_counter() - t0)
            disp.append({"t0_ns": n0, "t1_ns": time.monotonic_ns(),
                         "round0": r0, "k": k})

        for i in range(iters):
            for fn in ((one_off, one_on) if i % 2 == 0
                       else (one_on, one_off)):
                fn()
        return min(off_w), min(on_w), disp

    off_s, on_s, disp_log = measure(12)
    ratio = on_s / off_s
    if ratio > 1.02:
        # one automatic re-measure at double depth before failing: a
        # single background-load spike must not fail the build, a real
        # per-round telemetry cost will reproduce
        off_s, on_s, more = measure(24)
        disp_log += more
        ratio = on_s / off_s
    ok = ratio <= 1.02
    print(f"[obs_smoke] resident dispatch wall: telemetry off "
          f"{off_s * 1e3:.2f} ms vs on {on_s * 1e3:.2f} ms "
          f"(x{ratio:.4f}, bound x1.02) — {'ok' if ok else 'FAIL'}",
          flush=True)

    # drain both, then hold the full contract: byte-identical state,
    # identical scalars, and a valid merged host+device trace
    for sc in (sc_off, sc_on):
        for _ in range(8):
            c, f = sc.run_resident(k, 0)
            if f == 0:
                break
    try:
        for a, b in zip(jax.tree_util.tree_leaves(sc_off.ss),
                        jax.tree_util.tree_leaves(sc_on.ss)):
            assert np.array_equal(np.asarray(a), np.asarray(b)), \
                "telemetry-on state diverged from telemetry-off"
        tel = sc_on.resident_telemetry()
        assert len(tel) > 0, "telemetry ring captured nothing"
        reg, rec = _seed_replica_obs()
        events = rec.to_events(pid=0) + device_round_events(
            tel, disp_log, n_shards=g)
        errs = validate_chrome_trace(chrome_trace(events))
        assert not errs, errs[:5]
        dev = [e for e in events if e.get("cat") == "device_round"]
        assert dev and all(e["pid"] == DEVICE_PID for e in dev)
        print(f"[obs_smoke] telemetry parity + merged device trace "
              f"({len(dev)} round slices): ok", flush=True)
    except AssertionError as e:
        print(f"[obs_smoke] paxray smoke FAILED: {e}", file=sys.stderr,
              flush=True)
        return False
    return ok


def main() -> int:
    if "--resident" in sys.argv[1:]:
        return 0 if resident_telemetry_smoke() else 1
    ok = overhead_guard()
    ok = trace_overhead_guard() and ok
    ok = journal_overhead_guard() and ok
    ok = paxtop_smoke() and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
