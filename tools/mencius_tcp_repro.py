"""Repro driver: the mencius_tcp leg alone, with server stderr kept.

BENCH_TCP round-5 observed trial 4 of 5 losing exactly one rr
partition (13333/20000 acked); bench_tcp.py discards server stderr, so
this driver re-runs just that leg with per-server log files under
.bench_tcp_store/ to catch a fatal/fail-stop/exception on the replica.
"""

from __future__ import annotations

import json
import os
import pathlib
import signal
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import numpy as np

from bench_tcp import MENCIUS_SHAPE, _warm, _progress
from minpaxos_tpu.utils.netutil import CONTROL_OFFSET, free_ports

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> None:
    q = int(os.environ.get("BENCH_TCP_Q", "20000"))
    k = int(os.environ.get("BENCH_TCP_K", "5"))
    extra = os.environ.get("MENCIUS_EXTRA", "").split()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    tmp = REPO / ".bench_tcp_store"
    tmp.mkdir(exist_ok=True)
    for f in tmp.glob("stable-store-replica*"):
        f.unlink()
    mport = free_ports(1)[0]
    dports = free_ports(3, sibling_offset=CONTROL_OFFSET)
    procs = [subprocess.Popen(
        [sys.executable, "-m", "minpaxos_tpu.cli.master",
         "-port", str(mport), "-N", "3"],
        env=env, cwd=tmp, stdout=subprocess.DEVNULL,
        stderr=open(tmp / "master.err", "w"))]
    time.sleep(1.5)
    for p in dports:
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "minpaxos_tpu.cli.server",
             "-m", "-durable", "-port", str(p),
             "-mport", str(mport), *MENCIUS_SHAPE, *extra,
             "-storedir", str(tmp)],
            env=env, cwd=tmp, stdout=subprocess.DEVNULL,
            stderr=open(tmp / f"server{p}.err", "w")))
    maddr = ("127.0.0.1", mport)
    try:
        from minpaxos_tpu.runtime.client import MultiClient, gen_workload

        _warm(maddr)
        ops, keys, vals = gen_workload(q, seed=42)
        import threading

        for t in range(k):
            drv = MultiClient(maddr, check=True, mode="rr")
            stop_sampler = []

            import socket

            def ping(port):
                try:
                    with socket.create_connection(
                            ("127.0.0.1", port + CONTROL_OFFSET),
                            timeout=2) as s:
                        f = s.makefile("rw")
                        f.write(json.dumps({"m": "ping"}) + "\n")
                        f.flush()
                        return json.loads(f.readline())
                except OSError:
                    return {}

            def sample():
                t00 = time.perf_counter()
                last = 0
                while not stop_sampler:
                    time.sleep(5.0)
                    now = sum(len(c.replies) for c in drv.clients)
                    views = []
                    for p in dports:
                        r = ping(p)
                        st = r.get("stats", {})
                        views.append(
                            f"f={r.get('frontier')} c={r.get('crt_inst')}"
                            f" t={st.get('ticks')} x={st.get('executed')}")
                    _progress(f"  +{time.perf_counter()-t00:5.0f}s "
                              f"acked={now} (+{now-last}) | "
                              + " | ".join(views))
                    last = now

            smp = threading.Thread(target=sample, daemon=True)
            smp.start()
            try:
                t0 = time.perf_counter()
                stats = drv.run_workload(ops, keys, vals, timeout_s=120)
                wall = time.perf_counter() - t0
                stop_sampler.append(1)
            finally:
                try:
                    drv.close()
                except Exception:
                    pass
            _progress(f"trial {t}: {stats['acked']}/{q} acked, "
                      f"{round(stats['acked']/wall, 1)} ops/s, "
                      f"missing={stats.get('missing')}")
            if stats["acked"] != q:
                _progress(f"FAILURE at trial {t}: {stats}")
                break
    finally:
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
        time.sleep(1.0)
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
    for f in sorted(tmp.glob("*.err")):
        tail = f.read_text()[-2000:]
        if tail.strip():
            print(f"==== {f.name} ====\n{tail}")


if __name__ == "__main__":
    main()
