"""Shape-ladder autotuner: find the throughput-optimal (shards x
window x proposals x k) point for the device-resident consensus loop.

The bench's shapes were hand-picked for SURVIVAL (the biggest shape a
fragile remote worker boots), not throughput. This tool replaces that
guess with a measurement: it runs the resident fused loop
(parallel/sharded.py ``sharded_run_resident``) over a small grid of
(g, w, p, k) points per protocol, times a few back-to-back dispatches
at each, verifies every point drains exactly (assigned == committed,
the latency-accounting contract), and reports the winner. ``bench.py
--ladder`` consumes the JSON and measures its full record at the
winning point; the whole sweep lands in the bench artifact so a record
documents the alternatives its shape beat.

Grid design (PR 8 ablation, PERF.md): commits/round are capped by p
(proposal rows per shard per round) but only while the window stays >=
~4x p deep (the commit pipeline is 3 deliveries); inbox capacity costs
~50 us/row/round on the measured CPU host, so catchup_rows uses
economy sizing p/4 instead of a fixed 128 (ladder points skip the
bench's fault leg; sizing policy is imported from bench.py so the
winner re-measures under exactly the sweep's config — key space and
KV capacity scale with p, keeping the stride-walk keys
duplicate-free at every point); and shard counts beyond the device
count only dilute one core's time, so g sweeps {1, device_count}
with the shard axis meshed over real devices when there is more than
one.

Budget: points are measured best-first under ``--budget-s``; points
dropped for budget are LISTED in the output (never silently) and the
already-measured prefix still yields a winner.

    JAX_PLATFORMS=cpu python tools/shape_ladder.py [--json out.json]
    python tools/shape_ladder.py --smoke   # 2 tiny points, CI gate
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the sweep is CPU-friendly by default; let an operator pin the
# backend exactly as for the other tools
import jax  # noqa: E402
import numpy as np  # noqa: E402

# sizing policy is SHARED with bench.py (single definition): the
# measured winner must re-run under exactly the config that won the
# sweep — catch-up/inbox rows, key space, AND KV capacity. Ladder
# points use the economy (fault=False) catch-up sizing; the bench's
# kill/recover leg runs at its default shape with fault-viable sizing.
from bench import cpu_catchup_rows, cpu_key_space, cpu_kv_pow2  # noqa: E402
from minpaxos_tpu.models.minpaxos import MinPaxosConfig  # noqa: E402
from minpaxos_tpu.models.paxos import classic_config  # noqa: E402
from minpaxos_tpu.parallel import make_mesh  # noqa: E402
from minpaxos_tpu.parallel.sharded import ShardedCluster  # noqa: E402


def point_config(protocol: str, w: int, p: int, inbox: int | None = None,
                 compact: int = 0, q1: int = 0, q2: int = 0) -> MinPaxosConfig:
    cu = cpu_catchup_rows(p, fault=False)
    kw = dict(n_replicas=5, window=w, inbox=p + 2 * cu + 64 + 64,
              exec_batch=p, kv_pow2=cpu_kv_pow2(p), catchup_rows=cu,
              recovery_rows=64, compact_inbox=compact, q1=q1, q2=q2)
    if protocol == "classic":
        if inbox is not None:
            kw["inbox"] = inbox
        return classic_config(**kw)
    if protocol == "mencius":
        # per-step commit-broadcast chunk must beat the per-owner
        # proposal rate (bench.py mencius side config rationale)
        kw["catchup_rows"] = max(kw["catchup_rows"], 2 * p)
        kw["inbox"] = max(kw["inbox"], 4 * p)
        kw["noop_delay"] = 8
    if inbox is not None:
        kw["inbox"] = inbox
    return MinPaxosConfig(**kw)


def adaptive_capacity(hwm: int) -> int:
    """Occupancy-derived inbox capacity: the measured delivered-rows
    high-water mark (paxray TEL_INBOX_HWM) plus 25% headroom, rounded
    up to 32 rows. Both the routing capacity (cfg.inbox) and the
    compacted kernel inbox (cfg.compact_inbox) take this one number —
    below it a point LOSES proposals, which the lossless check
    rejects."""
    return max(64, ((hwm + hwm // 4 + 8 + 31) // 32) * 32)


def measure_point(protocol: str, g: int, w: int, p: int, k: int,
                  dispatches: int = 3, key_space: int | None = None,
                  shard_devices: int = 1, seed: int = 0,
                  inbox: int | None = None, compact: int = 0,
                  q1: int = 0, q2: int = 0) -> dict:
    """Time the resident loop at one (g, w, p, k) point: warm one
    dispatch, run ``dispatches`` back-to-back (two-scalar readbacks
    only), then drain and REQUIRE exactness (in-flight == 0) — a point
    that cannot drain is not a legal operating point, however fast.

    The paxray telemetry ring rides every point; the post-window
    readback (the sanctioned once-after-the-measured-window path)
    yields the point's delivered-occupancy high-water mark
    (``occupancy_hwm``), which seeds the adaptive-capacity axis —
    ``inbox``/``compact`` override the default capacity with an
    occupancy-derived one. ``lossless`` pins that no proposal was
    dropped (total commits == total injected; minpaxos/classic only —
    Mencius frontiers count SKIP no-op slots, so drained_exact is its
    contract)."""
    cfg = point_config(protocol, w, p, inbox=inbox, compact=compact,
                       q1=q1, q2=q2)
    if key_space is None:
        key_space = cpu_key_space(p)
    mesh = None
    if shard_devices > 1:
        mesh = make_mesh(n_shard_devices=shard_devices,
                         n_replica_devices=1)
    t_build = time.perf_counter()
    sc = ShardedCluster(cfg, g, ext_rows=p, mesh=mesh, protocol=protocol,
                        key_space=key_space, seed=seed)
    if protocol != "mencius":
        sc.elect(0)
    # ring sized for every round the point can run (warm + baseline +
    # measured + drain) so the readback never wraps
    sc.begin_resident(telemetry_rounds=(2 + dispatches + 8) * k)
    sc.run_resident(k, p)  # warm/compile
    compile_s = time.perf_counter() - t_build
    c0, _ = sc.run_resident(k, p)
    t0 = time.perf_counter()
    committed = c0
    for _ in range(dispatches):
        committed, _ = sc.run_resident(k, p)
    wall = time.perf_counter() - t0
    measured = committed - c0  # commits inside the timed window only
    in_flight = None
    total = committed
    drain_dispatches = 0
    for _ in range(8):
        total, in_flight = sc.run_resident(k, 0)
        drain_dispatches += 1
        if in_flight == 0:
            break
    from minpaxos_tpu.obs.recorder import TEL_INBOX_HWM

    tel = sc.resident_telemetry()
    hwm = int(tel[:, TEL_INBOX_HWM].max()) if len(tel) else 0
    hist = sc.end_resident()
    injected = (2 + dispatches) * k * p * g * (
        cfg.n_replicas if protocol == "mencius" else 1)
    return {
        "protocol": protocol,
        "g": g, "w": w, "p": p, "k": k,
        "shard_devices": shard_devices,
        # resolved flexible-quorum sizes (PR 16): default = majority
        "q1": cfg.quorum1,
        "q2": cfg.quorum2,
        "catchup_rows": cfg.catchup_rows,
        "inbox": cfg.inbox,
        "compact_inbox": cfg.compact_inbox,
        "adaptive": inbox is not None or compact > 0,
        "inst_per_sec": round(measured / wall, 1),
        "ms_per_round": round(wall / (dispatches * k) * 1e3, 3),
        "committed": int(measured),
        "committed_total": int(total),
        "drained_exact": in_flight == 0,
        "occupancy_hwm": hwm,
        # every injected proposal committed. Points can fail this for a
        # NON-capacity reason: deep-pipeline shapes (w = 4p) bounce a
        # slice of proposals off the full window at ANY capacity — the
        # PR-8/9 grid always had that; only capacity-ATTRIBUTABLE loss
        # (adaptive total < the same point's base total) disqualifies,
        # see _legal
        "lossless": (None if protocol == "mencius"
                     else int(total) == injected),
        "latency_samples": int(hist.sum()),
        "compile_s": round(compile_s, 1),
    }


def default_grid(protocol: str, device_count: int) -> list[tuple]:
    """(g, w, p, k, shard_devices) points, best-guess-first so a tight
    budget still measures the likely winners."""
    d = max(1, device_count)
    pts: list[tuple] = []
    for p in (1024, 512, 256):
        for g, sd in ([(d, d)] if d > 1 else []) + [(1, 1)]:
            pts.append((g, 4 * p, p, 8, sd))
    # k sensitivity at the expected winner
    pts.append((d if d > 1 else 1, 4096, 1024, 16, d))
    # the PR-7 hand-picked survival shape, as the sweep's own baseline
    pts.append((8, 512, 64, 8, 1))
    return pts


SMOKE_POINT = (1, 128, 16, 2, 1)  # base; the 2nd smoke point derives
# its capacity from this one's measured occupancy (same 2-compile
# budget as the original fixed pair — no new compiled gate variant)


def _legal(r: dict) -> bool:
    """A crownable point: drains exactly, no error — and an ADAPTIVE
    point must not have lost proposals to its capacity choice: either
    absolutely lossless, or (deep-pipeline shapes that bounce
    proposals off the full window at any capacity) committing exactly
    what its own base-capacity run committed (``lossless_vs_base``,
    stamped by the sweep). Base points keep the PR-8/9 bar."""
    if not (bool(r.get("drained_exact")) and not r.get("error")):
        return False
    if not r.get("adaptive"):
        return True
    return bool(r.get("lossless")) or bool(r.get("lossless_vs_base"))


def sweep(protocol: str = "minpaxos", budget_s: float = 900.0,
          points: list[tuple] | None = None, dispatches: int = 3,
          seed: int = 0, adaptive: bool = True) -> dict:
    """Measure the grid, then — ``adaptive`` — re-measure the best
    base point with its inbox capacity derived from the MEASURED
    occupancy high-water mark (telemetry TEL_INBOX_HWM ->
    ``adaptive_capacity``) and the kernel inbox compacted to the same
    rows (cfg.compact_inbox). The swept axis the PR-11 tentpole adds:
    branch-free kernels cost ∝ capacity, so occupancy-fit capacity is
    a direct throughput lever; a lossy point (dropped proposals) is
    rejected by ``_legal``."""
    t_start = time.perf_counter()
    grid = points if points is not None else default_grid(
        protocol, jax.device_count())
    results, dropped = [], []

    def run_point(g, w, p, k, sd, inbox=None, compact=0, derived=None,
                  q1=0, q2=0):
        try:
            rec = measure_point(protocol, g, w, p, k,
                                dispatches=dispatches, shard_devices=sd,
                                seed=seed, inbox=inbox, compact=compact,
                                q1=q1, q2=q2)
        except Exception as e:  # noqa: BLE001 — a too-big point must
            # not kill the sweep; the failure is recorded, not hidden
            rec = {"protocol": protocol, "g": g, "w": w, "p": p, "k": k,
                   "shard_devices": sd, "q1": q1, "q2": q2,
                   "error": repr(e)[:200]}
        if derived is not None:
            rec["derived_from_hwm"] = derived
        results.append(rec)
        print(f"[ladder] {rec}", file=sys.stderr, flush=True)
        return rec

    for pt in grid:
        g, w, p, k, sd = pt
        if time.perf_counter() - t_start > budget_s and results:
            dropped.append(list(pt))
            continue
        run_point(g, w, p, k, sd)
    if adaptive:
        base_legal = [r for r in results if _legal(r)
                      and r.get("occupancy_hwm", 0) > 0]
        if base_legal and time.perf_counter() - t_start <= budget_s:
            best = max(base_legal, key=lambda r: r["inst_per_sec"])
            cap = adaptive_capacity(best["occupancy_hwm"])
            if cap < best["inbox"] + best["p"]:  # else nothing to gain
                rec = run_point(best["g"], best["w"], best["p"],
                                best["k"], best["shard_devices"],
                                inbox=cap, compact=cap,
                                derived=best["occupancy_hwm"])
                # capacity-attributable loss check: same workload
                # schedule as the base run, so equal committed totals
                # mean the tighter capacity dropped nothing even on
                # shapes that bounce proposals off the window
                if rec.get("committed_total") == best.get(
                        "committed_total"):
                    rec["lossless_vs_base"] = True
        elif base_legal:
            dropped.append(["adaptive", "budget"])

    # flexible-quorum sweep (PR 16): re-measure the crowned SHAPE at
    # every other certified (q1, q2) pair for n=5 (the ledger rows in
    # analysis/quorum_golden.GOLDEN_THRESHOLDS — each satisfies
    # q1 + q2 > n, verify/quorum.py). Smaller q2 means fewer ACCEPT
    # votes per commit scan; q1 grows to compensate. Every pair bakes
    # new kernel thresholds (a fresh compile), so the stage is
    # budget-guarded and only runs on the already-measured winner.
    legal = [r for r in results if _legal(r)]
    shape_winner = (max(legal, key=lambda r: r["inst_per_sec"])
                    if legal else None)
    quorum_results: list[dict] = []
    if shape_winner is not None:
        from minpaxos_tpu.analysis.quorum_golden import GOLDEN_THRESHOLDS

        n = 5  # point_config pins n_replicas=5
        default_pair = (n // 2 + 1, n // 2 + 1)
        sw = shape_winner
        for pair in GOLDEN_THRESHOLDS[n]:
            if pair == default_pair:
                continue  # the base grid already measured majority
            if time.perf_counter() - t_start > budget_s:
                dropped.append(["quorum", list(pair)])
                continue
            rec = run_point(
                sw["g"], sw["w"], sw["p"], sw["k"], sw["shard_devices"],
                inbox=sw["inbox"] if sw.get("adaptive") else None,
                compact=sw.get("compact_inbox", 0),
                q1=pair[0], q2=pair[1])
            # same workload schedule as the winner's run: equal
            # committed totals mean the pair dropped nothing
            if rec.get("committed_total") == sw.get("committed_total"):
                rec["lossless_vs_base"] = True
            quorum_results.append(rec)
    legal = [r for r in results if _legal(r)]
    winner = max(legal, key=lambda r: r["inst_per_sec"]) if legal else None
    # best point across the default-quorum shape winner and every
    # legal flexible pair — the artifact's quorum-sweep verdict
    q_pool = ([shape_winner] if shape_winner is not None else []) + [
        r for r in quorum_results if _legal(r)]
    quorum_winner = (max(q_pool, key=lambda r: r["inst_per_sec"])
                     if q_pool else None)
    return {
        "protocol": protocol,
        "backend": jax.devices()[0].platform,
        "device_count": jax.device_count(),
        "budget_s": budget_s,
        "points": results,
        "dropped_for_budget": dropped,
        "winner": winner,
        "quorum_sweep": quorum_results,
        "quorum_winner": quorum_winner,
    }


def smoke() -> int:
    """CI gate (tools/run_tier1.sh): two tiny points through the full
    resident path — a fixed base point, then a g=2 point whose inbox
    capacity is DERIVED from the base point's measured occupancy
    high-water mark with the kernel inbox compacted to it (the PR-11
    adaptive-capacity path). Contract: commits flow, every point
    drains exactly, the adaptive point is LOSSLESS (occupancy-fit
    capacity dropped nothing), and the latency sample is complete.
    Still exactly two compiled dispatch variants; budget <=60s after
    compile."""
    t0 = time.perf_counter()
    g, w, p, k, sd = SMOKE_POINT

    def _point(*a, **kw):
        # same containment contract as sweep()'s run_point: a point
        # that throws becomes a FAIL-able error record, not a raw
        # traceback that skips the gate's diagnostics
        try:
            return measure_point(*a, **kw)
        except Exception as e:  # noqa: BLE001
            return {"error": repr(e)[:200]}

    points = [_point("minpaxos", g, w, p, k, dispatches=2,
                     shard_devices=sd)]
    base = points[0]
    ok = True
    if not base.get("error") and base.get("occupancy_hwm", 0) > 0:
        cap = adaptive_capacity(base["occupancy_hwm"])
        points.append(_point("minpaxos", 2, w, p, k, dispatches=2,
                             shard_devices=sd, inbox=cap,
                             compact=cap))
    else:
        print(f"FAIL: base point unusable (no occupancy readback): {base}")
        ok = False
    wall = time.perf_counter() - t0
    for r in points:
        if r.get("error") or not r.get("drained_exact"):
            print(f"FAIL: ladder point did not drain exactly: {r}")
            ok = False
            continue
        if r["committed"] <= 0 or r["latency_samples"] <= 0:
            print(f"FAIL: ladder point made no progress: {r}")
            ok = False
        if r.get("lossless") is False:
            print(f"FAIL: point dropped proposals (capacity below "
                  f"occupancy): {r}")
            ok = False
    winner = max([r for r in points if _legal(r)],
                 key=lambda r: r["inst_per_sec"], default=None)
    if winner is None:
        print("FAIL: no legal winner among smoke points")
        ok = False
    post_compile = wall - sum(r.get("compile_s", 0) for r in points)
    if ok:
        adapt = points[1]
        print(f"shape-ladder smoke: {len(points)} points, winner "
              f"g={winner['g']} w={winner['w']} p={winner['p']} "
              f"k={winner['k']} ({winner['inst_per_sec']:.0f} inst/s); "
              f"adaptive point: hwm={base['occupancy_hwm']} -> "
              f"inbox={adapt['inbox']} (compacted, was "
              f"{base['inbox']}+{p} ext), lossless+drain-exact; "
              f"{wall:.1f}s wall ({post_compile:.1f}s post-compile)")
    else:
        print("shape-ladder smoke: FAILED")
    return 0 if ok else 1


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--protocol", default="minpaxos",
                    choices=("minpaxos", "classic", "mencius"))
    ap.add_argument("--budget-s", type=float, default=900.0)
    ap.add_argument("--dispatches", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", default=None,
                    help="write the sweep record to this path")
    ap.add_argument("--smoke", action="store_true",
                    help="2-point tiny-shape CI gate (run_tier1.sh)")
    args = ap.parse_args()
    if args.smoke:
        return smoke()
    rec = sweep(args.protocol, args.budget_s, dispatches=args.dispatches,
                seed=args.seed)
    out = json.dumps(rec, indent=2)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
    print(out)
    if rec["winner"] is None:
        print("no legal (exactly-drained) point measured", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
