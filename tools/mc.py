#!/usr/bin/env python
"""paxmc CLI — bounded model checking of the consensus kernels.

    tools/mc.py                         # all 3 protocols, smoke bounds
    tools/mc.py --smoke                 # CI gate: fixed bounds + seeded
                                        # mutant self-test, 60 s budget,
                                        # MC.json artifact (run_tier1.sh)
    tools/mc.py --protocol mencius --depth 6 --cmds 2
    tools/mc.py --mutant broken-quorum  # seeded non-intersecting quorum:
                                        # exit 0 iff the split-brain
                                        # counterexample IS found
    tools/mc.py --replay tests/fixtures/mc_broken_quorum_minpaxos.json
    tools/mc.py --emit-faultplan ce.json > plan.json
    tools/mc.py --certify 5,4,2         # quorum certificate + ledger line
    tools/mc.py --print-quorum-golden   # re-verified certified ledger

Exit status: 0 = verified clean (or, in --mutant/--replay mode, the
expected counterexample found/reproduced), 1 = violation, undrained
frontier, or budget exceeded, 2 = usage error.

The checker drives the REAL step functions (models/minpaxos.py,
models/mencius.py) through every bounded interleaving of a 3-replica
cluster — per-link FIFO delivery, drops, duplications, internal
ticks, a concurrent second election — and holds every reached state
to the same invariant predicates the chaos campaigns run against live
clusters (verify/invariants.py). See VERIFY.md for the state-space
model, the invariant catalogue, and the counterexample-replay
workflow.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

#: the tier-1 smoke legs: per-protocol bounds measured to drain well
#: inside the budget on the 1-core CI host (see VERIFY.md for the
#: state counts each leg certifies)
SMOKE_BUDGET_S = 60.0


def _smoke_legs():
    from minpaxos_tpu.verify.mc import Bounds

    # leg 1 (first = budget-excluded, like the chaos smoke): the full
    # gauntlet — depth 5, one drop, one dup, a concurrent second
    # election. Leg 2 re-runs the SAME kernel in explicit-commit mode
    # without the election budget (that machinery is shared and was
    # exhausted in leg 1); leg 3 gives Mencius two concurrent owners
    # (the SKIP/cede interleavings that are its novel risk) at depth 4.
    # Leg 4 is the FLEXIBLE-quorum leg (ISSUE 16): q1=3/q2=1 at N=3 —
    # a unanimous phase 1 buying single-ack commits, the extreme
    # certified point of the q1+q2>N family — one drop, no election
    # budget (a q1=3 re-election can't complete inside these depths
    # anyway). Sized so legs 2+3+4+mutants stay well under the budget
    # even at the 1-core host's slow-tide speeds (VERIFY.md).
    # Legs are (label, protocol, bounds, explorer_kwargs).
    minpaxos = Bounds(max_depth=5, drops=1, dups=1, internal=1,
                      elections=1, electable=(1,), n_cmds=2,
                      propose_to=(0,))
    classic = Bounds(max_depth=5, drops=1, dups=1, internal=1,
                     elections=0, n_cmds=2, propose_to=(0,))
    mencius = Bounds(max_depth=4, drops=1, dups=1, internal=1,
                     elections=0, n_cmds=1, propose_to=(0, 1))
    flex = Bounds(max_depth=5, drops=1, dups=0, internal=1,
                  elections=0, n_cmds=2, propose_to=(0,))
    return [("minpaxos", "minpaxos", minpaxos, {}),
            ("classic", "classic", classic, {}),
            ("mencius", "mencius", mencius, {}),
            ("minpaxos-flex-q1=3-q2=1", "minpaxos", flex,
             {"q1": 3, "q2": 1})]


def _mutant_bounds():
    from minpaxos_tpu.verify.mc import Bounds

    # two drops + both ingress queues: enough schedule freedom for the
    # two-leaders split-brain to appear within depth 6
    return Bounds(max_depth=6, drops=2, dups=0, internal=1, elections=1,
                  electable=(1,), n_cmds=2, propose_to=(0, 1))


#: the planted non-intersecting FLEXIBLE pair (q1 + q2 = 3 <= N = 3):
#: q1=2 lets a second leader elect off one reply while q2=1 commits on
#: a leader's own accept — both ingress queues + one election is all
#: the schedule freedom the split-brain needs
FLEX_MUTANT = {"q1": 2, "q2": 1}


def _flex_mutant_bounds():
    from minpaxos_tpu.verify.mc import Bounds

    # no drops or ticks needed: the two leaders never lose a frame,
    # they just commit slot 0 from different ingress queues before
    # hearing each other — commit at 0, elect 1 off replica 2's reply
    # (its PREPARE_REPLY precedes the ACCEPT in no FIFO order), commit
    # again at 1. The known counterexample is 8 deliveries deep
    # (tests/fixtures/mc_flex_broken_minpaxos.json)
    return Bounds(max_depth=8, drops=0, dups=0, internal=0, elections=1,
                  electable=(1,), n_cmds=2, propose_to=(0, 1))


def _flex_certified_runs(log=print):
    """One bounded exploration per certified (q1, q2) ledger pair at
    N=3..5 (GOLDEN_THRESHOLDS), minpaxos kernel: BFS must drain with 0
    violations for every pair. Bounds shrink with N (the link count
    grows the branching factor) — each leg still reaches commits for
    the small-q2 pairs, and every reached state is invariant-checked."""
    from minpaxos_tpu.analysis.quorum_golden import GOLDEN_THRESHOLDS
    from minpaxos_tpu.verify.mc import Bounds, Explorer

    runs = []
    for n in (3, 4, 5):
        b = Bounds(max_depth=5 if n == 3 else 4,
                   drops=1 if n == 3 else 0, dups=0,
                   internal=1 if n == 3 else 0, elections=0,
                   n_cmds=2 if n == 3 else 1, propose_to=(0,))
        for q1, q2 in GOLDEN_THRESHOLDS.get(n, ()):
            log(f"[paxmc] flex-certified: n={n} q1={q1} q2={q2} "
                f"(depth {b.max_depth}) ...")
            res = Explorer("minpaxos", b, q1=q1, q2=q2,
                           n_replicas=n).run()
            runs.append(res)
            log(f"[paxmc]   -> {'ok' if res.ok else 'VIOLATION'} "
                f"states={res.states} drained={res.drained} "
                f"wall={res.wall_s:.1f}s")
    return runs


def _print_quorum_golden() -> int:
    """Re-verify and emit the certified ledger (the quorum twin of
    ``lint.py --print-wire-golden``)."""
    from minpaxos_tpu.analysis.quorum_golden import (
        GOLDEN_GRIDS, GOLDEN_MAX_N, GOLDEN_THRESHOLDS)
    from minpaxos_tpu.verify.quorum import (
        certify_grid, certify_threshold, verify_certificate)

    bad = 0
    print("GOLDEN_THRESHOLDS: dict[int, tuple[tuple[int, int], ...]] = {")
    for n in range(1, GOLDEN_MAX_N + 1):
        pairs = GOLDEN_THRESHOLDS.get(n, ())
        verified = []
        for q1, q2 in pairs:
            cert = certify_threshold(n, q1, q2)
            if cert.intersects and verify_certificate(cert):
                verified.append((q1, q2))
            else:
                bad += 1
                print(f"    # DROPPED (fails to prove): ({q1}, {q2})")
        print(f"    {n}: {tuple(verified)!r},")
    print("}")
    print("GOLDEN_GRIDS = (")
    for rows, cols, q1, q2 in GOLDEN_GRIDS:
        cert = certify_grid(rows, cols, q1, q2)
        if cert.intersects and verify_certificate(cert):
            print(f"    ({rows}, {cols}, {q1!r}, {q2!r}),")
        else:
            bad += 1
            print(f"    # DROPPED (fails to prove): ({rows}, {cols}, "
                  f"{q1!r}, {q2!r})")
    print(")")
    return 1 if bad else 0


def _certify(spec: str) -> int:
    from minpaxos_tpu.verify.quorum import (
        certify_threshold, verify_certificate)

    try:
        n, q1, q2 = (int(x) for x in spec.split(","))
        cert = certify_threshold(n, q1, q2)
    except ValueError as e:
        print(f"bad --certify spec {spec!r}: {e}", file=sys.stderr)
        return 2
    print(json.dumps(cert.to_dict(), indent=1))
    if cert.intersects and verify_certificate(cert):
        print(f"# certified — ledger line for GOLDEN_THRESHOLDS[{n}]: "
              f"({q1}, {q2})")
        return 0
    print("# REFUTED — do NOT add to the ledger; the witness above is "
          "a split-brain schedule seed")
    return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "paxmc", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: fixed bounds, mutant self-test, "
                        f"{SMOKE_BUDGET_S:.0f} s budget, MC.json")
    p.add_argument("--protocol", default="all",
                   help="minpaxos | classic | mencius | all")
    p.add_argument("--depth", type=int, default=None)
    p.add_argument("--cmds", type=int, default=None)
    p.add_argument("--drops", type=int, default=None)
    p.add_argument("--dups", type=int, default=None)
    p.add_argument("--reorders", type=int, default=None)
    p.add_argument("--internal", type=int, default=None)
    p.add_argument("--mutant", choices=["broken-quorum", "flex-broken"],
                   default=None,
                   help="seeded mutant: 'broken-quorum' forces the "
                        "threshold to 1 via the property override; "
                        "'flex-broken' plants the non-intersecting "
                        f"flexible pair {FLEX_MUTANT} through the real "
                        "cfg.q1/cfg.q2 fields. Exit 0 iff the "
                        "counterexample is found and replays")
    p.add_argument("--q1", type=int, default=0,
                   help="flexible phase-1 quorum (0 = majority)")
    p.add_argument("--q2", type=int, default=0,
                   help="flexible phase-2 quorum (0 = majority)")
    p.add_argument("--n", type=int, default=3, help="model replicas")
    p.add_argument("--flex-certified", action="store_true",
                   help="explore every certified GOLDEN_THRESHOLDS "
                        "(q1,q2) pair at N=3..5 (minpaxos); exit 0 iff "
                        "all drain with 0 violations")
    p.add_argument("--replay", default=None, metavar="CE_JSON",
                   help="replay a counterexample trace; exit 0 iff the "
                        "violation reproduces")
    p.add_argument("--emit-trace", default="", metavar="FILE",
                   help="write the first counterexample (JSON) here")
    p.add_argument("--emit-faultplan", default=None, metavar="CE_JSON",
                   help="project a counterexample onto a chaos "
                        "FaultPlan schedule (stdout)")
    p.add_argument("--json", default="",
                   help="write the full verdict to this file")
    p.add_argument("--certify", default=None, metavar="N,Q1,Q2",
                   help="certify one threshold quorum pair and print "
                        "the ledger line")
    p.add_argument("--print-quorum-golden", action="store_true",
                   help="emit the re-verified certified quorum ledger")
    args = p.parse_args(argv)

    if args.print_quorum_golden:
        return _print_quorum_golden()
    if args.certify:
        return _certify(args.certify)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from minpaxos_tpu.utils.backend import enable_compile_cache

    enable_compile_cache()

    from minpaxos_tpu.verify.mc import (
        PROTOCOLS,
        Explorer,
        counterexample_faultplan,
        replay_counterexample,
    )

    if args.emit_faultplan:
        ce = json.loads(Path(args.emit_faultplan).read_text())
        print(json.dumps(counterexample_faultplan(ce), indent=1))
        return 0

    if args.replay:
        ce = json.loads(Path(args.replay).read_text())
        reproduced, report = replay_counterexample(ce)
        print(json.dumps({"reproduced": reproduced,
                          "report": report.to_dict()}, indent=1))
        return 0 if reproduced else 1

    def override(b):
        kw = {}
        for name, val in (("max_depth", args.depth), ("n_cmds", args.cmds),
                          ("drops", args.drops), ("dups", args.dups),
                          ("reorders", args.reorders),
                          ("internal", args.internal)):
            if val is not None:
                kw[name] = val
        from dataclasses import replace
        return replace(b, **kw) if kw else b

    if args.flex_certified:
        runs = _flex_certified_runs()
        ok = all(r.ok and r.drained for r in runs)
        verdict = {"ok": ok, "flex_certified": True,
                   "runs": [r.to_dict() for r in runs]}
        print(f"[paxmc] flex-certified verdict: "
              f"{json.dumps({'ok': ok, 'pairs': len(runs)})}", flush=True)
        if args.json:
            Path(args.json).write_text(json.dumps(verdict, indent=1))
        return 0 if ok else 1

    if args.mutant:
        proto = "minpaxos" if args.protocol == "all" else args.protocol
        if args.mutant == "flex-broken":
            b = override(_flex_mutant_bounds())
            res = Explorer(proto, b, **FLEX_MUTANT).run(log=print)
        else:
            b = override(_mutant_bounds())
            res = Explorer(proto, b, majority_override=1).run(log=print)
        found = res.counterexample is not None
        line = {"mutant": args.mutant, "protocol": proto,
                "counterexample_found": found, "states": res.states,
                "wall_s": round(res.wall_s, 1)}
        if found:
            reproduced, _rep = replay_counterexample(
                res.counterexample.to_dict())
            line["replay_reproduced"] = reproduced
            if args.emit_trace:
                Path(args.emit_trace).write_text(
                    json.dumps(res.counterexample.to_dict(), indent=1))
                line["trace"] = args.emit_trace
        print(f"[paxmc] {json.dumps(line)}", flush=True)
        if args.json:
            verdict = dict(line, result=res.to_dict())
            Path(args.json).write_text(json.dumps(verdict, indent=1))
        return 0 if found and line.get("replay_reproduced") else 1

    # ------------------------------------------------ verification runs
    legs = _smoke_legs()
    if args.protocol != "all":
        if args.protocol not in PROTOCOLS:
            p.error(f"unknown protocol {args.protocol!r}")
        legs = [l for l in legs if l[1] == args.protocol]
    if args.q1 or args.q2 or args.n != 3:
        # ad-hoc flexible run: one leg per selected protocol at the
        # requested (n, q1, q2)
        legs = [(f"{label}-n={args.n}-q1={args.q1}-q2={args.q2}", proto,
                 b, dict(kw, q1=args.q1, q2=args.q2, n_replicas=args.n))
                for label, proto, b, kw in legs[:1]] or legs
    legs = [(label, proto, override(b), kw)
            for label, proto, b, kw in legs]

    t_start = time.monotonic()
    t_budget = None
    runs = []
    ok = True
    for label, proto, b, kw in legs:
        print(f"[paxmc] exploring {label} (depth {b.max_depth}, "
              f"{b.n_cmds} cmds, drops {b.drops}, dups {b.dups}) ...",
              flush=True)
        res = Explorer(proto, b, **kw).run(log=print)
        if t_budget is None:
            t_budget = time.monotonic()  # first run covered jit compile
        runs.append(res)
        ok = ok and res.ok and res.drained
        print(f"[paxmc]   -> {'ok' if res.ok else 'VIOLATION'} "
              f"states={res.states} transitions={res.transitions} "
              f"drained={res.drained} wall={res.wall_s:.1f}s", flush=True)
        if res.counterexample is not None and args.emit_trace:
            Path(args.emit_trace).write_text(
                json.dumps(res.counterexample.to_dict(), indent=1))
            print(f"[paxmc] counterexample written to {args.emit_trace}",
                  flush=True)

    verdict = {"ok": ok, "runs": [r.to_dict() for r in runs],
               "wall_s": round(time.monotonic() - t_start, 2)}

    if args.smoke:
        # seeded-mutant self-test: a checker that cannot find a planted
        # non-intersecting quorum certifies nothing
        res = Explorer("minpaxos", _mutant_bounds(),
                       majority_override=1).run()
        found = res.counterexample is not None
        reproduced = found and replay_counterexample(
            res.counterexample.to_dict())[0]
        verdict["mutant_self_test"] = {
            "found": found, "replay_reproduced": reproduced,
            "states": res.states, "wall_s": round(res.wall_s, 1),
            "trace_len": (len(res.counterexample.trace) if found else 0)}
        ok = ok and found and reproduced
        # same contract for the FLEXIBLE mutant: the planted
        # non-intersecting (q1, q2) pair — through the real config
        # fields, not the property override — must also be found and
        # replayed, or the flexible legs above prove nothing
        fres = Explorer("minpaxos", _flex_mutant_bounds(),
                        **FLEX_MUTANT).run()
        ffound = fres.counterexample is not None
        freproduced = ffound and replay_counterexample(
            fres.counterexample.to_dict())[0]
        verdict["flex_mutant_self_test"] = {
            "q1": FLEX_MUTANT["q1"], "q2": FLEX_MUTANT["q2"],
            "found": ffound, "replay_reproduced": freproduced,
            "states": fres.states, "wall_s": round(fres.wall_s, 1),
            "trace_len": (len(fres.counterexample.trace) if ffound else 0)}
        ok = ok and ffound and freproduced
        checked_wall = time.monotonic() - (t_budget or t_start)
        verdict["budget_s"] = SMOKE_BUDGET_S
        verdict["within_budget"] = checked_wall <= SMOKE_BUDGET_S
        if not verdict["within_budget"]:
            ok = False
        verdict["ok"] = ok
        verdict["wall_s"] = round(time.monotonic() - t_start, 2)
        # the committed MC.json artifact is regenerated explicitly via
        # `--smoke --json MC.json` (the CHAOS.json convention) — the
        # bare CI gate must not dirty the tree with fresh wall clocks
        # on every tier-1 run
        print(f"[paxmc] smoke verdict ready "
              f"(post-compile wall {checked_wall:.1f}s / budget "
              f"{SMOKE_BUDGET_S:.0f}s)", flush=True)

    line = {"ok": ok,
            "states": sum(r.states for r in runs),
            "transitions": sum(r.transitions for r in runs),
            "violations": sum(0 if r.ok else 1 for r in runs),
            "drained": all(r.drained for r in runs),
            "wall_s": verdict["wall_s"]}
    if args.smoke:
        line["mutant_self_test"] = verdict["mutant_self_test"]["found"]
        line["flex_mutant_self_test"] = (
            verdict["flex_mutant_self_test"]["found"])
    print(f"[paxmc] verdict: {json.dumps(line)}", flush=True)
    if args.json:
        Path(args.json).write_text(json.dumps(verdict, indent=1))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
