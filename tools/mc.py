#!/usr/bin/env python
"""paxmc CLI — bounded model checking of the consensus kernels.

    tools/mc.py                         # all 3 protocols, smoke bounds
    tools/mc.py --smoke                 # CI gate: fixed bounds + seeded
                                        # mutant self-test, 60 s budget,
                                        # MC.json artifact (run_tier1.sh)
    tools/mc.py --protocol mencius --depth 6 --cmds 2
    tools/mc.py --mutant broken-quorum  # seeded non-intersecting quorum:
                                        # exit 0 iff the split-brain
                                        # counterexample IS found
    tools/mc.py --replay tests/fixtures/mc_broken_quorum_minpaxos.json
    tools/mc.py --refine                # map every explored edge onto
                                        # the abstract spec (paxref)
    tools/mc.py --liveness              # eventual commit under weak
                                        # fairness (lasso/SCC search)
    tools/mc.py --refine --spec-pair 1,3
    tools/mc.py --mutant skip-quorum2   # commit below q2: refinement
                                        # CE found iff exit 0
    tools/mc.py --mutant dueling-leaders  # livelock: fair lasso
    tools/mc.py --emit-faultplan ce.json > plan.json
    tools/mc.py --certify 5,4,2         # quorum certificate + ledger line
    tools/mc.py --print-quorum-golden   # re-verified certified ledger

Exit status: 0 = verified clean (or, in --mutant/--replay mode, the
expected counterexample found/reproduced), 1 = violation, undrained
frontier, or budget exceeded, 2 = usage error.

The checker drives the REAL step functions (models/minpaxos.py,
models/mencius.py) through every bounded interleaving of a 3-replica
cluster — per-link FIFO delivery, drops, duplications, internal
ticks, a concurrent second election — and holds every reached state
to the same invariant predicates the chaos campaigns run against live
clusters (verify/invariants.py). See VERIFY.md for the state-space
model, the invariant catalogue, and the counterexample-replay
workflow.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

#: the tier-1 smoke legs: per-protocol bounds measured to drain well
#: inside the budget on the 1-core CI host (see VERIFY.md for the
#: state counts each leg certifies)
SMOKE_BUDGET_S = 60.0


def _smoke_legs():
    from minpaxos_tpu.verify.mc import Bounds

    # leg 1 (first = budget-excluded, like the chaos smoke): the full
    # gauntlet — depth 5, one drop, one dup, a concurrent second
    # election. Leg 2 re-runs the SAME kernel in explicit-commit mode
    # without the election budget (that machinery is shared and was
    # exhausted in leg 1); leg 3 gives Mencius two concurrent owners
    # (the SKIP/cede interleavings that are its novel risk) at depth 4.
    # Leg 4 is the FLEXIBLE-quorum leg (ISSUE 16): q1=3/q2=1 at N=3 —
    # a unanimous phase 1 buying single-ack commits, the extreme
    # certified point of the q1+q2>N family — one drop, no election
    # budget (a q1=3 re-election can't complete inside these depths
    # anyway). Sized so legs 2+3+4+mutants stay well under the budget
    # even at the 1-core host's slow-tide speeds (VERIFY.md).
    # Legs are (label, protocol, bounds, explorer_kwargs).
    minpaxos = Bounds(max_depth=5, drops=1, dups=1, internal=1,
                      elections=1, electable=(1,), n_cmds=2,
                      propose_to=(0,))
    classic = Bounds(max_depth=5, drops=1, dups=1, internal=1,
                     elections=0, n_cmds=2, propose_to=(0,))
    mencius = Bounds(max_depth=4, drops=1, dups=1, internal=1,
                     elections=0, n_cmds=1, propose_to=(0, 1))
    flex = Bounds(max_depth=5, drops=1, dups=0, internal=1,
                  elections=0, n_cmds=2, propose_to=(0,))
    return [("minpaxos", "minpaxos", minpaxos, {}),
            ("classic", "classic", classic, {}),
            ("mencius", "mencius", mencius, {}),
            ("minpaxos-flex-q1=3-q2=1", "minpaxos", flex,
             {"q1": 3, "q2": 1})]


def _mutant_bounds():
    from minpaxos_tpu.verify.mc import Bounds

    # two drops + both ingress queues: enough schedule freedom for the
    # two-leaders split-brain to appear within depth 6
    return Bounds(max_depth=6, drops=2, dups=0, internal=1, elections=1,
                  electable=(1,), n_cmds=2, propose_to=(0, 1))


#: the planted non-intersecting FLEXIBLE pair (q1 + q2 = 3 <= N = 3):
#: q1=2 lets a second leader elect off one reply while q2=1 commits on
#: a leader's own accept — both ingress queues + one election is all
#: the schedule freedom the split-brain needs
FLEX_MUTANT = {"q1": 2, "q2": 1}


def _flex_mutant_bounds():
    from minpaxos_tpu.verify.mc import Bounds

    # no drops or ticks needed: the two leaders never lose a frame,
    # they just commit slot 0 from different ingress queues before
    # hearing each other — commit at 0, elect 1 off replica 2's reply
    # (its PREPARE_REPLY precedes the ACCEPT in no FIFO order), commit
    # again at 1. The known counterexample is 8 deliveries deep
    # (tests/fixtures/mc_flex_broken_minpaxos.json)
    return Bounds(max_depth=8, drops=0, dups=0, internal=0, elections=1,
                  electable=(1,), n_cmds=2, propose_to=(0, 1))


#: the default flexible (q1, q2) pair for refinement/liveness legs —
#: the same certified extreme point the flex smoke leg drives
SPEC_PAIR = (3, 1)


def _refine_legs(pair=SPEC_PAIR):
    from minpaxos_tpu.verify.mc import Bounds

    # paxref refinement legs (ISSUE 17): every explored edge of each
    # run is mapped onto the abstract spec (verify/refine.py) — sized
    # so classic/mencius/flex reach Commit-labeled edges while the
    # whole block stays a small slice of the smoke budget (minpaxos
    # commits need depth 5; its depth-4 leg still certifies the
    # Phase1/Phase2 edge classes plus the election interleavings)
    minpaxos = Bounds(max_depth=4, drops=1, dups=0, internal=1,
                      elections=1, n_cmds=1, propose_to=(0,))
    classic = Bounds(max_depth=5, drops=1, dups=0, internal=1,
                     elections=0, n_cmds=1, propose_to=(0,))
    mencius = Bounds(max_depth=4, drops=1, dups=0, internal=1,
                     elections=0, n_cmds=1, propose_to=(0, 1))
    flex = Bounds(max_depth=4, drops=0, dups=0, internal=1,
                  elections=0, n_cmds=1, propose_to=(0,))
    q1, q2 = pair
    return [("refine-minpaxos", "minpaxos", minpaxos, {}),
            ("refine-classic", "classic", classic, {}),
            ("refine-mencius", "mencius", mencius, {}),
            (f"refine-minpaxos-flex-q1={q1}-q2={q2}", "minpaxos", flex,
             {"q1": q1, "q2": q2})]


def _run_refine(pair=SPEC_PAIR, log=print):
    """Run the refinement legs; every edge of every leg must map onto
    an abstract spec action (or a stutter) with zero violations."""
    from minpaxos_tpu.verify.refine import RefinementExplorer

    legs, ok = [], True
    for label, proto, b, kw in _refine_legs(pair):
        log(f"[paxmc] {label} (depth {b.max_depth}) ...", flush=True)
        ex = RefinementExplorer(proto, b, **kw)
        res = ex.run()
        stats = ex.refine_stats()
        ok = ok and res.ok and res.drained
        legs.append({
            "label": label, "ok": res.ok, "drained": res.drained,
            "states": res.states, "wall_s": round(res.wall_s, 2),
            "spec_q1": stats["spec_q1"], "spec_q2": stats["spec_q2"],
            "edges_checked": stats["edges_checked"],
            "abstract_actions": stats["abstract_actions"],
            "counterexample": (None if res.counterexample is None
                               else res.counterexample.to_dict())})
        log(f"[paxmc]   -> {'ok' if res.ok else 'VIOLATION'} "
            f"edges={stats['edges_checked']} "
            f"actions={stats['abstract_actions']} "
            f"wall={res.wall_s:.1f}s", flush=True)
    return {"ok": ok,
            "edges_checked": sum(l["edges_checked"] for l in legs),
            "legs": legs}


def _skip_quorum2_bounds():
    from minpaxos_tpu.verify.mc import Bounds

    # the planted early-commit mutant needs no faults at all: the
    # leader commits its own slot off a single vote three deliveries
    # in (tests/fixtures/mc_refine_skip_quorum2_minpaxos.json)
    return Bounds(max_depth=5, drops=0, dups=0, internal=1,
                  elections=0, n_cmds=1, propose_to=(0,))


def _refine_mutant_self_test(log=print):
    """A refinement checker that cannot catch a leader committing
    below q2 certifies nothing: plant skip-quorum2 and demand the
    commit-no-quorum counterexample is found AND replays. The mutant
    passes every safety invariant (only the leader commits early, so
    no two replicas disagree) — exactly the bug class refinement
    exists to catch."""
    from minpaxos_tpu.verify.mc import replay_counterexample
    from minpaxos_tpu.verify.refine import RefinementExplorer

    ex = RefinementExplorer("minpaxos", _skip_quorum2_bounds(),
                            mutant="skip-quorum2")
    res = ex.run()
    found = res.counterexample is not None
    reproduced = found and replay_counterexample(
        res.counterexample.to_dict())[0]
    log(f"[paxmc] refine-mutant skip-quorum2: found={found} "
        f"replayed={reproduced} states={res.states} "
        f"wall={res.wall_s:.1f}s", flush=True)
    return {"mutant": "skip-quorum2", "found": found,
            "replay_reproduced": reproduced, "states": res.states,
            "wall_s": round(res.wall_s, 1),
            "trace_len": (len(res.counterexample.trace) if found else 0),
            "counterexample": (res.counterexample.to_dict()
                               if found else None)}


def _run_liveness(pair=SPEC_PAIR, log=print):
    """Liveness legs: eventual commit under weak fairness for the
    default quorums and one certified flexible pair (minpaxos; classic
    explicit-commit traffic overflows the smoke-sized state cap and
    mencius liveness is deferred with its reconfiguration story)."""
    from minpaxos_tpu.verify.liveness import LivenessExplorer, fair_bounds

    q1, q2 = pair
    legs_spec = [("liveness-minpaxos-default", {}),
                 (f"liveness-minpaxos-flex-q1={q1}-q2={q2}",
                  {"q1": q1, "q2": q2})]
    legs, ok = [], True
    for label, kw in legs_spec:
        log(f"[paxmc] {label} ...", flush=True)
        r = LivenessExplorer("minpaxos", fair_bounds(n_cmds=1),
                             max_states=10_000, **kw).explore()
        ok = ok and r.ok
        legs.append(dict(r.to_dict(), label=label))
        log(f"[paxmc]   -> {'ok' if r.ok else 'FAIL'} states={r.states} "
            f"goal={r.goal_states} deadlocks={r.deadlocks} "
            f"lassos={r.fair_lassos} drained={r.drained} "
            f"wall={r.wall_s:.1f}s", flush=True)
    return {"ok": ok, "legs": legs}


def _lasso_mutant_self_test(log=print):
    """The liveness twin of the quorum mutants: plant dueling leaders
    (unbudgeted mutual preemption on replicas 0 and 1) and demand a
    fair lasso is found and its stem+cycle replays to the same
    quotient state with the command uncommitted."""
    from minpaxos_tpu.verify.liveness import (LivenessExplorer,
                                              dueling_bounds)
    from minpaxos_tpu.verify.mc import replay_counterexample

    r = LivenessExplorer("minpaxos", dueling_bounds(),
                         mutant="dueling-leaders", max_states=3000,
                         max_queue_rows=10).explore()
    found = r.fair_lassos > 0 and r.lasso is not None
    reproduced = found and replay_counterexample(r.lasso.to_dict())[0]
    log(f"[paxmc] liveness-mutant dueling-leaders: found={found} "
        f"replayed={reproduced} states={r.states} "
        f"lassos={r.fair_lassos} wall={r.wall_s:.1f}s", flush=True)
    return {"mutant": "dueling-leaders", "found": found,
            "replay_reproduced": reproduced, "states": r.states,
            "fair_lassos": r.fair_lassos, "wall_s": round(r.wall_s, 1),
            "trace_len": (len(r.lasso.trace) if found else 0),
            "loop_start": (r.lasso.loop_start if found else None),
            "counterexample": (r.lasso.to_dict() if found else None)}


def _flex_certified_runs(log=print):
    """One bounded exploration per certified (q1, q2) ledger pair at
    N=3..5 (GOLDEN_THRESHOLDS), minpaxos kernel: BFS must drain with 0
    violations for every pair. Bounds shrink with N (the link count
    grows the branching factor) — each leg still reaches commits for
    the small-q2 pairs. Since ISSUE 17 each run is a
    ``RefinementExplorer``: on top of the invariant suite, EVERY
    explored edge is held to the abstract spec parameterized by that
    ledger pair (verify/spec.py), so the certified sweep proves the
    kernels implement flexible Paxos — not merely that they avoid
    split-brain within these bounds."""
    from minpaxos_tpu.analysis.quorum_golden import GOLDEN_THRESHOLDS
    from minpaxos_tpu.verify.mc import Bounds
    from minpaxos_tpu.verify.refine import RefinementExplorer

    runs = []
    for n in (3, 4, 5):
        b = Bounds(max_depth=5 if n == 3 else 4,
                   drops=1 if n == 3 else 0, dups=0,
                   internal=1 if n == 3 else 0, elections=0,
                   n_cmds=2 if n == 3 else 1, propose_to=(0,))
        for q1, q2 in GOLDEN_THRESHOLDS.get(n, ()):
            log(f"[paxmc] flex-certified: n={n} q1={q1} q2={q2} "
                f"(depth {b.max_depth}) ...")
            ex = RefinementExplorer("minpaxos", b, q1=q1, q2=q2,
                                    n_replicas=n)
            res = ex.run()
            stats = ex.refine_stats()
            runs.append((res, stats))
            log(f"[paxmc]   -> {'ok' if res.ok else 'VIOLATION'} "
                f"states={res.states} edges={stats['edges_checked']} "
                f"drained={res.drained} wall={res.wall_s:.1f}s")
    return runs


def _print_quorum_golden() -> int:
    """Re-verify and emit the certified ledger (the quorum twin of
    ``lint.py --print-wire-golden``)."""
    from minpaxos_tpu.analysis.quorum_golden import (
        GOLDEN_GRIDS, GOLDEN_MAX_N, GOLDEN_THRESHOLDS)
    from minpaxos_tpu.verify.quorum import (
        certify_grid, certify_threshold, verify_certificate)

    bad = 0
    print("GOLDEN_THRESHOLDS: dict[int, tuple[tuple[int, int], ...]] = {")
    for n in range(1, GOLDEN_MAX_N + 1):
        pairs = GOLDEN_THRESHOLDS.get(n, ())
        verified = []
        for q1, q2 in pairs:
            cert = certify_threshold(n, q1, q2)
            if cert.intersects and verify_certificate(cert):
                verified.append((q1, q2))
            else:
                bad += 1
                print(f"    # DROPPED (fails to prove): ({q1}, {q2})")
        print(f"    {n}: {tuple(verified)!r},")
    print("}")
    print("GOLDEN_GRIDS = (")
    for rows, cols, q1, q2 in GOLDEN_GRIDS:
        cert = certify_grid(rows, cols, q1, q2)
        if cert.intersects and verify_certificate(cert):
            print(f"    ({rows}, {cols}, {q1!r}, {q2!r}),")
        else:
            bad += 1
            print(f"    # DROPPED (fails to prove): ({rows}, {cols}, "
                  f"{q1!r}, {q2!r})")
    print(")")
    return 1 if bad else 0


def _certify(spec: str) -> int:
    from minpaxos_tpu.verify.quorum import (
        certify_threshold, verify_certificate)

    try:
        n, q1, q2 = (int(x) for x in spec.split(","))
        cert = certify_threshold(n, q1, q2)
    except ValueError as e:
        print(f"bad --certify spec {spec!r}: {e}", file=sys.stderr)
        return 2
    print(json.dumps(cert.to_dict(), indent=1))
    if cert.intersects and verify_certificate(cert):
        print(f"# certified — ledger line for GOLDEN_THRESHOLDS[{n}]: "
              f"({q1}, {q2})")
        return 0
    print("# REFUTED — do NOT add to the ledger; the witness above is "
          "a split-brain schedule seed")
    return 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "paxmc", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: fixed bounds, mutant self-test, "
                        f"{SMOKE_BUDGET_S:.0f} s budget, MC.json")
    p.add_argument("--protocol", default="all",
                   help="minpaxos | classic | mencius | all")
    p.add_argument("--depth", type=int, default=None)
    p.add_argument("--cmds", type=int, default=None)
    p.add_argument("--drops", type=int, default=None)
    p.add_argument("--dups", type=int, default=None)
    p.add_argument("--reorders", type=int, default=None)
    p.add_argument("--internal", type=int, default=None)
    p.add_argument("--mutant", choices=["broken-quorum", "flex-broken",
                                        "skip-quorum2",
                                        "dueling-leaders"],
                   default=None,
                   help="seeded mutant: 'broken-quorum' forces the "
                        "threshold to 1 via the property override; "
                        "'flex-broken' plants the non-intersecting "
                        f"flexible pair {FLEX_MUTANT} through the real "
                        "cfg.q1/cfg.q2 fields; 'skip-quorum2' makes "
                        "the leader commit below the phase-2 quorum "
                        "(caught only by --refine's spec mapping); "
                        "'dueling-leaders' un-budgets mutual "
                        "preemption (caught only by --liveness as a "
                        "fair lasso). Exit 0 iff the counterexample "
                        "is found and replays")
    p.add_argument("--q1", type=int, default=0,
                   help="flexible phase-1 quorum (0 = majority)")
    p.add_argument("--q2", type=int, default=0,
                   help="flexible phase-2 quorum (0 = majority)")
    p.add_argument("--n", type=int, default=3, help="model replicas")
    p.add_argument("--flex-certified", action="store_true",
                   help="explore every certified GOLDEN_THRESHOLDS "
                        "(q1,q2) pair at N=3..5 (minpaxos) with "
                        "per-edge refinement checking; exit 0 iff "
                        "all drain with 0 violations")
    p.add_argument("--refine", action="store_true",
                   help="refinement legs: map every explored edge of "
                        "all 3 protocols (plus the --spec-pair "
                        "flexible leg) onto the abstract Paxos spec; "
                        "exit 0 iff every edge has an abstract "
                        "counterpart")
    p.add_argument("--liveness", action="store_true",
                   help="liveness legs: prove eventual commit under "
                        "weak fairness (lasso/SCC search over the "
                        "fair-suffix graph) for the default quorums "
                        "and the --spec-pair flexible pair")
    p.add_argument("--spec-pair", default=None, metavar="Q1,Q2",
                   help="certified (q1,q2) pair for the flexible "
                        f"refinement/liveness legs (default "
                        f"{SPEC_PAIR[0]},{SPEC_PAIR[1]})")
    p.add_argument("--replay", default=None, metavar="CE_JSON",
                   help="replay a counterexample trace; exit 0 iff the "
                        "violation reproduces")
    p.add_argument("--emit-trace", default="", metavar="FILE",
                   help="write the first counterexample (JSON) here")
    p.add_argument("--emit-faultplan", default=None, metavar="CE_JSON",
                   help="project a counterexample onto a chaos "
                        "FaultPlan schedule (stdout)")
    p.add_argument("--json", default="",
                   help="write the full verdict to this file")
    p.add_argument("--certify", default=None, metavar="N,Q1,Q2",
                   help="certify one threshold quorum pair and print "
                        "the ledger line")
    p.add_argument("--print-quorum-golden", action="store_true",
                   help="emit the re-verified certified quorum ledger")
    args = p.parse_args(argv)

    if args.print_quorum_golden:
        return _print_quorum_golden()
    if args.certify:
        return _certify(args.certify)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from minpaxos_tpu.utils.backend import enable_compile_cache

    enable_compile_cache()

    from minpaxos_tpu.verify.mc import (
        PROTOCOLS,
        Explorer,
        counterexample_faultplan,
        replay_counterexample,
    )

    if args.emit_faultplan:
        ce = json.loads(Path(args.emit_faultplan).read_text())
        print(json.dumps(counterexample_faultplan(ce), indent=1))
        return 0

    if args.replay:
        ce = json.loads(Path(args.replay).read_text())
        reproduced, report = replay_counterexample(ce)
        print(json.dumps({"reproduced": reproduced,
                          "report": report.to_dict()}, indent=1))
        return 0 if reproduced else 1

    def override(b):
        kw = {}
        for name, val in (("max_depth", args.depth), ("n_cmds", args.cmds),
                          ("drops", args.drops), ("dups", args.dups),
                          ("reorders", args.reorders),
                          ("internal", args.internal)):
            if val is not None:
                kw[name] = val
        from dataclasses import replace
        return replace(b, **kw) if kw else b

    try:
        spec_pair = (SPEC_PAIR if args.spec_pair is None
                     else tuple(int(x) for x in args.spec_pair.split(",")))
        if len(spec_pair) != 2:
            raise ValueError("need exactly Q1,Q2")
    except ValueError as e:
        p.error(f"bad --spec-pair {args.spec_pair!r}: {e}")

    if args.flex_certified:
        runs = _flex_certified_runs()
        ok = all(r.ok and r.drained for r, _s in runs)
        # the flexible liveness leg rides along: the certified sweep
        # says every pair is SAFE; this says the extreme point also
        # still COMMITS under weak fairness
        liveness = _run_liveness(spec_pair)
        ok = ok and liveness["ok"]
        verdict = {"ok": ok, "flex_certified": True,
                   "refined_edges": sum(s["edges_checked"]
                                        for _r, s in runs),
                   "runs": [dict(r.to_dict(),
                                 edges_checked=s["edges_checked"],
                                 abstract_actions=s["abstract_actions"])
                            for r, s in runs],
                   "liveness": liveness}
        print(f"[paxmc] flex-certified verdict: "
              f"{json.dumps({'ok': ok, 'pairs': len(runs), 'refined_edges': verdict['refined_edges']})}",
              flush=True)
        if args.json:
            Path(args.json).write_text(json.dumps(verdict, indent=1))
        return 0 if ok else 1

    if args.refine or args.liveness:
        verdict, ok = {}, True
        if args.refine:
            rv = _run_refine(spec_pair)
            verdict["refine"] = rv
            ok = ok and rv["ok"]
        if args.liveness:
            lv = _run_liveness(spec_pair)
            verdict["liveness"] = lv
            ok = ok and lv["ok"]
        verdict["ok"] = ok
        line = {"ok": ok}
        if args.refine:
            line["refined_edges"] = verdict["refine"]["edges_checked"]
        if args.liveness:
            line["liveness_legs"] = len(verdict["liveness"]["legs"])
        print(f"[paxmc] verdict: {json.dumps(line)}", flush=True)
        if args.json:
            Path(args.json).write_text(json.dumps(verdict, indent=1))
        return 0 if ok else 1

    if args.mutant == "dueling-leaders":
        # liveness mutant: the "counterexample" is a fair lasso, not
        # an invariant breach — found/replayed via the lasso contract
        line = _lasso_mutant_self_test(log=print)
        ce = line.pop("counterexample")
        if ce is not None and args.emit_trace:
            Path(args.emit_trace).write_text(json.dumps(ce, indent=1))
            line["trace"] = args.emit_trace
        print(f"[paxmc] {json.dumps(line)}", flush=True)
        if args.json:
            Path(args.json).write_text(
                json.dumps(dict(line, counterexample=ce), indent=1))
        return 0 if line["found"] and line["replay_reproduced"] else 1

    if args.mutant:
        proto = "minpaxos" if args.protocol == "all" else args.protocol
        if args.mutant == "flex-broken":
            b = override(_flex_mutant_bounds())
            res = Explorer(proto, b, **FLEX_MUTANT).run(log=print)
        elif args.mutant == "skip-quorum2":
            from minpaxos_tpu.verify.refine import RefinementExplorer
            b = override(_skip_quorum2_bounds())
            res = RefinementExplorer(proto, b,
                                     mutant="skip-quorum2").run(log=print)
        else:
            b = override(_mutant_bounds())
            res = Explorer(proto, b, majority_override=1).run(log=print)
        found = res.counterexample is not None
        line = {"mutant": args.mutant, "protocol": proto,
                "counterexample_found": found, "states": res.states,
                "wall_s": round(res.wall_s, 1)}
        if found:
            reproduced, _rep = replay_counterexample(
                res.counterexample.to_dict())
            line["replay_reproduced"] = reproduced
            if args.emit_trace:
                Path(args.emit_trace).write_text(
                    json.dumps(res.counterexample.to_dict(), indent=1))
                line["trace"] = args.emit_trace
        print(f"[paxmc] {json.dumps(line)}", flush=True)
        if args.json:
            verdict = dict(line, result=res.to_dict())
            Path(args.json).write_text(json.dumps(verdict, indent=1))
        return 0 if found and line.get("replay_reproduced") else 1

    # ------------------------------------------------ verification runs
    legs = _smoke_legs()
    if args.protocol != "all":
        if args.protocol not in PROTOCOLS:
            p.error(f"unknown protocol {args.protocol!r}")
        legs = [l for l in legs if l[1] == args.protocol]
    if args.q1 or args.q2 or args.n != 3:
        # ad-hoc flexible run: one leg per selected protocol at the
        # requested (n, q1, q2)
        legs = [(f"{label}-n={args.n}-q1={args.q1}-q2={args.q2}", proto,
                 b, dict(kw, q1=args.q1, q2=args.q2, n_replicas=args.n))
                for label, proto, b, kw in legs[:1]] or legs
    legs = [(label, proto, override(b), kw)
            for label, proto, b, kw in legs]

    t_start = time.monotonic()
    t_budget = None
    runs = []
    ok = True
    for label, proto, b, kw in legs:
        print(f"[paxmc] exploring {label} (depth {b.max_depth}, "
              f"{b.n_cmds} cmds, drops {b.drops}, dups {b.dups}) ...",
              flush=True)
        res = Explorer(proto, b, **kw).run(log=print)
        if t_budget is None:
            t_budget = time.monotonic()  # first run covered jit compile
        runs.append(res)
        ok = ok and res.ok and res.drained
        print(f"[paxmc]   -> {'ok' if res.ok else 'VIOLATION'} "
              f"states={res.states} transitions={res.transitions} "
              f"drained={res.drained} wall={res.wall_s:.1f}s", flush=True)
        if res.counterexample is not None and args.emit_trace:
            Path(args.emit_trace).write_text(
                json.dumps(res.counterexample.to_dict(), indent=1))
            print(f"[paxmc] counterexample written to {args.emit_trace}",
                  flush=True)

    verdict = {"ok": ok, "runs": [r.to_dict() for r in runs],
               "wall_s": round(time.monotonic() - t_start, 2)}

    if args.smoke:
        # seeded-mutant self-test: a checker that cannot find a planted
        # non-intersecting quorum certifies nothing
        res = Explorer("minpaxos", _mutant_bounds(),
                       majority_override=1).run()
        found = res.counterexample is not None
        reproduced = found and replay_counterexample(
            res.counterexample.to_dict())[0]
        verdict["mutant_self_test"] = {
            "found": found, "replay_reproduced": reproduced,
            "states": res.states, "wall_s": round(res.wall_s, 1),
            "trace_len": (len(res.counterexample.trace) if found else 0)}
        ok = ok and found and reproduced
        # same contract for the FLEXIBLE mutant: the planted
        # non-intersecting (q1, q2) pair — through the real config
        # fields, not the property override — must also be found and
        # replayed, or the flexible legs above prove nothing
        fres = Explorer("minpaxos", _flex_mutant_bounds(),
                        **FLEX_MUTANT).run()
        ffound = fres.counterexample is not None
        freproduced = ffound and replay_counterexample(
            fres.counterexample.to_dict())[0]
        verdict["flex_mutant_self_test"] = {
            "q1": FLEX_MUTANT["q1"], "q2": FLEX_MUTANT["q2"],
            "found": ffound, "replay_reproduced": freproduced,
            "states": fres.states, "wall_s": round(fres.wall_s, 1),
            "trace_len": (len(fres.counterexample.trace) if ffound else 0)}
        ok = ok and ffound and freproduced
        # paxref legs (ISSUE 17): refinement over all 3 protocols plus
        # the flexible pair, liveness under weak fairness, and the
        # planted mutants each layer exists to catch — all riding the
        # same compiled kernel shapes as the legs above (the per-
        # instance jit closures hit the persistent compile cache)
        rv = _run_refine(spec_pair, log=print)
        verdict["refine"] = rv
        ok = ok and rv["ok"]
        rm = _refine_mutant_self_test(log=print)
        rm.pop("counterexample")
        verdict["refine_mutant_self_test"] = rm
        ok = ok and rm["found"] and rm["replay_reproduced"]
        lv = _run_liveness(spec_pair, log=print)
        verdict["liveness"] = lv
        ok = ok and lv["ok"]
        lm = _lasso_mutant_self_test(log=print)
        lm.pop("counterexample")
        verdict["lasso_mutant_self_test"] = lm
        ok = ok and lm["found"] and lm["replay_reproduced"]
        checked_wall = time.monotonic() - (t_budget or t_start)
        verdict["budget_s"] = SMOKE_BUDGET_S
        verdict["within_budget"] = checked_wall <= SMOKE_BUDGET_S
        if not verdict["within_budget"]:
            ok = False
        verdict["ok"] = ok
        verdict["wall_s"] = round(time.monotonic() - t_start, 2)
        # the committed MC.json artifact is regenerated explicitly via
        # `--smoke --json MC.json` (the CHAOS.json convention) — the
        # bare CI gate must not dirty the tree with fresh wall clocks
        # on every tier-1 run
        print(f"[paxmc] smoke verdict ready "
              f"(post-compile wall {checked_wall:.1f}s / budget "
              f"{SMOKE_BUDGET_S:.0f}s)", flush=True)

    line = {"ok": ok,
            "states": sum(r.states for r in runs),
            "transitions": sum(r.transitions for r in runs),
            "violations": sum(0 if r.ok else 1 for r in runs),
            "drained": all(r.drained for r in runs),
            "wall_s": verdict["wall_s"]}
    if args.smoke:
        line["mutant_self_test"] = verdict["mutant_self_test"]["found"]
        line["flex_mutant_self_test"] = (
            verdict["flex_mutant_self_test"]["found"])
        line["refined_edges"] = verdict["refine"]["edges_checked"]
        line["refine_mutant_self_test"] = (
            verdict["refine_mutant_self_test"]["found"])
        line["liveness_ok"] = verdict["liveness"]["ok"]
        line["lasso_mutant_self_test"] = (
            verdict["lasso_mutant_self_test"]["found"])
    print(f"[paxmc] verdict: {json.dumps(line)}", flush=True)
    if args.json:
        Path(args.json).write_text(json.dumps(verdict, indent=1))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
