"""Per-kernel step profiler: where does a protocol tick's time go?

Times the jitted protocol steps (MinPaxos / Mencius) and the KV
sub-kernels standalone at deployment shapes, on whatever backend JAX
resolves (pin with JAX_PLATFORMS). This is the measurement tool behind
the round-5 step optimization work (VERDICT round 4 items 6-7): it
separates device compute from dispatch overhead and isolates the KV
claim loop's capacity scaling.

Run: JAX_PLATFORMS=cpu python tools/profile_step.py [--window 4096]
Prints one labeled ms/op line per case.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from minpaxos_tpu.models.mencius import init_mencius, mencius_step
from minpaxos_tpu.models.minpaxos import (
    MinPaxosConfig,
    MsgBatch,
    init_replica,
    replica_step,
)
from minpaxos_tpu.ops import kvstore
from minpaxos_tpu.wire.messages import MsgKind, Op


def _time(fn, iters: int = 20) -> float:
    """Median ms over ``iters`` calls (after one warmup)."""
    fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    return ts[len(ts) // 2]


def propose_inbox(cfg: MinPaxosConfig, n_prop: int, to_leader: bool) -> MsgBatch:
    m = cfg.inbox
    cols = {c: np.zeros(m, np.int32) for c in MsgBatch._fields}
    cols["kind"][:n_prop] = int(MsgKind.PROPOSE)
    cols["src"][:n_prop] = -1
    cols["op"][:n_prop] = int(Op.PUT)
    cols["key_lo"][:n_prop] = np.arange(n_prop, dtype=np.int32)
    cols["val_lo"][:n_prop] = np.arange(n_prop, dtype=np.int32) + 7
    cols["cmd_id"][:n_prop] = np.arange(n_prop, dtype=np.int32)
    cols["client_id"][:n_prop] = 5
    return MsgBatch(**{k: jnp.asarray(v) for k, v in cols.items()})


def bench_step(name, step, cfg, state, inbox, iters=20) -> None:
    # thread the state through (the steps donate their state argument,
    # so the input buffers are consumed by each call); copy first so
    # init-time aliased zero buffers aren't donated twice
    holder = [jax.tree.map(jnp.copy, state)]

    def once():
        st2, out, ex = step(cfg, holder[0], inbox)
        jax.block_until_ready(st2)
        holder[0] = st2

    ms = _time(once, iters)
    print(f"{name:44s} {ms:8.2f} ms/step")


def bench_kv(cfg_label: str, cap_pow2: int, b: int, iters=20) -> None:
    kv = kvstore.kv_init(cap_pow2)
    rng = np.random.default_rng(0)
    op = jnp.asarray(np.full(b, int(Op.PUT), np.int32))
    k_hi = jnp.asarray(np.zeros(b, np.int32))
    k_lo = jnp.asarray(rng.integers(0, 100000, b).astype(np.int32))
    v = jnp.asarray(np.ones((b, kvstore.VAL_LANES), np.int32))
    valid = jnp.asarray(np.ones(b, bool))

    apply_j = jax.jit(kvstore.kv_apply_batch_lanes)

    def once():
        kv2, out, found = apply_j(kv, op, k_hi, k_lo, v, valid)
        jax.block_until_ready(kv2)

    ms = _time(once, iters)
    print(f"kv_apply_batch  C=2^{cap_pow2:<2d} B={b:<5d} {cfg_label:12s}"
          f" {ms:8.2f} ms/call")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--window", type=int, default=4096)
    ap.add_argument("--inbox", type=int, default=2048)
    ap.add_argument("--props", type=int, default=512)
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    print(f"backend: {jax.devices()[0].platform}", file=sys.stderr)

    for kvp in (16, 20):
        cfg = MinPaxosConfig(n_replicas=3, window=args.window,
                             inbox=args.inbox, exec_batch=args.window,
                             kv_pow2=kvp)
        st_m = init_mencius(cfg, 0)
        st_p = init_replica(cfg, 0)
        empty = MsgBatch.empty(cfg.inbox)
        prop = propose_inbox(cfg, args.props, to_leader=True)
        bench_step(f"mencius idle   W={args.window} kv=2^{kvp}",
                   mencius_step, cfg, st_m, empty, args.iters)
        bench_step(f"mencius {args.props}prop W={args.window} kv=2^{kvp}",
                   mencius_step, cfg, st_m, prop, args.iters)
        bench_step(f"minpaxos idle  W={args.window} kv=2^{kvp}",
                   replica_step, cfg, st_p, empty, args.iters)
        bench_step(f"minpaxos {args.props}prop W={args.window} kv=2^{kvp}",
                   replica_step, cfg, st_p, prop, args.iters)

    for cap in (16, 20):
        for b in (512, 2048):
            bench_kv("", cap, b, args.iters)


if __name__ == "__main__":
    main()
