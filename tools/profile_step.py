"""Per-kernel step profiler: where does a protocol tick's time go?

Times the jitted protocol steps (MinPaxos / Mencius) and the KV
sub-kernels standalone at deployment shapes, on whatever backend JAX
resolves (pin with JAX_PLATFORMS). This is the measurement tool behind
the round-5 step optimization work (VERDICT round 4 items 6-7): it
separates device compute from dispatch overhead and isolates the KV
claim loop's capacity scaling.

Run: JAX_PLATFORMS=cpu python tools/profile_step.py [--window 4096]
Prints one labeled ms/op line per case.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from minpaxos_tpu.models.mencius import init_mencius, mencius_step
from minpaxos_tpu.models.minpaxos import (
    MinPaxosConfig,
    MsgBatch,
    init_replica,
    replica_step,
)
from minpaxos_tpu.ops import kvstore
from minpaxos_tpu.wire.messages import MsgKind, Op


def _time(fn, iters: int = 20) -> float:
    """Median ms over ``iters`` calls (after one warmup)."""
    fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    return ts[len(ts) // 2]


def propose_inbox(cfg: MinPaxosConfig, n_prop: int, to_leader: bool) -> MsgBatch:
    m = cfg.inbox
    cols = {c: np.zeros(m, np.int32) for c in MsgBatch._fields}
    cols["kind"][:n_prop] = int(MsgKind.PROPOSE)
    cols["src"][:n_prop] = -1
    cols["op"][:n_prop] = int(Op.PUT)
    cols["key_lo"][:n_prop] = np.arange(n_prop, dtype=np.int32)
    cols["val_lo"][:n_prop] = np.arange(n_prop, dtype=np.int32) + 7
    cols["cmd_id"][:n_prop] = np.arange(n_prop, dtype=np.int32)
    cols["client_id"][:n_prop] = 5
    return MsgBatch(**{k: jnp.asarray(v) for k, v in cols.items()})


def bench_step(name, step, cfg, state, inbox, iters=20) -> None:
    # thread the state through (the steps donate their state argument,
    # so the input buffers are consumed by each call); copy first so
    # init-time aliased zero buffers aren't donated twice
    holder = [jax.tree.map(jnp.copy, state)]

    def once():
        st2, out, ex = step(cfg, holder[0], inbox)
        jax.block_until_ready(st2)
        holder[0] = st2

    ms = _time(once, iters)
    print(f"{name:44s} {ms:8.2f} ms/step")


def bench_kv(cfg_label: str, cap_pow2: int, b: int, iters=20) -> None:
    kv = kvstore.kv_init(cap_pow2)
    rng = np.random.default_rng(0)
    op = jnp.asarray(np.full(b, int(Op.PUT), np.int32))
    k_hi = jnp.asarray(np.zeros(b, np.int32))
    k_lo = jnp.asarray(rng.integers(0, 100000, b).astype(np.int32))
    v = jnp.asarray(np.ones((b, kvstore.VAL_LANES), np.int32))
    valid = jnp.asarray(np.ones(b, bool))

    apply_j = jax.jit(kvstore.kv_apply_batch_lanes)

    def once():
        kv2, out, found = apply_j(kv, op, k_hi, k_lo, v, valid)
        jax.block_until_ready(kv2)

    ms = _time(once, iters)
    print(f"kv_apply_batch  C=2^{cap_pow2:<2d} B={b:<5d} {cfg_label:12s}"
          f" {ms:8.2f} ms/call")


def decompose(window: int = 512, iters: int = 40) -> None:
    """Split the per-tick cost into dispatch floor vs marginal compute
    at the serial-latency shape (bench_tcp.py SERIAL_SHAPE) — the
    round-6 question behind VERDICT item 5: how much of the 0.3-0.9 ms
    tick is the host->device round trip that fused substeps amortize?

    Method: time the packed k-substep step for k=1..4; the slope
    (t_k - t_1)/(k-1) is one substep's pure compute (substeps share
    one dispatch), so t_1 minus the slope is the dispatch floor. Also
    A/Bs the narrow resident view: a server-default 16384-slot window
    stepped full-width vs through a 512-slot view.
    """
    from minpaxos_tpu.models.minpaxos import replica_step_impl
    from minpaxos_tpu.runtime.replica import _packed_step

    cfg = MinPaxosConfig(n_replicas=3, window=window, inbox=256,
                         exec_batch=64, kv_pow2=12, catchup_rows=256,
                         recovery_rows=256, gossip_ticks=4)
    prop = propose_inbox(cfg, 1, to_leader=True)  # a serial op's tick

    def timed(k: int) -> float:
        holder = [jax.tree.map(jnp.copy, init_replica(cfg, 0))]

        def once():
            st, om, em, sc = _packed_step(cfg, holder[0], prop,
                                          replica_step_impl, k)
            jax.block_until_ready(sc)
            holder[0] = st

        return _time(once, iters)

    ts = {k: timed(k) for k in (1, 2, 3, 4)}
    slope = (ts[4] - ts[1]) / 3
    floor = max(ts[1] - slope, 0.0)
    print(f"\n-- dispatch-vs-compute decomposition, W={window} "
          f"(1-prop tick, serial shape) --")
    for k, t in ts.items():
        print(f"  k={k} substeps/dispatch {t:8.3f} ms "
              f"({t / k:.3f} ms/substep amortized)")
    print(f"  marginal substep compute {slope:8.3f} ms")
    print(f"  dispatch floor (t1 - marginal) {floor:8.3f} ms "
          f"({100 * floor / ts[1]:.0f}% of a k=1 tick)")

    # narrow view A/B: server-default window, low occupancy
    big = MinPaxosConfig(n_replicas=3, window=1 << 14, inbox=256,
                         exec_batch=64, kv_pow2=12, catchup_rows=256,
                         recovery_rows=256, gossip_ticks=4)
    bprop = propose_inbox(big, 1, to_leader=True)
    for narrow in (0, 512):
        holder = [jax.tree.map(jnp.copy, init_replica(big, 0))]

        def once():
            st, om, em, sc = _packed_step(big, holder[0], bprop,
                                          replica_step_impl, 1, narrow,
                                          jnp.int32(0))
            jax.block_until_ready(sc)
            holder[0] = st

        label = f"narrow view W=16384->{narrow}" if narrow else \
            "full step  W=16384"
        print(f"  {label:28s} {_time(once, iters):8.3f} ms/tick")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--window", type=int, default=4096)
    ap.add_argument("--inbox", type=int, default=2048)
    ap.add_argument("--props", type=int, default=512)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--no-decompose", action="store_true",
                    help="skip the dispatch-vs-compute / narrow-view "
                         "section (it compiles extra W=16384 and fused "
                         "variants — minutes on slow hosts)")
    args = ap.parse_args()

    print(f"backend: {jax.devices()[0].platform}", file=sys.stderr)

    for kvp in (16, 20):
        cfg = MinPaxosConfig(n_replicas=3, window=args.window,
                             inbox=args.inbox, exec_batch=args.window,
                             kv_pow2=kvp)
        st_m = init_mencius(cfg, 0)
        st_p = init_replica(cfg, 0)
        empty = MsgBatch.empty(cfg.inbox)
        prop = propose_inbox(cfg, args.props, to_leader=True)
        bench_step(f"mencius idle   W={args.window} kv=2^{kvp}",
                   mencius_step, cfg, st_m, empty, args.iters)
        bench_step(f"mencius {args.props}prop W={args.window} kv=2^{kvp}",
                   mencius_step, cfg, st_m, prop, args.iters)
        bench_step(f"minpaxos idle  W={args.window} kv=2^{kvp}",
                   replica_step, cfg, st_p, empty, args.iters)
        bench_step(f"minpaxos {args.props}prop W={args.window} kv=2^{kvp}",
                   replica_step, cfg, st_p, prop, args.iters)

    for cap in (16, 20):
        for b in (512, 2048):
            bench_kv("", cap, b, args.iters)

    if not args.no_decompose:
        decompose(iters=args.iters)


if __name__ == "__main__":
    main()
