"""Per-kernel step profiler: where does a protocol tick's time go?

Times the jitted protocol steps (MinPaxos / Mencius) and the KV
sub-kernels standalone at deployment shapes, on whatever backend JAX
resolves (pin with JAX_PLATFORMS). This is the measurement tool behind
the round-5 step optimization work (VERDICT round 4 items 6-7): it
separates device compute from dispatch overhead and isolates the KV
claim loop's capacity scaling.

Run: JAX_PLATFORMS=cpu python tools/profile_step.py [--window 4096]
Prints one labeled ms/op line per case.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from minpaxos_tpu.models.mencius import init_mencius, mencius_step
from minpaxos_tpu.models.minpaxos import (
    MinPaxosConfig,
    MsgBatch,
    init_replica,
    replica_step,
)
from minpaxos_tpu.ops import kvstore
from minpaxos_tpu.wire.messages import MsgKind, Op


def _time(fn, iters: int = 20) -> float:
    """Median ms over ``iters`` calls (after one warmup)."""
    fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    return ts[len(ts) // 2]


def propose_inbox(cfg: MinPaxosConfig, n_prop: int, to_leader: bool) -> MsgBatch:
    m = cfg.inbox
    cols = {c: np.zeros(m, np.int32) for c in MsgBatch._fields}
    cols["kind"][:n_prop] = int(MsgKind.PROPOSE)
    cols["src"][:n_prop] = -1
    cols["op"][:n_prop] = int(Op.PUT)
    cols["key_lo"][:n_prop] = np.arange(n_prop, dtype=np.int32)
    cols["val_lo"][:n_prop] = np.arange(n_prop, dtype=np.int32) + 7
    cols["cmd_id"][:n_prop] = np.arange(n_prop, dtype=np.int32)
    cols["client_id"][:n_prop] = 5
    return MsgBatch(**{k: jnp.asarray(v) for k, v in cols.items()})


def bench_step(name, step, cfg, state, inbox, iters=20) -> None:
    # thread the state through (the steps donate their state argument,
    # so the input buffers are consumed by each call); copy first so
    # init-time aliased zero buffers aren't donated twice
    holder = [jax.tree.map(jnp.copy, state)]

    def once():
        st2, out, ex = step(cfg, holder[0], inbox)
        jax.block_until_ready(st2)
        holder[0] = st2

    ms = _time(once, iters)
    print(f"{name:44s} {ms:8.2f} ms/step")


def bench_kv(cfg_label: str, cap_pow2: int, b: int, iters=20) -> None:
    kv = kvstore.kv_init(cap_pow2)
    rng = np.random.default_rng(0)
    op = jnp.asarray(np.full(b, int(Op.PUT), np.int32))
    k_hi = jnp.asarray(np.zeros(b, np.int32))
    k_lo = jnp.asarray(rng.integers(0, 100000, b).astype(np.int32))
    v = jnp.asarray(np.ones((b, kvstore.VAL_LANES), np.int32))
    valid = jnp.asarray(np.ones(b, bool))

    apply_j = jax.jit(kvstore.kv_apply_batch_lanes)

    def once():
        kv2, out, found = apply_j(kv, op, k_hi, k_lo, v, valid)
        jax.block_until_ready(kv2)

    ms = _time(once, iters)
    print(f"kv_apply_batch  C=2^{cap_pow2:<2d} B={b:<5d} {cfg_label:12s}"
          f" {ms:8.2f} ms/call")


def decompose(window: int = 512, iters: int = 40) -> None:
    """Split the per-tick cost into dispatch floor vs marginal compute
    at the serial-latency shape (bench_tcp.py SERIAL_SHAPE) — the
    round-6 question behind VERDICT item 5: how much of the 0.3-0.9 ms
    tick is the host->device round trip that fused substeps amortize?

    Method: time the packed k-substep step for k=1..4; the slope
    (t_k - t_1)/(k-1) is one substep's pure compute (substeps share
    one dispatch), so t_1 minus the slope is the dispatch floor. Also
    A/Bs the narrow resident view: a server-default 16384-slot window
    stepped full-width vs through a 512-slot view.
    """
    from minpaxos_tpu.models.minpaxos import replica_step_impl
    from minpaxos_tpu.runtime.replica import _packed_step

    cfg = MinPaxosConfig(n_replicas=3, window=window, inbox=256,
                         exec_batch=64, kv_pow2=12, catchup_rows=256,
                         recovery_rows=256, gossip_ticks=4)
    prop = propose_inbox(cfg, 1, to_leader=True)  # a serial op's tick

    def timed(k: int) -> float:
        holder = [jax.tree.map(jnp.copy, init_replica(cfg, 0))]

        def once():
            st, om, em, sc = _packed_step(cfg, holder[0], prop,
                                          replica_step_impl, k)
            jax.block_until_ready(sc)
            holder[0] = st

        return _time(once, iters)

    ts = {k: timed(k) for k in (1, 2, 3, 4)}
    slope = (ts[4] - ts[1]) / 3
    floor = max(ts[1] - slope, 0.0)
    print(f"\n-- dispatch-vs-compute decomposition, W={window} "
          f"(1-prop tick, serial shape) --")
    for k, t in ts.items():
        print(f"  k={k} substeps/dispatch {t:8.3f} ms "
              f"({t / k:.3f} ms/substep amortized)")
    print(f"  marginal substep compute {slope:8.3f} ms")
    print(f"  dispatch floor (t1 - marginal) {floor:8.3f} ms "
          f"({100 * floor / ts[1]:.0f}% of a k=1 tick)")

    # narrow view A/B: server-default window, low occupancy
    big = MinPaxosConfig(n_replicas=3, window=1 << 14, inbox=256,
                         exec_batch=64, kv_pow2=12, catchup_rows=256,
                         recovery_rows=256, gossip_ticks=4)
    bprop = propose_inbox(big, 1, to_leader=True)
    for narrow in (0, 512):
        holder = [jax.tree.map(jnp.copy, init_replica(big, 0))]

        def once():
            st, om, em, sc = _packed_step(big, holder[0], bprop,
                                          replica_step_impl, 1, narrow,
                                          jnp.int32(0))
            jax.block_until_ready(sc)
            holder[0] = st

        label = f"narrow view W=16384->{narrow}" if narrow else \
            "full step  W=16384"
        print(f"  {label:28s} {_time(once, iters):8.3f} ms/tick")


def pipeline_decompose(window: int = 512, iters: int = 40) -> None:
    """The pipelined tick loop's decomposition (ISSUE: runtime/
    replica.py now ENQUEUES the step, runs the previous tick's host
    phases while the device computes, then reads back): measure, at
    the serial shape, the walls the pipeline is made of —

    * ``enqueue``: host wall to launch the async dispatch,
    * ``compute``: device wall (enqueue + block, no host work between),
    * ``readback``: host blocked on the transfers after hiding host
      work under the compute,
    * ``host``: a calibrated stand-in for persist+dispatch+reply
      (numpy masking/grouping over outbox-shaped arrays, measured
      standalone),

    and report overlap efficiency: of the host wall, how much
    disappeared when run between enqueue and readback —
    (serial_total - pipelined_total) / host_wall. 1.0 = fully hidden;
    0 = the backend dispatches synchronously and the pipeline only
    reorders."""
    from minpaxos_tpu.models.minpaxos import replica_step_impl
    from minpaxos_tpu.runtime.replica import _packed_step

    cfg = MinPaxosConfig(n_replicas=3, window=window, inbox=256,
                         exec_batch=64, kv_pow2=12, catchup_rows=256,
                         recovery_rows=256, gossip_ticks=4)
    prop = propose_inbox(cfg, 1, to_leader=True)

    # calibrated host-phase stand-in: outbox-shaped numpy work (mask,
    # unique, group), repeated to land near a loaded tick's real
    # persist+dispatch+reply wall (~0.3-0.5 ms on this class of host —
    # the paxmon flight recorder's measured phase sum at bench load)
    out_kind = np.zeros(cfg.inbox, np.int32)
    out_kind[:128] = 3
    out_inst = np.arange(cfg.inbox, dtype=np.int32)

    def host_phases():
        for _ in range(8):
            live = out_kind != 0
            for q in range(cfg.n_replicas):
                m = live & (out_inst % cfg.n_replicas == q)
                if m.any():
                    ks = np.unique(out_kind[m])
                    for k_ in ks:
                        _ = out_inst[m][out_kind[m] == k_].copy()

    holder = [jax.tree.map(jnp.copy, init_replica(cfg, 0))]

    def enqueue():
        st, om, em, sc = _packed_step(cfg, holder[0], prop,
                                      replica_step_impl, 1)
        holder[0] = st
        return sc

    sc = enqueue()
    jax.block_until_ready(sc)  # warm compile

    def timed_leg(with_host: bool):
        """(enqueue_ms, mid_ms, readback_ms) — mid is the host work
        (or nothing) run between enqueue and the blocking readback."""
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            sc = enqueue()
            t1 = time.perf_counter()
            if with_host:
                host_phases()
            t2 = time.perf_counter()
            np.asarray(sc)
            t3 = time.perf_counter()
            ts.append(((t1 - t0) * 1e3, (t2 - t1) * 1e3,
                       (t3 - t2) * 1e3))
        ts.sort(key=lambda t: sum(t))
        return ts[len(ts) // 2]

    host_ms = _time(host_phases, iters)
    enq0, _, rb0 = timed_leg(False)  # device wall, no host work
    enq1, mid1, rb1 = timed_leg(True)
    compute_ms = enq0 + rb0
    serial_ms = compute_ms + host_ms
    pipelined_ms = enq1 + mid1 + rb1
    # of the host wall, how much did NOT extend the tick: host work
    # that fits the (compute - enqueue) overlap window is free
    hidden_ms = host_ms - max(0.0, pipelined_ms - compute_ms)
    eff = (hidden_ms / host_ms) if host_ms > 0 else 0.0
    print(f"\n-- pipeline decomposition, W={window} "
          f"(1-prop tick, serial shape) --")
    print(f"  enqueue (async dispatch launch) {enq1:8.3f} ms")
    print(f"  device compute (enqueue+block)  {compute_ms:8.3f} ms")
    print(f"  overlap window (compute-enqueue){compute_ms - enq0:8.3f} ms")
    print(f"  host phases (standalone)        {host_ms:8.3f} ms")
    print(f"  readback after hidden host work {rb1:8.3f} ms")
    print(f"  serial total (compute + host)   {serial_ms:8.3f} ms")
    print(f"  pipelined total                 {pipelined_ms:8.3f} ms")
    print(f"  overlap efficiency              {eff:8.2f} "
          f"(1.0 = host wall fully hidden under device compute)")


def resident_decompose(g: int = 2, w: int = 1024, p: int = 256,
                       k: int = 8, iters: int = 10) -> None:
    """Per-dispatch decomposition of the device-resident measured loop
    (ISSUE 8 satellite — mirrors ``--pipeline`` for the pipelined tick
    loop): split one resident dispatch into

    * ``enqueue``: host wall to launch the k-round fused dispatch
      (jit call overhead + async submit; nothing transferred in),
    * ``device compute``: enqueue + block, no readback,
    * ``scalar readback``: the two-scalar cursor read after compute
      (the ONLY sanctioned host sync in the steady state),

    and A/B it against the legacy host-in-the-loop dispatch
    (``run_fused``: same k rounds, then the [k, G] cursor-history
    transfer + blocking conversion). A regression in the resident path
    shows up as the readback line growing past scalar size, or the
    enqueue line growing a recompile."""
    import jax.numpy as jnp

    from minpaxos_tpu.parallel.sharded import (
        ShardedCluster,
        sharded_run_resident,
    )

    cu = max(32, p // 4)
    cfg = MinPaxosConfig(n_replicas=5, window=w, inbox=p + 2 * cu + 128,
                         exec_batch=p, kv_pow2=10, catchup_rows=cu,
                         recovery_rows=64)
    sc = ShardedCluster(cfg, g, ext_rows=p, key_space=1 << 8)
    sc.elect(0)
    sc.begin_resident()
    sc.run_resident(k, p)  # warm/compile the resident dispatch

    def dispatch_async():
        out = sharded_run_resident(
            sc.cfg, sc.n_shards, sc.ext_rows, k, sc.ss, sc._inject_round,
            sc._lat_hist, sc._telemetry, jnp.int32(p), jnp.int32(sc.leader),
            jnp.int32(sc._seed), jnp.int32(sc.seed), sc._step_impl,
            sc.key_space, 1, jnp.int32(sc._tel_base))
        (sc.ss, sc._inject_round, sc._lat_hist,
         sc._telemetry) = out[0], out[1], out[2], out[3]
        sc._seed += k
        return out[4], out[5]

    legs = []
    for _ in range(iters):
        t0 = time.perf_counter()
        committed, in_flight = dispatch_async()
        t1 = time.perf_counter()
        jax.block_until_ready(committed)
        t2 = time.perf_counter()
        c, f = int(committed), int(in_flight)  # the scalar readback
        t3 = time.perf_counter()
        legs.append(((t1 - t0) * 1e3, (t2 - t1) * 1e3, (t3 - t2) * 1e3))
    legs.sort(key=lambda t: sum(t))
    enq, comp, rb = legs[len(legs) // 2]

    # legacy comparison: same rounds, host-in-the-loop history readback
    sc2 = ShardedCluster(cfg, g, ext_rows=p, key_space=1 << 8)
    sc2.elect(0)
    sc2.run_fused(k, p)  # warm

    def legacy():
        u, c = sc2.run_fused(k, p)  # np.asarray blocks inside

    legacy_ms = _time(legacy, iters)
    total = enq + comp + rb
    print(f"\n-- resident-loop decomposition, g={g} W={w} p={p} k={k} --")
    print(f"  enqueue (jit call + async submit) {enq:8.3f} ms/dispatch")
    print(f"  device compute ({k} fused rounds)  {comp:8.3f} ms/dispatch")
    print(f"  scalar readback (2 cursors)       {rb:8.3f} ms/dispatch")
    print(f"  resident dispatch total           {total:8.3f} ms "
          f"({total / k:.3f} ms/round)")
    print(f"  legacy run_fused ([k,G] readback) {legacy_ms:8.3f} ms "
          f"({legacy_ms / k:.3f} ms/round)")
    print(f"  host-loop tax amortized away      {legacy_ms - total:8.3f} ms/dispatch")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--window", type=int, default=4096)
    ap.add_argument("--inbox", type=int, default=2048)
    ap.add_argument("--props", type=int, default=512)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--no-decompose", action="store_true",
                    help="skip the dispatch-vs-compute / narrow-view "
                         "section (it compiles extra W=16384 and fused "
                         "variants — minutes on slow hosts)")
    ap.add_argument("--pipeline", action="store_true",
                    help="run ONLY the pipeline decomposition "
                         "(enqueue/compute/readback/host walls + "
                         "overlap efficiency) and exit — the per-tick "
                         "evidence behind the pipelined tick loop")
    ap.add_argument("--resident", action="store_true",
                    help="run ONLY the resident-loop decomposition "
                         "(enqueue/device-compute/scalar-readback per "
                         "dispatch + legacy host-loop A/B) and exit — "
                         "the per-dispatch evidence behind the "
                         "device-resident measured loop")
    args = ap.parse_args()

    if args.pipeline:
        print(f"backend: {jax.devices()[0].platform}", file=sys.stderr)
        pipeline_decompose(iters=args.iters)
        return
    if args.resident:
        print(f"backend: {jax.devices()[0].platform}", file=sys.stderr)
        resident_decompose(iters=args.iters)
        return

    print(f"backend: {jax.devices()[0].platform}", file=sys.stderr)

    for kvp in (16, 20):
        cfg = MinPaxosConfig(n_replicas=3, window=args.window,
                             inbox=args.inbox, exec_batch=args.window,
                             kv_pow2=kvp)
        st_m = init_mencius(cfg, 0)
        st_p = init_replica(cfg, 0)
        empty = MsgBatch.empty(cfg.inbox)
        prop = propose_inbox(cfg, args.props, to_leader=True)
        bench_step(f"mencius idle   W={args.window} kv=2^{kvp}",
                   mencius_step, cfg, st_m, empty, args.iters)
        bench_step(f"mencius {args.props}prop W={args.window} kv=2^{kvp}",
                   mencius_step, cfg, st_m, prop, args.iters)
        bench_step(f"minpaxos idle  W={args.window} kv=2^{kvp}",
                   replica_step, cfg, st_p, empty, args.iters)
        bench_step(f"minpaxos {args.props}prop W={args.window} kv=2^{kvp}",
                   replica_step, cfg, st_p, prop, args.iters)

    for cap in (16, 20):
        for b in (512, 2048):
            bench_kv("", cap, b, args.iters)

    if not args.no_decompose:
        decompose(iters=args.iters)
        pipeline_decompose(iters=args.iters)


if __name__ == "__main__":
    main()
