"""Scatter-pattern microbenchmark: what does a batched multi-column
scatter cost on this backend, vs the gather-based rewrites?

The protocol step writes inbox-rows into window arrays as ~10 separate
per-column scatters per section (models/minpaxos.py sections 2/3/5),
and the routing fabric compacts outboxes the same way (~12 columns,
models/cluster.py _route). Under vmap over [G, R] those become batched
scatters; if XLA:TPU serializes per update row, the step cost is
O(sections * columns * batch * rows) — the hypothesis for the observed
674 ms/round at g=64 (BENCH round 5, ~40M scattered rows/round).

Candidates measured here at bench-rung-0-like shape (B=320 batch,
M=1408 updates, S=2048 targets):

  a. baseline   — 10 independent per-column scatters (today's code)
  b. argmax+gather — 1 scatter-max of row index, then 10 gathers
  c. onehot-matmul — one-hot [S, M] f32 matmul against [M, 10] payload

Run (relay must be free): python tools/scatter_micro.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

B, M, S, NCOL = 320, 1408, 2048, 10


def _time(fn, *args, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    return ts[len(ts) // 2]


def main() -> None:
    print(f"backend: {jax.devices()[0].platform}", file=sys.stderr)
    rng = np.random.default_rng(0)
    tgt = jnp.asarray(rng.integers(0, S + 1, (B, M)).astype(np.int32))
    cols = jnp.asarray(rng.integers(0, 1 << 20, (B, NCOL, M)).astype(np.int32))
    old = jnp.zeros((B, NCOL, S), jnp.int32)

    @jax.jit
    def scatter_percol(old, tgt, cols):
        def one(o, t, c):
            return jnp.stack([o[i].at[t].set(c[i], mode="drop")
                              for i in range(NCOL)])
        return jax.vmap(one)(old, tgt, cols)

    @jax.jit
    def argmax_gather(old, tgt, cols):
        def one(o, t, c):
            rows = jnp.arange(M, dtype=jnp.int32)
            win = jnp.full(S + 1, -1, jnp.int32).at[t].max(rows,
                                                           mode="drop")[:S]
            hit = win >= 0
            g = c[:, jnp.clip(win, 0)]          # [NCOL, S] gather
            return jnp.where(hit[None, :], g, o)
        return jax.vmap(one)(old, tgt, cols)

    @jax.jit
    def onehot_matmul(old, tgt, cols):
        def one(o, t, c):
            oh = (t[None, :] == jnp.arange(S)[:, None]).astype(jnp.float32)
            # last-writer-wins not preserved (sums dups) — timing probe only
            out = jnp.einsum("sm,cm->cs", oh, c.astype(jnp.float32))
            hit = oh.sum(1) > 0
            return jnp.where(hit[None, :], out.astype(jnp.int32), o)
        return jax.vmap(one)(old, tgt, cols)

    for name, fn in [("a. per-column scatter x10", scatter_percol),
                     ("b. argmax + gather", argmax_gather),
                     ("c. one-hot matmul", onehot_matmul)]:
        ms = _time(fn, old, tgt, cols)
        print(f"{name:28s} {ms:9.2f} ms  "
              f"({B}x{M} rows -> {S} slots, {NCOL} cols)")

    # single-column scatter scaling: is cost per-column or fixed?
    @jax.jit
    def scatter_onecol(old, tgt, cols):
        return jax.vmap(lambda o, t, c: o.at[t].set(c, mode="drop"))(
            old[:, 0], tgt, cols[:, 0])

    ms1 = _time(scatter_onecol, old, tgt, cols)
    print(f"d. single-column scatter     {ms1:9.2f} ms")


if __name__ == "__main__":
    main()
