"""Scatter-pattern microbenchmark: what does a batched multi-column
scatter cost on this backend, vs the gather-based rewrites?

The protocol step writes inbox-rows into window arrays as ~10 separate
per-column scatters per section (models/minpaxos.py sections 2/3/5),
and the routing fabric compacts outboxes the same way (~12 columns,
models/cluster.py _route). Under vmap over [G, R] those become batched
scatters; if XLA:TPU serializes per update row, the step cost is
O(sections * columns * batch * rows) — the hypothesis for the observed
674 ms/round at g=64 (BENCH round 5, ~40M scattered rows/round).

Candidates measured here at bench-rung-0-like shape (B=320 batch,
M=1408 updates, S=2048 targets):

  a. baseline   — 10 independent per-column scatters (today's code)
  b. argmax+gather — 1 scatter-max of row index, then 10 gathers
  c. onehot-matmul — one-hot [S, M] f32 matmul against [M, 10] payload

Run (relay must be free): python tools/scatter_micro.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

B, M, S, NCOL = 320, 1408, 2048, 10


def _time(fn, *args, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append((time.perf_counter() - t0) * 1e3)
    ts.sort()
    return ts[len(ts) // 2]


def main() -> None:
    print(f"backend: {jax.devices()[0].platform}", file=sys.stderr)
    rng = np.random.default_rng(0)
    tgt = jnp.asarray(rng.integers(0, S + 1, (B, M)).astype(np.int32))
    cols = jnp.asarray(rng.integers(0, 1 << 20, (B, NCOL, M)).astype(np.int32))
    old = jnp.zeros((B, NCOL, S), jnp.int32)

    @jax.jit
    def scatter_percol(old, tgt, cols):
        def one(o, t, c):
            return jnp.stack([o[i].at[t].set(c[i], mode="drop")
                              for i in range(NCOL)])
        return jax.vmap(one)(old, tgt, cols)

    @jax.jit
    def argmax_gather(old, tgt, cols):
        def one(o, t, c):
            rows = jnp.arange(M, dtype=jnp.int32)
            win = jnp.full(S + 1, -1, jnp.int32).at[t].max(rows,
                                                           mode="drop")[:S]
            hit = win >= 0
            g = c[:, jnp.clip(win, 0)]          # [NCOL, S] gather
            return jnp.where(hit[None, :], g, o)
        return jax.vmap(one)(old, tgt, cols)

    @jax.jit
    def onehot_matmul(old, tgt, cols):
        def one(o, t, c):
            oh = (t[None, :] == jnp.arange(S)[:, None]).astype(jnp.float32)
            # last-writer-wins not preserved (sums dups) — timing probe only
            out = jnp.einsum("sm,cm->cs", oh, c.astype(jnp.float32))
            hit = oh.sum(1) > 0
            return jnp.where(hit[None, :], out.astype(jnp.int32), o)
        return jax.vmap(one)(old, tgt, cols)

    for name, fn in [("a. per-column scatter x10", scatter_percol),
                     ("b. argmax + gather", argmax_gather),
                     ("c. one-hot matmul", onehot_matmul)]:
        ms = _time(fn, old, tgt, cols)
        print(f"{name:28s} {ms:9.2f} ms  "
              f"({B}x{M} rows -> {S} slots, {NCOL} cols)")

    # single-column scatter scaling: is cost per-column or fixed?
    @jax.jit
    def scatter_onecol(old, tgt, cols):
        return jax.vmap(lambda o, t, c: o.at[t].set(c, mode="drop"))(
            old[:, 0], tgt, cols[:, 0])

    ms1 = _time(scatter_onecol, old, tgt, cols)
    print(f"d. single-column scatter     {ms1:9.2f} ms")

    # -- routing fabric: dense pool-per-destination vs one-pass
    # segmented (PR 11). The dense fabric is a masked cumsum + scatter
    # per destination over the [R·M] pool; the segmented one is one
    # segment-prefix-sum + a searchsorted winner + 12 dense gathers
    # (ops/segscatter.py). Same inputs, byte-identical outputs
    # (tests/test_route_fabric.py) — this leg isolates the (a)
    # rewrite's win from the rest of the round.
    from minpaxos_tpu.models.cluster import _route, _route_segmented
    from minpaxos_tpu.models.minpaxos import MinPaxosConfig, MsgBatch

    r_f = 5
    for m_f in (256, 1024):
        cfg = MinPaxosConfig(n_replicas=r_f, window=512, inbox=m_f)
        n_live = m_f // 2
        cols_f = {f: np.zeros((r_f, m_f), np.int32)
                  for f in MsgBatch._fields}
        dst_f = np.full((r_f, m_f), -1, np.int32)
        for rr in range(r_f):
            cols_f["kind"][rr, :n_live] = 1 + rng.integers(0, 8, n_live)
            u = rng.random(n_live)
            dst_f[rr, :n_live] = np.where(
                u < 0.6, -1, np.where(u < 0.85,
                                      rng.integers(0, r_f, n_live), -2))
        msgs = MsgBatch(**{f: jnp.asarray(v) for f, v in cols_f.items()})
        dstj = jnp.asarray(dst_f)
        alive = jnp.ones(r_f, bool)
        dense = jax.jit(lambda a, b, c, _cfg=cfg, _m=m_f:
                        _route(_cfg, a, b, c, _m))
        seg = jax.jit(lambda a, b, c, _cfg=cfg, _m=m_f:
                      _route_segmented(_cfg, a, b, c, _m))
        ms_d = _time(dense, msgs, dstj, alive)
        ms_s = _time(seg, msgs, dstj, alive)
        print(f"e. route dense  (R=5,M={m_f:5d}) {ms_d:9.2f} ms")
        print(f"f. route segmented   (same)  {ms_s:9.2f} ms "
              f"({ms_d / ms_s:.1f}x)")


if __name__ == "__main__":
    main()
