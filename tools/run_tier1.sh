#!/usr/bin/env bash
# The one blessed test entry point.
#
#   tools/run_tier1.sh          — the ROADMAP.md tier-1 line, verbatim
#                                 (full 'not slow' suite + DOTS_PASSED
#                                 count; ~10-13 min on the 1-core host)
#   tools/run_tier1.sh smoke    — fast pre-commit smoke: runtime + wire
#                                 units only (~2 min)
#
# Builders and CI invoke this instead of re-deriving the pytest flags:
# the tier-1 command's exact flags (marker filter, plugin disables,
# collection-error tolerance) ARE the acceptance contract, and ad-hoc
# variations have produced incomparable pass counts before.
set -u
cd "$(dirname "$0")/.."

# paxlint first: pure-AST consensus-aware lint (ANALYSIS.md), no JAX
# import, runs cold in ~2 s. A hot-path host sync, a wire-contract
# drift, or a lock-discipline break fails the build before any test
# boots a cluster.
echo "== paxlint =="
python tools/lint.py || exit 1

# paxmon smoke second: still no JAX import (~2 s). Gates the
# recorder-overhead contract (obs is default-ON in the runtime, so a
# hot-path regression there is a throughput regression everywhere)
# and the paxtop --once --json / TRACE-schema end-to-end path against
# a real master + control-plane stub (OBSERVABILITY.md).
echo "== paxmon smoke (recorder overhead + paxtop --once --json) =="
python tools/obs_smoke.py || exit 1

# paxmc smoke third: bounded model checking of the real protocol
# kernels — all 3 protocols explored exhaustively at the smoke bounds
# (every per-link delivery order, one drop, one dup, a concurrent
# election), every reached state held to the shared invariant suite,
# plus a seeded broken-quorum mutant that MUST yield a replayable
# counterexample (VERIFY.md). First JAX boot of the gate; budget
# clock starts after the first protocol's jit compile.
echo "== paxmc smoke (bounded model check: 3 protocols + quorum mutant) =="
env JAX_PLATFORMS=cpu python tools/mc.py --smoke || exit 1

# shape-ladder + resident-loop smoke fourth: two tiny (g, w, p, k)
# points through the fully device-resident measured loop — commits
# flow, the drain is exact (in-flight == 0: the latency-accounting
# contract), the on-device latency histogram is populated, and the
# autotuner picks a winner (PERF.md resident-loop section). The second
# point runs with OCCUPANCY-ADAPTIVE capacity on (PR 11): its inbox is
# derived from the first point's measured occupancy high-water mark
# (paxray TEL_INBOX_HWM, read on the sanctioned post-window path) with
# the kernel inbox compacted to it, and must additionally be LOSSLESS
# (no proposal dropped) — still exactly two compiled dispatch
# variants. Budgeted <= 60 s including the jit compile of both.
echo "== shape-ladder smoke (2-point resident-loop sweep, drain-exact) =="
env JAX_PLATFORMS=cpu python tools/shape_ladder.py --smoke || exit 1

# paxray smoke fifth: the resident-telemetry observability contract
# (ISSUE 9) — telemetry-on vs telemetry-off dispatch wall within 2%
# (min-of-N, order-alternating A/B), byte-identical protocol state,
# and a validated merged host+device Chrome trace with the device
# rounds under the reserved pid. JAX is warm from the ladder smoke;
# ~45 s including the two dispatch-variant compiles.
echo "== paxray smoke (telemetry overhead <=2% + merged device trace) =="
env JAX_PLATFORMS=cpu python tools/obs_smoke.py --resident || exit 1

# paxchaos smoke sixth: two fixed-seed fault schedules (partition-heal
# + 10% loss/reorder) against a real in-process cluster, checked with
# the SAME invariant predicates the model checker just proved at small
# bounds (ROBUSTNESS.md). Budget clock starts after the first run so
# the one-time jit compile doesn't count.
echo "== paxchaos smoke (2 seeded fault schedules + invariant checker) =="
env JAX_PLATFORMS=cpu python tools/chaos.py --smoke || exit 1

# paxsoak smoke seventh: the scenario driver end-to-end (ISSUE 18) —
# a 2-phase manifest (warmup + a micro overload burst) through the
# open-loop sharded swarm against a real cluster, checking EV_PHASE
# landed on every replica's journal, exactly-once held across shards
# (0 lost), and the joined scorecard is well-formed. Same compiled
# cluster shape as the chaos smoke above (JAX + the dispatch variants
# are warm); phase walls are manifest-fixed, ~40 s total.
echo "== paxsoak smoke (2-phase open-loop scenario + joined scorecard) =="
env JAX_PLATFORMS=cpu python tools/soak.py --smoke || exit 1

# The concurrent-client swarm leg (ISSUE 15) rides the pytest suite
# below: tests/test_swarm.py drives 64 real closed-loop TCP sessions
# through the ingress coalescer against an in-process cluster (~18 s,
# no new compiled variants); the 1024-session overload leg is marked
# `slow` and runs only in the full suite (pytest tests/ -m slow).

if [ "${1:-}" = "smoke" ]; then
    exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
        -k "runtime_units or wire or fused" \
        -p no:cacheprovider -p no:xdist -p no:randomly
fi

# ROADMAP.md tier-1 verify line, verbatim:
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
