#!/usr/bin/env python
"""profile_substeps — per-substep cost attribution for the consensus
kernels (paxray, ISSUE 9 piece 2).

The CPU ablation behind ROADMAP item 1 says per-round cost is ~50 µs
per INBOX ROW (accept/ack/route handling), and that now bounds
throughput everywhere — but that number was one aggregate. This tool
compiles and times the protocol's substep kernels IN ISOLATION at real
bench shapes, sweeps the inbox capacity (the kernels are branch-free
and masked, so cost scales with CAPACITY rows, not live rows — exactly
the ~50 µs/row the ablation measured), fits the per-row cost of each
substep by least squares, and emits a JSON cost table — the direct
input to the kernel work ROADMAP item 1 calls for, and the measured
table PERF.md records.

Substeps isolated (one inbox kind each, through the real jitted
kernels — NOT re-implementations):

* ``propose`` — leader slot assignment + ACCEPT emission
  (replica_step with a PROPOSE-only inbox);
* ``accept``  — follower ballot-compare/scatter + run-length ack
  compression (ACCEPT-only inbox);
* ``ack``     — leader vote counting + range coverage + commit scan
  (ACCEPT_REPLY-only inbox against an in-flight log);
* ``empty``   — the same kernel on an all-padding inbox: the fixed
  per-round floor (commit scan, exec gate, window slide) every round
  pays regardless of traffic;
* ``route``   — the ORIGINAL dense routing fabric (models/cluster.
  _route, kept behind ``route_fabric="dense"``): pool all outboxes,
  cumsum-scatter each replica's next inbox — measured so the PR-9 fit
  stays comparable across the PR-11 rewrite;
* ``route_v2`` — the one-pass segmented fabric (_route_segmented /
  ops/segscatter.py) the cluster actually runs: one segment-prefix-sum
  + searchsorted winner, no per-destination scatter;
* ``apply``   — the KV claim/apply path (ops/kvstore.kv_apply_batch:
  lexsort, segmented scans, two-choice claim rounds) per exec row.

Isolation discipline: every case is jitted WITHOUT donation and
re-invoked on the SAME input state, so each call does identical work
and the protocol cannot drift mid-measurement (a donated propose loop
would fill the window and silently switch to timing the rejection
path). One compile covers propose/accept/ack/empty at each capacity —
they share the replica_step jaxpr.

    JAX_PLATFORMS=cpu python tools/profile_substeps.py
    python tools/profile_substeps.py --rows 128 256 512 --json COSTS.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from minpaxos_tpu.models.cluster import _route, _route_segmented  # noqa: E402
from minpaxos_tpu.models.minpaxos import (  # noqa: E402
    MinPaxosConfig,
    MsgBatch,
    become_leader,
    init_replica,
    replica_step_impl,
)
from minpaxos_tpu.ops import kvstore  # noqa: E402
from minpaxos_tpu.wire.messages import MsgKind, Op  # noqa: E402


def _time_ms(fn, iters: int) -> float:
    """MIN wall ms over ``iters`` calls (one warmup/compile call).
    The min, not the median: these are fixed-shape deterministic
    kernels, so the minimum is the interference-free cost — on a
    shared host the median carries scheduler noise that wrecks the
    linear fit the per-row numbers come from."""
    fn()
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1e3)
    return min(ts)


def _mk_inbox(m: int, n: int, **cols) -> MsgBatch:
    """[m]-capacity inbox with the first ``n`` rows live, remaining
    rows padding (kind 0). ``cols`` give per-field fill (scalar or
    [n] array)."""
    out = {f: np.zeros(m, np.int32) for f in MsgBatch._fields}
    for f, v in cols.items():
        out[f][:n] = v
    return MsgBatch(**{f: jnp.asarray(v) for f, v in out.items()})


def _prepared_leader(cfg: MinPaxosConfig, step):
    """A replica-0 state holding a prepare majority at a fresh ballot
    (the steady-state serving leader every hot-path substep runs
    under), built through the real kernels."""
    st, _ = become_leader(cfg, init_replica(cfg, 0))
    b = int(st.default_ballot)
    m = cfg.inbox
    replies = _mk_inbox(
        m, 2, kind=int(MsgKind.PREPARE_REPLY),
        src=np.array([1, 2], np.int32), ballot=b, op=1,
        last_committed=-1)
    st, _, _ = step(cfg, st, replies)
    assert bool(st.prepared), "leader failed to prepare"
    return st, b


def _adopted_follower(cfg: MinPaxosConfig, step, ballot: int):
    """A replica-1 state that has adopted the leader's ballot (the
    state every follower substep runs against)."""
    st = init_replica(cfg, 1)
    prep = _mk_inbox(cfg.inbox, 1, kind=int(MsgKind.PREPARE), src=0,
                     ballot=ballot, last_committed=-1)
    st, _, _ = step(cfg, st, prep)
    assert int(st.default_ballot) == ballot
    return st


def profile_capacity(cfg: MinPaxosConfig, live: int, iters: int) -> dict:
    """ms/step of each replica_step substep at this inbox capacity
    (``cfg.inbox``), with ``live`` live rows each."""
    # no donation: the same input state is re-stepped every iteration
    step = jax.jit(replica_step_impl, static_argnums=0)
    leader, b = _prepared_leader(cfg, step)
    follower = _adopted_follower(cfg, step, b)
    n, m = live, cfg.inbox
    rows = np.arange(n, dtype=np.int32)

    propose = _mk_inbox(m, n, kind=int(MsgKind.PROPOSE), src=-1,
                        op=int(Op.PUT), key_lo=rows, val_lo=rows + 7,
                        cmd_id=rows, client_id=5)
    # leader with n slots in flight (so acks have something to cover);
    # votes stay below majority (self + one peer of five), so the
    # re-stepped state would not commit even if it were kept
    leader_inflight, _, _ = step(cfg, leader, propose)
    accept = _mk_inbox(m, n, kind=int(MsgKind.ACCEPT), src=0, ballot=b,
                       inst=rows, op=int(Op.PUT), key_lo=rows,
                       val_lo=rows + 7, cmd_id=rows, last_committed=-1)
    ack = _mk_inbox(m, n, kind=int(MsgKind.ACCEPT_REPLY), src=1, ballot=b,
                    inst=rows, op=1, cmd_id=1, last_committed=-1)
    empty = _mk_inbox(m, 0)

    def run(state, inbox):
        return lambda: jax.block_until_ready(step(cfg, state, inbox))

    out = {
        "propose": _time_ms(run(leader, propose), iters),
        "accept": _time_ms(run(follower, accept), iters),
        "ack": _time_ms(run(leader_inflight, ack), iters),
        "empty": _time_ms(run(leader_inflight, empty), iters),
    }

    # routing fabric: [R, M] outboxes, n live broadcast rows each
    r = cfg.n_replicas
    omsgs = MsgBatch(**{f: jnp.asarray(np.tile(getattr(accept, f), (r, 1)))
                        for f in MsgBatch._fields})
    dst = jnp.full((r, m), -1, jnp.int32)
    alive = jnp.ones(r, dtype=bool)

    # both fabrics at the same inputs: "route" (dense, the PR-9 fit's
    # subject) stays comparable across the rewrite, "route_v2" is the
    # segmented fabric the cluster actually runs (PR 11)
    route = jax.jit(lambda msgs, d, a: _route(cfg, msgs, d, a, m))
    out["route"] = _time_ms(
        lambda: jax.block_until_ready(route(omsgs, dst, alive)), iters)
    route2 = jax.jit(
        lambda msgs, d, a: _route_segmented(cfg, msgs, d, a, m))
    out["route_v2"] = _time_ms(
        lambda: jax.block_until_ready(route2(omsgs, dst, alive)), iters)

    # KV claim/apply path at batch size m — the batch axis IS the
    # swept dimension for this kernel, so it must equal the fit's x
    # (timing m//2 rows against an x of m would halve the reported
    # per-row cost). Distinct keys — the duplicate-free workload
    # contract, ops/workload.py.
    kv = kvstore.kv_init(cfg.kv_pow2)
    rows_m = np.arange(m, dtype=np.int32)
    op = jnp.asarray(np.full(m, int(Op.PUT), np.int32))
    k_lo = jnp.asarray(rows_m)
    z = jnp.zeros(m, jnp.int32)
    valid = jnp.ones(m, dtype=bool)
    apply_fn = jax.jit(kvstore.kv_apply_batch)
    out["apply"] = _time_ms(
        lambda: jax.block_until_ready(
            apply_fn(kv, op, z, k_lo, z, k_lo + 7, valid)), iters)
    return out


def fit_per_row(caps: list[int], ms: list[float]) -> dict:
    """Least-squares wall(M) = fixed + per_row * M over the capacity
    sweep; per-row cost in µs, plus r² so a bad fit is visible."""
    x, y = np.asarray(caps, float), np.asarray(ms, float)
    b, a = np.polyfit(x, y, 1)
    pred = a + b * x
    ss_res = float(((y - pred) ** 2).sum())
    ss_tot = float(((y - y.mean()) ** 2).sum())
    return {
        "per_row_us": round(b * 1e3, 3),
        "fixed_ms": round(a, 4),
        "r2": round(1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0, 4),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        "profile_substeps", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--rows", type=int, nargs="+",
                    default=[128, 256, 512, 1024],
                    help="inbox capacities to sweep (per-row cost is "
                         "fitted across these)")
    ap.add_argument("--window", type=int, default=512,
                    help="log window (the bench's CPU shape)")
    ap.add_argument("--iters", type=int, default=40,
                    help="timing iterations per point (min is kept — "
                         "see _time_ms). Raised 15 -> 40 in PR 11: the "
                         "PR-9 table's accept/empty fits bottomed out "
                         "at r2 0.71/0.77, too noisy for before/after "
                         "claims on a shared host")
    ap.add_argument("--json", default="",
                    help="write the cost table as JSON here")
    args = ap.parse_args(argv)

    platform = jax.devices()[0].platform
    # exec_batch HELD CONSTANT across the sweep: it sizes the step's
    # exec/KV block, so letting it ride m would fold per-exec-row cost
    # into every substep's "per inbox row" slope and kink the fit at
    # m == window — the isolation premise of the sweep
    exec_batch = min(min(args.rows), args.window)
    sweep: dict[str, dict[int, float]] = {}
    for m in args.rows:
        cfg = MinPaxosConfig(
            n_replicas=5, window=args.window, inbox=m,
            exec_batch=exec_batch, kv_pow2=12,
            catchup_rows=64, recovery_rows=64)
        t0 = time.perf_counter()
        point = profile_capacity(cfg, live=m // 2, iters=args.iters)
        print(f"-- capacity {m} rows ({time.perf_counter() - t0:.0f}s "
              f"incl. compile) --")
        for name, ms in point.items():
            sweep.setdefault(name, {})[m] = ms
            print(f"  {name:10s} {ms:8.3f} ms/step")

    table = {}
    bad_fits = []
    print(f"\n== per-row cost (fit over capacities {args.rows}, "
          f"window {args.window}, platform {platform}) ==")
    for name, pts in sweep.items():
        caps = sorted(pts)
        fit = fit_per_row(caps, [pts[c] for c in caps])
        table[name] = {"ms_by_capacity": {str(c): round(pts[c], 3)
                                          for c in caps}, **fit}
        flag = ""
        if fit["r2"] < 0.9:
            flag = "  <-- NOISY FIT (r2 < 0.9)"
            bad_fits.append(name)
        print(f"  {name:10s} {fit['per_row_us']:8.2f} us/row "
              f"(+{fit['fixed_ms']:.3f} ms fixed, r2={fit['r2']}){flag}")
    if bad_fits:
        print(f"\nWARNING: fits below r2=0.9: {', '.join(bad_fits)} — "
              f"their per_row_us/fixed_ms are NOT trustworthy for "
              f"before/after claims. Re-run with a higher --iters on a "
              f"quiet host (min-of-N only rejects noise it gets enough "
              f"samples to see).", flush=True)

    result = {
        "platform": platform,
        "window": args.window,
        "n_replicas": 5,
        "capacities": args.rows,
        "iters": args.iters,
        "substeps": table,
        "fits_below_r2_0_9": bad_fits,
        "note": "branch-free masked kernels: cost scales with inbox "
                "CAPACITY rows; live-row count only changes data. "
                "'empty' is the fixed per-round floor (commit scan, "
                "exec gate, slide) and also scales with capacity "
                "through the outbox/concat shapes. 'route' is the "
                "retired dense fabric (route_fabric='dense', kept for "
                "comparability); 'route_v2' is the segmented fabric "
                "the cluster runs (PR 11).",
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f, indent=1)
        print(f"wrote cost table to {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
