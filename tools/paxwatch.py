#!/usr/bin/env python
"""paxwatch — cluster health sampler, retention, and live SLO alarms.

Polls the master's ``stats`` + ``events`` fan-outs on an interval,
appends each health sample to an on-disk series with a streaming
downsample (raw recent samples, p50/p99/max per coarse bucket older,
compaction bounds the file — a week-long run stays a few MB), and
evaluates the SLO/anomaly detectors on every poll: frontier-stall
(with replica attribution), election-churn budget, exec-backlog
growth, and p99 burn rate against the declared latency SLO. Alarm
raises/clears print as parser-safe stdout lines and land in the
tool's own event journal.

    python tools/paxwatch.py -mport 7087                    # watch loop
    python tools/paxwatch.py -mport 7087 --series w.jsonl   # + retention
    python tools/paxwatch.py -mport 7087 --once --json      # one sample
    python tools/paxwatch.py -mport 7087 --duration 60      # bounded run
    python tools/paxwatch.py --report w.jsonl               # offline

``--once --json`` emits one machine-readable snapshot: the flattened
health sample, currently-firing alarms, and the cluster event journal
counts (the stable schema OBSERVABILITY.md documents). ``--report``
reads a saved series file back (no cluster needed) and summarizes its
raw/coarse coverage.

No JAX import anywhere on this path (the paxtop contract, pinned by
tools/obs_smoke.py's import probe): paxwatch runs cold in
milliseconds and is safe to leave attached to a week-long bench.

Exit status: 0 = ok, 1 = cluster unreachable / bad series file;
``--watch`` loops exit 0 on Ctrl-C.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from minpaxos_tpu.obs.watch import (  # noqa: E402
    SLO,
    HealthSeries,
    HealthWatcher,
    align_event_collections,
    counts_by_kind,
    load_series,
)
from minpaxos_tpu.runtime.master import (  # noqa: E402
    cluster_events,
    cluster_stats,
)


def _event_counts(maddr) -> dict:
    """{kind: count} over every replica's retained journal events."""
    resp = cluster_events(maddr)
    return counts_by_kind(align_event_collections(
        [r["journal"] for r in resp.get("replicas", [])
         if r.get("ok") and r.get("journal")]))


def _alarm_line(verb: str, a: dict) -> str:
    ev = a.get("evidence", {})
    return (f"paxwatch: {verb} {a['detector']} subject=replica "
            f"{a['subject']} window={ev.get('window_s', '?')}s "
            f"{ev.get('why', '')}".rstrip())


def report(path: str) -> int:
    try:
        doc = load_series(path)
    except OSError as e:
        print(f"paxwatch: cannot read {path}: {e!r}", file=sys.stderr)
        return 1
    raw, coarse = doc["raw"], doc["coarse"]
    span = 0.0
    if coarse:
        t1 = raw[-1]["t"] if raw else coarse[-1]["t1"]
        span = t1 - coarse[0]["t0"]
    elif len(raw) >= 2:
        span = raw[-1]["t"] - raw[0]["t"]
    print(json.dumps({
        "series": path, "raw_samples": len(raw),
        "coarse_buckets": len(coarse), "span_s": round(span, 1),
        "file_bytes": Path(path).stat().st_size,
    }))
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "paxwatch", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-maddr", default="127.0.0.1", help="master address")
    p.add_argument("-mport", type=int, default=7087, help="master port")
    p.add_argument("-i", "--interval", type=float, default=1.0,
                   help="poll interval seconds")
    p.add_argument("--series", default="",
                   help="append health samples to this file "
                        "(downsampled + compacted, bounded size)")
    p.add_argument("--max-bytes", type=int, default=8 << 20,
                   help="series-file compaction bound")
    p.add_argument("--raw-keep-s", type=float, default=300.0,
                   help="seconds of full-resolution samples retained")
    p.add_argument("--coarse-s", type=float, default=60.0,
                   help="downsample bucket width for older samples")
    p.add_argument("--duration", type=float, default=0.0,
                   help="stop after this many seconds (0 = forever)")
    p.add_argument("--once", action="store_true",
                   help="one sample + detector evaluation, then exit")
    p.add_argument("--json", action="store_true",
                   help="machine output (with --once)")
    p.add_argument("--report", default="", metavar="FILE",
                   help="summarize a saved series file and exit")
    # the declared SLO + detector tuning (OBSERVABILITY.md catalogue)
    p.add_argument("--slo-p99-ms", type=float, default=50.0,
                   help="tick-wall latency SLO the burn rate is "
                        "measured against")
    p.add_argument("--burn-budget", type=float, default=0.01,
                   help="allowed fraction of ticks over the SLO")
    p.add_argument("--stall-s", type=float, default=1.0,
                   help="frontier flat this long under load = stall")
    p.add_argument("--churn-budget", type=int, default=3,
                   help="elections allowed per churn window")
    args = p.parse_args(argv)

    if args.report:
        return report(args.report)

    maddr = (args.maddr, args.mport)
    slo = SLO(stall_s=args.stall_s, churn_budget=args.churn_budget,
              p99_ms=args.slo_p99_ms, burn_budget_frac=args.burn_budget)
    series = (HealthSeries(args.series, raw_keep_s=args.raw_keep_s,
                           coarse_s=args.coarse_s,
                           max_bytes=args.max_bytes)
              if args.series else None)
    watcher = HealthWatcher(
        poll_fn=lambda: cluster_stats(maddr, timeout_s=10.0),
        slo=slo, series=series, interval_s=args.interval)

    if args.once:
        try:
            active = watcher.poll_once()
            events = _event_counts(maddr)
        except (OSError, ValueError) as e:
            print(f"paxwatch: master unreachable at {maddr}: {e!r}",
                  file=sys.stderr)
            return 1
        sample = watcher.samples[-1]
        if args.json:
            print(json.dumps({"sample": sample, "alarms": active,
                              "events": events, "slo": vars(slo)}))
        else:
            print(f"paxwatch: tip={sample['tip']} "
                  f"alive={sample['alive']}/{len(sample['replicas'])} "
                  f"leader={sample['leader']} "
                  f"in_flight={sample['in_flight']} events={events}")
            for a in active:
                print(_alarm_line("ALARM", a))
        if series is not None:
            series.close()
        return 0

    deadline = (time.monotonic() + args.duration if args.duration > 0
                else None)
    seen: set[int] = set()
    try:
        while deadline is None or time.monotonic() < deadline:
            try:
                watcher.poll_once()
            except (OSError, ValueError) as e:
                print(f"paxwatch: poll failed: {e!r}", file=sys.stderr)
                time.sleep(args.interval)
                continue
            for i, a in enumerate(watcher.alarms):
                if i not in seen and a["t_cleared"] is None:
                    seen.add(i)
                    print(_alarm_line("ALARM", a), flush=True)
                elif a["t_cleared"] is not None and i in seen:
                    seen.discard(i)
                    print(_alarm_line("clear", a), flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        if series is not None:
            series.close()
        summary = watcher.summary()
        summary.pop("alarms", None)
        if series is not None:
            summary["series"] = series.summary()
        print(f"paxwatch: {json.dumps(summary)}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
