#!/usr/bin/env python
"""tail — paxtrace tail-latency attribution for a live cluster.

Pulls every replica's paxtrace span rings through the master's
``tracespans`` fan-out (runtime/master.py), aligns the per-process
clock anchors, joins spans into per-command chains, and prints the
stage-decomposition table: p50/p90/p99/p999 per stage (client send,
transport in, drain-queue wait, proposal->commit device rounds,
exec-backlog wait, reply serialization, transport out) plus the
worst-stage call-out for the commands in the end-to-end p99 tail —
"p99 is 497 ms" becomes "p99 commands spend X ms waiting in <stage>".

    python tools/tail.py -mport 7087                  # one table
    python tools/tail.py -mport 7087 --once --json    # machine output
    python tools/tail.py -mport 7087 --watch -i 2     # refresh loop
    python tools/tail.py -mport 7087 -dump-trace t.json

``-dump-trace`` merges the cluster flight-recorder timeline (the
TRACE verb) with per-command span tracks (reserved pid 9998, schema
v5), validates the result, and writes a file that loads directly in
Perfetto — one timeline showing a traced command's chain next to the
tick and device-round tracks. ``-spans-file`` analyzes saved raw
collections (a JSON list of TRACESPANS payloads, e.g. dumped from
``cluster_tracespans(maddr)``) instead of polling a live cluster.

No JAX import anywhere on this path (the paxtop contract): tail runs
cold in milliseconds.

Exit status: 0 = ok, 1 = cluster unreachable / invalid trace / no
complete chains.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from minpaxos_tpu.obs.recorder import (  # noqa: E402
    chrome_trace,
    validate_chrome_trace,
)
from minpaxos_tpu.obs.trace import (  # noqa: E402
    analyze_collections as analyze,
    format_stage_table,
    span_events,
)
from minpaxos_tpu.runtime.master import (  # noqa: E402
    cluster_trace,
    cluster_tracespans,
)


def fetch_collections(maddr) -> list[dict]:
    """Every live replica's span collection via the master fan-out."""
    resp = cluster_tracespans(maddr)
    out = []
    for r in resp.get("replicas", []):
        if r.get("ok") and isinstance(r.get("trace"), dict):
            out.append(r["trace"])
        elif not r.get("ok"):
            print(f"tail: replica {r.get('id')} unreachable "
                  f"({r.get('error')})", file=sys.stderr)
    return out


def _dump_trace(maddr, path: str, last: int | None) -> int:
    table, decomp, chains = analyze(fetch_collections(maddr))
    resp = cluster_trace(maddr, last=last)
    trace = resp.get("trace") or {}
    events = list(trace.get("traceEvents", []))
    sp = span_events(decomp, chains)
    events.extend(sp)
    merged = chrome_trace(events)
    errs = validate_chrome_trace(merged)
    if errs:
        print(f"tail: INVALID merged trace ({len(errs)} schema errors):",
              file=sys.stderr)
        for e in errs[:10]:
            print(f"  {e}", file=sys.stderr)
        return 1
    Path(path).write_text(json.dumps(merged))
    print(f"tail: wrote {len(events)} events ({len(sp)} command spans, "
          f"{table['n_traced']} traced commands) to {path} "
          f"(open in ui.perfetto.dev)")
    # same contract as the table path: a merged file with zero command
    # chains means tracing was off or rings were empty — fail the step
    return 0 if table["n_traced"] else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "tail", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-maddr", default="127.0.0.1", help="master address")
    p.add_argument("-mport", type=int, default=7087, help="master port")
    p.add_argument("--once", action="store_true",
                   help="one sample (the default; kept for paxtop "
                        "flag symmetry)")
    p.add_argument("--watch", action="store_true",
                   help="refresh the table on an interval")
    p.add_argument("-i", "--interval", type=float, default=2.0,
                   help="refresh interval for --watch (seconds)")
    p.add_argument("--json", action="store_true",
                   help="emit the stage table + per-trace decomposition "
                        "as JSON instead of the text table")
    p.add_argument("-dump-trace", default="",
                   help="merge flight-recorder timeline + command-span "
                        "tracks into a validated schema-v5 Perfetto "
                        "file and exit")
    p.add_argument("-last", type=int, default=1024,
                   help="newest recorder ticks per replica for "
                        "-dump-trace")
    p.add_argument("-spans-file", default="",
                   help="analyze saved raw span collections (a JSON "
                        "list of TRACESPANS payloads, e.g. dumped "
                        "from cluster_tracespans) instead of a live "
                        "cluster")
    args = p.parse_args(argv)
    maddr = (args.maddr, args.mport)

    if args.dump_trace:
        try:
            return _dump_trace(maddr, args.dump_trace, args.last)
        except (OSError, ValueError) as e:
            print(f"tail: trace fetch failed: {e!r}", file=sys.stderr)
            return 1

    while True:
        try:
            if args.spans_file:
                payload = json.loads(Path(args.spans_file).read_text())
                colls = payload if isinstance(payload, list) else [payload]
            else:
                colls = fetch_collections(maddr)
        except (OSError, ValueError) as e:
            print(f"tail: collection failed at {maddr}: {e!r}",
                  file=sys.stderr)
            return 1
        table, decomp, _ = analyze(colls)
        if args.json:
            print(json.dumps({"stage_table": table,
                              "per_trace": decomp}), flush=True)
        else:
            if args.watch:
                print("\x1b[2J\x1b[H", end="")
            print(format_stage_table(table), flush=True)
        if not args.watch:
            return 0 if table["n_traced"] else 1
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
