#!/usr/bin/env python
"""paxsoak: scenario-driven soak runs with one joined scorecard.

    tools/soak.py --smoke             # CI gate: 2 short phases incl.
                                      # a micro overload burst, 45 s
                                      # budget after boot, JSON verdict
    tools/soak.py --full              # the committed SOAK.json run:
                                      # warmup -> Zipf skew -> overload
                                      # burst -> partition-under-load
                                      # -> heal -> drain
    tools/soak.py --manifest m.json   # run your own phase manifest
    tools/soak.py --json SOAK.json    # where the scorecard lands

The scorecard joins, per phase: client-side acked/shed/retransmit
counts and p50/p99/p999, the paxwatch detector raise->clear timeline
classified against the ground-truth fault/phase timeline, per-phase
traced stage tables (tools/tail.py math), and the admission gate's
counters. ``tools/trend.py`` renders it as a markdown table.

Smoke pass criteria (the tier-1 wiring): every phase ran, EV_PHASE
landed on every replica's journal, exactly-once held across shards
(0 lost), and the scorecard is well-formed — the gate firing
ORGANICALLY is asserted for the committed full run (where the
overload phase is sized to provoke it), not for the CI micro burst,
whose sizing must stay friendly to slow shared hosts.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

SMOKE_BUDGET_S = 45.0  # measured from the end of cluster boot


def smoke_verdict(card: dict, n_replicas: int) -> dict:
    """The tier-1 gate's pass line (see module docstring)."""
    eo = card["exactly_once"]
    phases_ran = (len(card["phases"]) == len(card["manifest"]["phases"])
                  and all(p["client"]["sent"] > 0
                          and p["client"]["acked"] > 0
                          for p in card["phases"]))
    # EV_PHASE fan-out proof: every (ordinal incl. drain) x replica
    want_edges = (len(card["phases"]) + 1) * n_replicas
    checks = {
        "phases_ran": phases_ran,
        "ev_phase_on_every_replica":
            len(card["phase_events"]) == want_edges,
        "exactly_once": eo["lost"] == 0 and eo["acked_unique"] > 0,
        "no_dead_sessions": eo["dead_sessions"] == 0,
        "scorecard_joined": bool(card["stage_tables"]["overall"]
                                 or card["watch"]["samples"] > 0),
    }
    checks["ok"] = all(checks.values())
    return checks


def main(argv=None) -> int:
    p = argparse.ArgumentParser("paxsoak")
    p.add_argument("--smoke", action="store_true",
                   help="CI gate: the 2-phase smoke manifest under a "
                        f"{SMOKE_BUDGET_S:.0f} s post-boot budget")
    p.add_argument("--full", action="store_true",
                   help="the committed multi-phase chaos-under-load "
                        "run (writes SOAK.json's content)")
    p.add_argument("--manifest", default="",
                   help="path to a custom manifest JSON, or a named "
                        "manifest (smoke/full)")
    p.add_argument("--sessions", type=int, default=0,
                   help="override the manifest's swarm sessions")
    p.add_argument("--shards", type=int, default=0,
                   help="override the manifest's swarm shards")
    p.add_argument("--json", default="",
                   help="write the scorecard to this file")
    args = p.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from minpaxos_tpu.utils.backend import enable_compile_cache

    enable_compile_cache()

    from minpaxos_tpu.soak.scenario import (
        MANIFESTS,
        run_scenario,
        save_scorecard,
    )

    if args.smoke and args.full:
        p.error("--smoke and --full are exclusive")
    if args.manifest:
        if args.manifest in MANIFESTS:
            manifest = dict(MANIFESTS[args.manifest])
        else:
            manifest = json.loads(Path(args.manifest).read_text())
    elif args.full:
        manifest = dict(MANIFESTS["full"])
    else:
        manifest = dict(MANIFESTS["smoke"])
    if args.sessions:
        manifest["sessions"] = args.sessions
    if args.shards:
        manifest["shards"] = args.shards

    t0 = time.monotonic()
    card = run_scenario(manifest, log=lambda m: print(m, flush=True))
    card["wall_s"] = round(time.monotonic() - t0, 2)

    if args.json:
        save_scorecard(card, args.json)
        print(f"[soak] scorecard written to {args.json}", flush=True)

    if args.smoke or (args.manifest == "smoke"):
        checks = smoke_verdict(card, int(manifest.get("n_replicas", 3)))
        # the budget is advisory-but-loud: boot (jit) time is excluded
        # like the chaos smoke's, and phase walls are fixed by the
        # manifest, so an overrun means the drain dragged
        phase_wall = sum(p["t1_wall"] - p["t0_wall"]
                         for p in card["phases"])
        drain_wall = card["drain"]["t1_wall"] - card["drain"]["t0_wall"]
        checks["in_budget"] = phase_wall + drain_wall <= SMOKE_BUDGET_S
        checks["ok"] = checks["ok"] and checks["in_budget"]
        line = {**checks,
                "acked": card["exactly_once"]["acked_unique"],
                "shed": sum(p["cluster"]["coalesce_admission_rejects"]
                            for p in card["phases"]),
                "wall_s": card["wall_s"]}
        print(f"[soak] smoke verdict: {json.dumps(line)}", flush=True)
        return 0 if checks["ok"] else 1

    line = {"ok": card["ok"], **card["criteria"],
            "acked": card["exactly_once"]["acked_unique"],
            "lost": card["exactly_once"]["lost"],
            "alarms": card["watch"]["alarm_counts"],
            "wall_s": card["wall_s"]}
    print(f"[soak] verdict: {json.dumps(line)}", flush=True)
    return 0 if card["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
