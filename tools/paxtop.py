#!/usr/bin/env python
"""paxtop — live terminal view of a minpaxos cluster (paxmon).

Polls every replica THROUGH the master's ``stats`` fan-out verb
(runtime/master.py) and renders, per replica: role, frontier and lag
behind the cluster tip, commit throughput (delta of the ``committed``
gauge between polls), the dispatch-regime mix (full / fused / narrow /
idle-skip — PR 1's multi-modal tick cost, finally visible), exec
backlog, paxchaos injected-fault totals and narrow-anchor fallbacks
(a running chaos campaign or a flapping narrow view is visible
without a trace dump), the paxtrace TRACE column (sampled spans
collected / ring-overwrite drops — whether tools/tail.py has data to
attribute), p50/p99 tick wall from the typed histogram, the paxdur
SNAP column (snapshots taken / last-snapshot age / on-disk redo log
bytes — whether the truncation policy is actually bounding disk), and
the paxwatch HEALTH column (the newest WARN-or-worse journal event per
replica + its age). Below the table, an EVENTS tail pane shows the
newest cluster journal events (elections, leader changes, chaos
installs, store-corruption recoveries, alarms) from the master's
``events`` fan-out. When a paxsoak scenario (tools/soak.py) is
stamping EV_PHASE events, the header grows a SOAK stanza — current
phase name, ordinal, elapsed vs planned seconds. ``--once --json``
emits the whole model — response / derived / events / health / soak —
under the stable key schema pinned in tests/test_paxwatch.py
(OBSERVABILITY.md documents it).

    python tools/paxtop.py -mport 7087              # live, 1s refresh
    python tools/paxtop.py -mport 7087 -i 0.5       # faster refresh
    python tools/paxtop.py -mport 7087 --once       # one sample, no UI
    python tools/paxtop.py -mport 7087 --once --json  # machine output
    python tools/paxtop.py -mport 7087 -dump-trace t.json -last 2048

``-dump-trace`` pulls every replica's flight recorder through the
master's ``trace`` verb, validates the merged Chrome trace against the
trace-event schema, and writes a file that loads directly in Perfetto
(ui.perfetto.dev) or chrome://tracing — the way to capture per-phase
evidence during an A/B (PERF.md). No JAX import anywhere on this
path: paxtop runs cold in milliseconds.

Exit status: 0 = ok, 1 = cluster unreachable / invalid trace.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from minpaxos_tpu.obs.recorder import validate_chrome_trace  # noqa: E402
from minpaxos_tpu.obs.watch import (  # noqa: E402
    DETECTOR_NAMES,
    EV_ALARM,
    EV_ALARM_CLEAR,
    EV_AUX,
    EV_KIND,
    EV_SEV,
    EV_SUBJECT,
    EV_TRACE,
    EV_VALUE,
    EV_WALL,
    EVENT_NAMES,
    PHASE_KIND_NAMES,
    SEV_NAMES,
    SEV_WARN,
)
from minpaxos_tpu.runtime.master import (  # noqa: E402
    cluster_events,
    cluster_stats,
    cluster_trace,
)

_REGIMES = ("full_steps", "fused_dispatches", "narrow_steps")

#: --once --json payload keys — a STABLE schema (pinned by
#: tests/test_paxwatch.py; OBSERVABILITY.md documents it). Consumers
#: may rely on these being present; additions are fine, removals and
#: renames are a breaking change.
JSON_PAYLOAD_KEYS = ("response", "derived", "events", "health", "soak")
DERIVED_ROW_KEYS = (
    "id", "ok", "role", "protocol", "frontier", "lag", "fatal", "error",
    "dispatches", "ticks", "idle_skips", "committed", "chaos_injected",
    "narrow_fallbacks", "trace_spans", "trace_dropped", "exec_backlog",
    "mix_pct", "tick_p50_ms", "tick_p99_ms", "commits_per_s",
    "coalesce", "snap", "health")
EVENT_ROW_KEYS = ("rid", "t_wall_s", "age_s", "kind", "severity",
                  "subject", "value", "aux", "trace_id")
SOAK_ROW_KEYS = ("ordinal", "phase", "elapsed_s", "planned_s", "rid")


def _derive_events(ev_resp: dict, now_wall_ns: int,
                   last: int | None = None) -> list[dict]:
    """Flatten an ``events`` fan-out into render rows (newest-last;
    ``last`` bounds the tail, None = all retained), one per journal
    event, tagged with the replica the journal belongs to."""
    rows: list[dict] = []
    for r in ev_resp.get("replicas", []):
        j = r.get("journal")
        if not r.get("ok") or not j:
            continue
        rid = r.get("id", -1)
        for ev in j.get("events", []):
            kind = int(ev[EV_KIND])
            if kind <= 0:
                continue
            name = (EVENT_NAMES[kind] if kind < len(EVENT_NAMES)
                    else str(kind))
            if kind in (EV_ALARM, EV_ALARM_CLEAR):
                # match the Perfetto naming: alarm events carry their
                # detector so the pane reads "alarm:frontier_stall"
                name = f"{name}:{DETECTOR_NAMES.get(int(ev[EV_AUX]), '?')}"
            rows.append({
                "rid": rid,
                "t_wall_s": ev[EV_WALL] / 1e9,
                "age_s": round(max(0.0,
                                   (now_wall_ns - ev[EV_WALL]) / 1e9), 3),
                "kind": name,
                "severity": SEV_NAMES[min(int(ev[EV_SEV]), 2)],
                "subject": int(ev[EV_SUBJECT]),
                "value": int(ev[EV_VALUE]),
                "aux": int(ev[EV_AUX]),
                "trace_id": int(ev[EV_TRACE]),
            })
    rows.sort(key=lambda e: e["t_wall_s"])
    return rows if last is None else rows[-last:]


def _derive_soak(event_rows: list[dict]) -> dict | None:
    """SOAK stanza: the newest ``EV_PHASE`` journal event — which
    paxsoak scenario phase the cluster is in, how long it has been
    running, and the manifest's planned duration. None when no soak
    scenario has stamped the journals (the common idle case)."""
    newest = None
    for e in event_rows:  # newest-last: later rows overwrite
        if e["kind"] == "phase":
            newest = e
    if newest is None:
        return None
    kid = newest["aux"]
    return {"ordinal": newest["subject"],
            "phase": (PHASE_KIND_NAMES[kid]
                      if 0 <= kid < len(PHASE_KIND_NAMES) else str(kid)),
            "elapsed_s": newest["age_s"],
            "planned_s": newest["value"] / 1e3,
            "rid": newest["rid"]}


def _derive_health(event_rows: list[dict]) -> dict:
    """Per-replica HEALTH: the newest WARN-or-worse journal event
    ({rid: {kind, severity, age_s}}; absent rid = nothing loud)."""
    out: dict[int, dict] = {}
    for e in event_rows:  # newest-last: later rows overwrite
        if SEV_NAMES.index(e["severity"]) >= SEV_WARN:
            out[e["rid"]] = {"kind": e["kind"],
                             "severity": e["severity"],
                             "age_s": e["age_s"]}
    return out


def snapshot_payload(resp: dict, ev_resp: dict, prev: dict | None,
                     dt: float, now_wall_ns: int | None = None) -> dict:
    """The --once --json document (and the live view's model): the
    raw stats fan-out, derived per-replica rows (with the HEALTH
    stanza), the flattened cluster event tail, and the per-replica
    health map. Key sets are the stable schema above."""
    if now_wall_ns is None:
        now_wall_ns = time.time_ns()
    # health reads ALL retained events: an active never-cleared alert
    # must not vanish from the HEALTH column just because 64 newer
    # info events (a churn wave's peer_up storm) pushed it out of the
    # display tail
    all_events = _derive_events(ev_resp, now_wall_ns)
    health = _derive_health(all_events)
    rows = _derive(resp, prev, dt)
    for row in rows:
        row["health"] = health.get(row["id"])
    return {"response": resp, "derived": rows,
            "events": all_events[-64:],
            "health": {str(k): v for k, v in health.items()},
            "soak": _derive_soak(all_events)}


def _derive(resp: dict, prev: dict | None, dt: float) -> list[dict]:
    """Flatten one fan-out response into render rows, with commit
    throughput computed against the previous poll's gauges."""
    rows = []
    frontiers = [r.get("frontier", -1) for r in resp.get("replicas", [])
                 if r.get("ok")]
    tip = max(frontiers, default=-1)
    for r in resp.get("replicas", []):
        rid = r.get("id", -1)
        row = {"id": rid, "ok": bool(r.get("ok")),
               "role": ("leader" if rid == resp.get("leader") else
                        "replica"),
               "protocol": r.get("protocol", "?"),
               "frontier": r.get("frontier", -1),
               "lag": (tip - r.get("frontier", -1)) if r.get("ok") else None,
               "fatal": r.get("fatal"), "error": r.get("error")}
        mx = r.get("metrics") or {}
        counters = dict(mx.get("counters") or {})
        counters.update(mx.get("gauges") or {})
        disp = counters.get("dispatches", 0)
        row["dispatches"] = disp
        row["ticks"] = counters.get("ticks", 0)
        row["idle_skips"] = counters.get("idle_skips", 0)
        row["committed"] = counters.get("committed", 0)
        # live-visible health signals that previously needed a trace
        # dump: a running chaos campaign (paxchaos injected-fault
        # total) and a flapping narrow anchor (validation failures
        # forcing full-width recounts) both show in the table
        row["chaos_injected"] = counters.get("chaos_injected", 0)
        row["narrow_fallbacks"] = counters.get("narrow_fallbacks", 0)
        # paxtrace health: sampled spans collected + ring-overwrite
        # drops (a live view of whether tail.py has data to attribute)
        row["trace_spans"] = counters.get("trace_spans", 0)
        row["trace_dropped"] = counters.get("trace_dropped", 0)
        scal = r.get("scalars") or {}
        row["exec_backlog"] = (row["frontier"] + 1
                               - (scal.get("executed", row["frontier"]) + 1))
        row["mix_pct"] = {k.split("_")[0]: (100.0 * counters.get(k, 0)
                                            / disp if disp else 0.0)
                          for k in _REGIMES}
        hist = (mx.get("histograms") or {}).get("tick_wall_ms") or {}
        row["tick_p50_ms"] = hist.get("p50", 0.0)
        row["tick_p99_ms"] = hist.get("p99", 0.0)
        # ingress-coalescer health (ISSUE 15): cv wakeups delivered to
        # a parked tick loop, max-wait deadline expiries, admission
        # rejects, and the median coalesced batch size — all zero on a
        # -nocoalesce server (the keys stay present: stable schema)
        chist = (mx.get("histograms") or {}).get("coalesce_batch_rows") or {}
        row["coalesce"] = {
            "wakeups": counters.get("coalesce_wakeups", 0),
            "deadline_hits": counters.get("coalesce_deadline_hits", 0),
            "rejects": counters.get("coalesce_admission_rejects", 0),
            "occ_p50": chist.get("p50", 0.0),
            "queue_depth": counters.get("ingress_queue_depth", 0),
        }
        # paxdur durability health: last-snapshot age, on-disk redo log
        # bytes, snapshots taken — log_bytes climbing without bound (or
        # age frozen at -1 on a durable server) means the snapshot
        # policy is not engaging; all zeros/-1 on a -nosnap or
        # non-durable server (keys stay present: stable schema)
        row["snap"] = {
            "age_s": counters.get("snap_age_s", -1),
            "log_bytes": counters.get("store_log_bytes", 0),
            "count": counters.get("snap_count", 0),
        }
        ops = None
        if prev is not None and dt > 0:
            for p in prev.get("replicas", []):
                if p.get("id") == rid and p.get("ok") and r.get("ok"):
                    pc = ((p.get("metrics") or {}).get("gauges") or {})
                    ops = (row["committed"] - pc.get("committed", 0)) / dt
        row["commits_per_s"] = ops
        rows.append(row)
    return rows


def _abbrev(n: int) -> str:
    """Compact count for fixed-width columns: the TRACE pair is a
    lifetime-monotone span total, so a long-lived server would
    otherwise overflow its field and shear every column after it."""
    if n >= 10_000_000:
        return f"{n / 1e6:.0f}M"
    if n >= 1_000_000:
        return f"{n / 1e6:.1f}M"
    if n >= 10_000:
        return f"{n / 1e3:.0f}k"
    return str(n)


def _fmt_coalesce(c: dict | None) -> str:
    """COALESCE column: wakeups/deadline-hits/rejects (abbreviated) —
    a live coalescer shows wakeups climbing with traffic; rejects > 0
    means the admission gate is actively shedding."""
    if not c:
        return "-"
    return (f"{_abbrev(c['wakeups'])}/{_abbrev(c['deadline_hits'])}"
            f"/{_abbrev(c['rejects'])}")


def _fmt_snap(s: dict | None) -> str:
    """SNAP column: snapshots-taken/last-age/log-bytes — a durable
    server under load shows the count climbing and log bytes sawtoothing
    under the policy threshold; '-' age means never snapshotted."""
    if not s:
        return "-"
    age = s.get("age_s", -1)
    age_s = ("-" if age < 0
             else f"{age:.0f}s" if age < 600 else f"{age / 60:.0f}m")
    return f"{s.get('count', 0)}/{age_s}/{_abbrev(s.get('log_bytes', 0))}"


def _fmt_health(h: dict | None) -> str:
    if not h:
        return "-"
    age = h["age_s"]
    age_s = f"{age:.0f}s" if age < 600 else f"{age / 60:.0f}m"
    return f"{h['kind']}/{age_s}"


def _render(resp: dict, rows: list[dict], clear: bool,
            events: list[dict] | None = None,
            tail_n: int = 6, soak: dict | None = None) -> None:
    out = []
    if clear:
        out.append("\x1b[2J\x1b[H")
    alive = sum(1 for r in rows if r["ok"])
    header = (f"paxtop — {alive}/{len(rows)} replicas up, "
              f"leader={resp.get('leader')}   "
              f"{time.strftime('%H:%M:%S')}")
    if soak:
        # paxsoak SOAK column: the scenario phase the cluster is in,
        # from the newest EV_PHASE journal stamp (tools/soak.py)
        header += (f"   SOAK phase#{soak['ordinal']} {soak['phase']} "
                   f"+{soak['elapsed_s']:.0f}s"
                   f"/{soak['planned_s']:.0f}s")
    out.append(header)
    hdr = (f"{'ID':>2} {'ROLE':<8} {'ST':<2} {'FRONTIER':>9} {'LAG':>6} "
           f"{'COMMIT/S':>9} {'BACKLOG':>8} {'DISP':>8} {'FULL%':>6} "
           f"{'FUSE%':>6} {'NARR%':>6} {'SKIPS':>8} {'CHAOS':>7} "
           f"{'NARRFB':>6} {'TRACE':>11} {'p50ms':>7} {'p99ms':>8} "
           f"{'COALESCE':>13} {'SNAP':>12} {'HEALTH':<18}")
    out.append(hdr)
    out.append("-" * len(hdr))
    for r in rows:
        if not r["ok"]:
            out.append(f"{r['id']:>2} {'?':<8} DN "
                       f"{r.get('fatal') or r.get('error') or 'down'}")
            continue
        mix = r["mix_pct"]
        ops = ("-" if r["commits_per_s"] is None
               else f"{r['commits_per_s']:.0f}")
        out.append(
            f"{r['id']:>2} {r['role']:<8} ok {r['frontier']:>9} "
            f"{r['lag']:>6} {ops:>9} {r['exec_backlog']:>8} "
            f"{r['dispatches']:>8} {mix.get('full', 0):>6.1f} "
            f"{mix.get('fused', 0):>6.1f} {mix.get('narrow', 0):>6.1f} "
            f"{r['idle_skips']:>8} {r['chaos_injected']:>7} "
            f"{r['narrow_fallbacks']:>6} "
            f"{_abbrev(r['trace_spans']) + '/' + _abbrev(r['trace_dropped']):>11} "
            f"{r['tick_p50_ms']:>7.2f} "
            f"{r['tick_p99_ms']:>8.2f} "
            f"{_fmt_coalesce(r.get('coalesce')):>13} "
            f"{_fmt_snap(r.get('snap')):>12} "
            f"{_fmt_health(r.get('health')):<18}")
    if events:
        # paxwatch EVENTS tail pane: the newest journal events across
        # the cluster (elections, failovers, chaos installs, alarms)
        out.append("")
        out.append(f"events (newest {min(tail_n, len(events))} of "
                   f"{len(events)} retained):")
        for e in events[-tail_n:]:
            when = time.strftime("%H:%M:%S", time.localtime(e["t_wall_s"]))
            out.append(f"  {when} r{e['rid']} {e['severity']:<5} "
                       f"{e['kind']} subject={e['subject']} "
                       f"value={e['value']}")
    print("\n".join(out), flush=True)


def _dump_trace(maddr, path: str, last: int | None) -> int:
    resp = cluster_trace(maddr, last=last)
    trace = resp.get("trace") or {}
    errs = validate_chrome_trace(trace)
    if errs:
        print(f"paxtop: INVALID trace ({len(errs)} schema errors):",
              file=sys.stderr)
        for e in errs[:10]:
            print(f"  {e}", file=sys.stderr)
        return 1
    Path(path).write_text(json.dumps(trace))
    n = len(trace.get("traceEvents", []))
    pids = sorted({e.get("pid") for e in trace.get("traceEvents", [])})
    print(f"paxtop: wrote {n} trace events from replicas {pids} to "
          f"{path} (open in ui.perfetto.dev or chrome://tracing)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "paxtop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("-maddr", default="127.0.0.1", help="master address")
    p.add_argument("-mport", type=int, default=7087, help="master port")
    p.add_argument("-i", "--interval", type=float, default=1.0,
                   help="poll/refresh interval seconds")
    p.add_argument("--once", action="store_true",
                   help="print one sample and exit (no screen clearing)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw fan-out response + derived rows "
                        "as JSON instead of the table")
    p.add_argument("-dump-trace", default="",
                   help="fetch + validate the merged cluster flight-"
                        "recorder trace, write Chrome trace JSON here, "
                        "and exit")
    p.add_argument("-last", type=int, default=1024,
                   help="newest recorder ticks per replica for "
                        "-dump-trace / the TRACE verb")
    args = p.parse_args(argv)
    maddr = (args.maddr, args.mport)

    if args.dump_trace:
        try:
            return _dump_trace(maddr, args.dump_trace, args.last)
        except (OSError, ValueError) as e:
            print(f"paxtop: trace fetch failed: {e!r}", file=sys.stderr)
            return 1

    prev, t_prev = None, 0.0
    while True:
        try:
            resp = cluster_stats(maddr)
        except (OSError, ValueError) as e:
            print(f"paxtop: master unreachable at {maddr}: {e!r}",
                  file=sys.stderr)
            return 1
        try:
            ev_resp = cluster_events(maddr)
        except (OSError, ValueError):
            ev_resp = {}  # events pane degrades, stats still render
        now = time.monotonic()
        payload = snapshot_payload(resp, ev_resp, prev,
                                   now - t_prev if prev else 0.0)
        if args.json:
            print(json.dumps(payload), flush=True)
        else:
            _render(resp, payload["derived"], clear=not args.once,
                    events=payload["events"], soak=payload["soak"])
        if args.once:
            return 0
        prev, t_prev = resp, now
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
