#!/usr/bin/env python
"""paxlint CLI — run the repo's consensus-aware static analysis.

    python tools/lint.py                 # lint the whole tree, human output
    python tools/lint.py --json          # machine output (bench tracking)
    python tools/lint.py --rules wire-contract,concurrency
    python tools/lint.py --list-rules
    python tools/lint.py --print-wire-golden   # regen the wire ledger
    python tools/lint.py --print-store-golden  # regen the store ledger

Exit status: 0 = clean, 1 = violations, 2 = usage error.

Fast by design: pure AST + a numpy-only evaluation of the wire
schemas; no jax import, so it runs cold in under a couple of seconds
and belongs at the top of tools/run_tier1.sh. See ANALYSIS.md for the
rule catalogue and the suppression syntax.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from minpaxos_tpu.analysis import PASSES, Project, run_passes  # noqa: E402


def _print_wire_golden() -> None:
    """Emit the current tree's wire ledger (paste into
    analysis/wire_golden.py when legitimately extending the contract)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_wire_messages", REPO_ROOT / "minpaxos_tpu/wire/messages.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    print("GOLDEN_KINDS: dict[str, tuple[int, int | None]] = {")
    for k in mod.MsgKind:
        dt = mod.SCHEMAS.get(k)
        size = dt.itemsize if dt is not None else None
        print(f'    "{k.name}": ({int(k)}, {size}),')
    print("}")


def _print_store_golden() -> None:
    """Emit the current tree's stable-store ledger (paste into
    analysis/store_golden.py when legitimately extending the contract)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_stable_store", REPO_ROOT / "minpaxos_tpu/runtime/stable.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    tags = sorted((n, v) for n, v in vars(mod).items()
                  if n.startswith("REC_") and isinstance(v, int))
    print("GOLDEN_REC_TAGS: dict[str, int] = {")
    for name, value in sorted(tags, key=lambda nv: nv[1]):
        print(f'    "{name}": {value},')
    print("}")
    print("GOLDEN_MAGICS: dict[str, bytes] = {")
    for name in ("MAGIC_V1", "MAGIC"):
        print(f'    "{name}": {getattr(mod, name)!r},')
    print("}")
    print("GOLDEN_STRUCT_FMTS: dict[str, str] = {")
    for name in ("_HDR", "_CRC", "_FRONTIER", "_SNAP_HDR"):
        print(f'    "{name}": "{getattr(mod, name).format}",')
    print("}")
    print("GOLDEN_ROW_BYTES: dict[str, int] = {")
    for name in ("SLOT_DT", "SNAP_DT"):
        print(f'    "{name}": {getattr(mod, name).itemsize},')
    print("}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "paxlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--json", action="store_true",
                   help="JSON output: violations + per-rule counts")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--root", default=str(REPO_ROOT),
                   help="repo root to lint (default: this repo)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--print-wire-golden", action="store_true",
                   help="emit the current wire ledger and exit")
    p.add_argument("--print-store-golden", action="store_true",
                   help="emit the current stable-store ledger and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in sorted(PASSES):
            doc = (PASSES[rule].__module__ or "").rsplit(".", 1)[-1]
            print(f"{rule:20s} minpaxos_tpu/analysis/{doc}.py")
        return 0
    if args.print_wire_golden:
        _print_wire_golden()
        return 0
    if args.print_store_golden:
        _print_store_golden()
        return 0

    rules = None
    if args.rules:
        rules = tuple(r.strip() for r in args.rules.split(",") if r.strip())
        unknown = [r for r in rules if r not in PASSES]
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}; known: "
                  f"{', '.join(sorted(PASSES))}", file=sys.stderr)
            return 2

    project = Project.from_root(args.root)
    violations = run_passes(project, rules)

    if args.json:
        print(json.dumps({
            "clean": not violations,
            "files_scanned": len(project.files),
            "rules_run": sorted(rules if rules is not None else PASSES),
            "counts": dict(Counter(v.rule for v in violations)),
            "violations": [v.as_json() for v in violations],
        }, indent=2))
    else:
        for v in violations:
            print(v.render())
        n = len(violations)
        print(f"paxlint: {len(project.files)} files, "
              f"{n} violation{'s' if n != 1 else ''}"
              + ("" if n else " — clean"))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
