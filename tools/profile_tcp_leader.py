"""Where does a TCP leader's wall time go under closed-loop load?

Boots an in-process 3-replica MinPaxos cluster (the test-harness
deployment), drives q ops through the real wire path, and prints the
leader's protocol-thread cProfile (cumulative top-25). In-process on a
1-core host overstates contention, but the RELATIVE split between
device dispatch, codec, socket IO and bookkeeping is what we're after.

Run: python tools/profile_tcp_leader.py [q]
"""

from __future__ import annotations

import cProfile
import os
import pathlib
import pstats
import sys
import tempfile
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main() -> None:
    q = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
    from minpaxos_tpu.models.minpaxos import MinPaxosConfig
    from minpaxos_tpu.runtime.client import Client, gen_workload
    from minpaxos_tpu.utils.backend import enable_compile_cache

    enable_compile_cache()  # keep first-boot re-jits out of the profile
    from minpaxos_tpu.runtime.master import Master, register_with_master
    from minpaxos_tpu.runtime.replica import ReplicaServer, RuntimeFlags
    from minpaxos_tpu.utils.netutil import CONTROL_OFFSET, free_ports

    tmp = tempfile.mkdtemp(prefix="prof_tcp_")
    mport = free_ports(1)[0]
    dports = free_ports(3, sibling_offset=CONTROL_OFFSET)
    master = Master("127.0.0.1", mport, 3)
    master.start()
    for p in dports:
        register_with_master(("127.0.0.1", mport), "127.0.0.1", p)
    cfg = MinPaxosConfig(n_replicas=3, window=2048, inbox=1024,
                         exec_batch=128, kv_pow2=18,
                         catchup_rows=256, recovery_rows=256)
    prof = cProfile.Profile()
    servers = []
    # A/B knobs for the fused/idle/narrow paths (round 6): e.g.
    #   PROF_FUSE=1 PROF_IDLEFAST=0 python tools/profile_tcp_leader.py
    # reproduces the pre-round-6 runtime; the stats block printed at
    # the end carries the dispatch/fused/idle-skip counts either way.
    fuse = int(os.environ.get("PROF_FUSE", "3"))
    idlefast = os.environ.get("PROF_IDLEFAST", "1") != "0"
    narrow = int(os.environ.get("PROF_NARROW", "0"))
    for i, p in enumerate(dports):
        flags = RuntimeFlags(durable=True, store_dir=tmp,
                             fuse_ticks=fuse, idle_fastpath=idlefast,
                             narrow_window=narrow,
                             profile=prof if i == 0 else None)
        s = ReplicaServer(i, [("127.0.0.1", pp) for pp in dports],
                          cfg, flags)
        s.start()
        servers.append(s)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if servers[0].snapshot["prepared"]:
            break
        time.sleep(0.1)

    cli = Client(("127.0.0.1", mport), check=True)
    ops, keys, vals = gen_workload(q, seed=9)
    t0 = time.perf_counter()
    stats = cli.run_workload(
        ops, keys, vals, timeout_s=180,
        batch=int(os.environ.get("PROF_BATCH", "512")))
    wall = time.perf_counter() - t0
    print(f"acked {stats['acked']}/{q} in {wall:.2f}s "
          f"({stats['acked']/wall:.0f} ops/s)", file=sys.stderr)
    cli.close_conn()
    print(f"knobs: fuse_ticks={fuse} idle_fastpath={idlefast} "
          f"narrow={narrow}", file=sys.stderr)
    for i, s in enumerate(servers):
        d = s.stats
        print(f"replica {i}: dispatches={d['dispatches']} "
              f"fused_substeps={d['fused_substeps']} "
              f"idle_skips={d['idle_skips']} "
              f"narrow_steps={d['narrow_steps']} ticks={d['ticks']}",
              file=sys.stderr)
    for s in servers:
        s.stop()
    master.stop()

    ps = pstats.Stats(prof)
    ps.sort_stats("cumulative")
    ps.print_stats(25)
    ps.sort_stats("tottime")
    ps.print_stats(30)


if __name__ == "__main__":
    main()
