"""Wait for the TPU relay, then run the rung-0 bench child (A/B of the
winner-gather rewrite against the 673.9 ms/round pre-rewrite record).

Probes the backend on the shared playbook's cadence indefinitely (the
relay outage window has been hours); on the first live non-cpu answer,
runs ``MP_BENCH_CHILD=64,2048,256,16 python bench.py`` and writes the
record to .bench_tpu_r5_rung0_postwinner.json.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from minpaxos_tpu.utils.backend import probe_backend

REPO = pathlib.Path(__file__).resolve().parent.parent


def main() -> None:
    t0 = time.monotonic()
    while True:
        platform = probe_backend(timeout_s=120)
        waited = time.monotonic() - t0
        print(f"[ab-waiter] +{waited:6.0f}s probe -> {platform}",
              file=sys.stderr, flush=True)
        if platform and platform != "cpu":
            break
        time.sleep(120)
    env = dict(os.environ, MP_BENCH_CHILD="64,2048,256,16",
               MP_BENCH_PROBED="1")
    proc = subprocess.run([sys.executable, str(REPO / "bench.py")],
                          env=env, stdout=subprocess.PIPE, timeout=2400)
    lines = [ln for ln in proc.stdout.decode().splitlines()
             if ln.strip().startswith("{")]
    out = REPO / ".bench_tpu_r5_rung0_postwinner.json"
    out.write_text((lines[-1] + "\n") if lines else
                   json.dumps({"error": f"child rc={proc.returncode}"}))
    print(f"[ab-waiter] wrote {out}", file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
