"""Replica-axis mesh layout A/B: shard-only vs replicas-over-chips.

VERDICT round-5 weak #6: the ``n_replica_devices > 1`` layout
(parallel/mesh.py) — each consensus group's replicas spread across
chips, turning the routing gather into inter-chip collectives — is
executed by a smoke test but has never been MEASURED against the
default all-shards layout. This tool runs the same fused workload at
one fixed shape under both layouts on the visible device mesh (the
8-virtual-device CPU mesh in CI; a real chip mesh when present) and
prints a comparison table for PERF.md.

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
         python tools/mesh_layout_ab.py
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip()

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from minpaxos_tpu.models.minpaxos import MinPaxosConfig
from minpaxos_tpu.parallel.mesh import make_mesh
from minpaxos_tpu.parallel.sharded import (
    elect_all,
    init_sharded,
    make_propose_ext,
    sharded_run,
)


def run_layout(n_replica_devices: int, g: int, w: int, p: int, k: int,
               dispatches: int) -> dict:
    """One layout's measurement: boot, elect, warm, time fused rounds."""
    n_dev = len(jax.devices())
    mesh = make_mesh(n_shard_devices=n_dev // n_replica_devices,
                     n_replica_devices=n_replica_devices)
    cfg = MinPaxosConfig(n_replicas=4, window=w, inbox=p + 2 * 64 + 64,
                         exec_batch=p, kv_pow2=10, catchup_rows=64,
                         recovery_rows=64)
    ss = init_sharded(cfg, g)

    def put(x):
        spec = (P("shard", "replica") if x.ndim >= 2
                else P("shard") if x.ndim >= 1 else P())
        return jax.device_put(x, NamedSharding(mesh, spec))

    ss = jax.tree_util.tree_map(put, ss)
    ss = elect_all(cfg, ss, 0)
    ext_sharding = NamedSharding(mesh, P("shard"))

    def fused(ss, seed):
        ss, uptos, crts = sharded_run(
            cfg, g, p, k, ss, jnp.int32(p), jnp.int32(0), jnp.int32(seed))
        return ss, uptos

    # two quiet steps deliver prepares/replies; then warm the fused path
    ss, _ = fused(ss, 0)
    start = int((np.asarray(ss.states.committed_upto[:, 0]) + 1).sum())
    t0 = time.perf_counter()
    for d in range(dispatches):
        ss, uptos = fused(ss, 1 + d)
        np.asarray(uptos)  # block
    wall = time.perf_counter() - t0
    committed = int((np.asarray(
        ss.states.committed_upto[:, 0]) + 1).sum()) - start
    return {
        "layout": (f"shard-only ({n_dev}x1)" if n_replica_devices == 1
                   else f"replica-axis ({n_dev // n_replica_devices}"
                        f"x{n_replica_devices})"),
        "inst_per_sec": round(committed / wall, 1),
        "ms_per_round": round(wall / (dispatches * k) * 1e3, 3),
        "committed": committed,
        "wall_s": round(wall, 2),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--window", type=int, default=1024)
    ap.add_argument("--props", type=int, default=128)
    ap.add_argument("--k", type=int, default=8)
    ap.add_argument("--dispatches", type=int, default=3)
    args = ap.parse_args()
    print(f"backend: {jax.devices()[0].platform}, "
          f"{len(jax.devices())} devices", file=sys.stderr)
    rows = []
    for nrd in (1, 2):
        rec = run_layout(nrd, args.shards, args.window, args.props,
                         args.k, args.dispatches)
        rows.append(rec)
        print(rec, flush=True)
    a, b = rows
    ratio = (b["ms_per_round"] / a["ms_per_round"]
             if a["ms_per_round"] else float("nan"))
    print(f"replica-axis / shard-only round cost: {ratio:.2f}x "
          f"(fixed shape g={args.shards} w={args.window} "
          f"p={args.props} R=4, k={args.k})")


if __name__ == "__main__":
    main()
