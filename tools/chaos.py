#!/usr/bin/env python
"""paxchaos campaign runner: seeded network- and process-fault
schedules against a real in-process cluster, invariant-checked after
every one.

    tools/chaos.py                      # all 11 schedules, default seed
    tools/chaos.py --schedules flex_partition  # N=5 (q1=4, q2=2):
                                       # starve the q2-sized island
    tools/chaos.py --schedules crash_restart_heal  # kill/restart a
                                       # durable replica under load
    tools/chaos.py --seeds 7,1234      # replay specific seeds
    tools/chaos.py --schedules isolated_leader --seeds 42
    tools/chaos.py --smoke             # CI gate: 2 fixed seeds, quick
                                       # schedule pair, 60 s budget,
                                       # JSON verdict (run_tier1.sh)
    tools/chaos.py --json out.json     # write the full verdict

Every run prints its seed; a failing (schedule, seed) pair reproduces
the identical fault schedule — the event times, the per-link drop/
delay/duplicate/reorder decisions, and the client's backoff jitter are
all derived from it (ROBUSTNESS.md has the fault model and recipes).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

#: the CI smoke's schedule subset: one partition-heal (the canonical
#: safety scenario) + one loss/reorder soak (the messy-network one) —
#: quick enough for the 60 s budget, distinct enough to cover both
#: fault families
SMOKE_SCHEDULES = ["partition_heal", "loss_reorder"]
SMOKE_SEEDS = [1009, 2003]  # fixed: CI failures replay bit-identically


def main(argv=None) -> int:
    p = argparse.ArgumentParser("paxchaos")
    p.add_argument("--schedules", default="all",
                   help="comma-separated schedule names, or 'all'")
    p.add_argument("--seeds", default="1009",
                   help="comma-separated campaign seeds")
    p.add_argument("--n", type=int, default=3, help="replicas")
    p.add_argument("--ops", type=int, default=400,
                   help="sizes the closed-loop load chunks (the loader "
                        "proposes continuously until the schedule's "
                        "last fault event has fired)")
    p.add_argument("--budget", type=float, default=0.0,
                   help="wall budget in seconds (0 = none), measured "
                        "from the end of the first run (the first "
                        "cluster boot pays the one-time jit compile)")
    p.add_argument("--json", default="",
                   help="also write the full verdict to this file")
    p.add_argument("--smoke", action="store_true",
                   help="CI gate mode: fixed seeds "
                        f"{SMOKE_SEEDS}, schedules {SMOKE_SCHEDULES}, "
                        "60 s budget, exit nonzero on any failure")
    p.add_argument("--plan-file", default=None, metavar="FILE",
                   help="replay a paxmc counterexample's FaultPlan on a "
                        "live cluster: FILE is tools/mc.py "
                        "--emit-faultplan output (or a raw paxmc-ce-v1 "
                        "trace, converted on the fly)")
    args = p.parse_args(argv)

    import jax

    jax.config.update("jax_platforms", "cpu")
    from minpaxos_tpu.utils.backend import enable_compile_cache

    enable_compile_cache()

    from minpaxos_tpu.chaos.campaign import (
        SCHEDULES,
        run_campaign,
        run_schedule,
    )

    if args.plan_file:
        doc = json.loads(Path(args.plan_file).read_text())
        if doc.get("format") == "paxmc-ce-v1":  # raw trace: project it
            from minpaxos_tpu.verify.mc import counterexample_faultplan

            doc = counterexample_faultplan(doc)
        events = [tuple(e) for e in doc["events"]]
        seed = int(args.seeds.split(",")[0])
        r = run_schedule("mc_replay", seed, n=args.n, ops_n=args.ops,
                         events=events)
        print(f"[chaos] mc_replay verdict: "
              f"{json.dumps({'ok': r['ok'], 'acked': r.get('acked'), 'faults': r.get('faults_injected'), 'check': r.get('check', {}).get('ok')})}",
              flush=True)
        if args.json:
            Path(args.json).write_text(json.dumps(r, indent=1))
        return 0 if r["ok"] else 1

    pairs = None
    if args.smoke:
        schedules, seeds = SMOKE_SCHEDULES, SMOKE_SEEDS
        # one run per fixed seed (seed i drives schedule i): two full
        # boot+fault+check cycles fit the 60 s budget, the full product
        # does not on a 1-core host (each cluster boot is ~20 s there)
        pairs = list(zip(SMOKE_SEEDS, SMOKE_SCHEDULES))
        budget = 60.0
        ops_n = 250
    else:
        schedules = (list(SCHEDULES) if args.schedules == "all"
                     else args.schedules.split(","))
        seeds = [int(s) for s in args.seeds.split(",")]
        budget = args.budget or None
        ops_n = args.ops
    for s in schedules:
        if s not in SCHEDULES:
            p.error(f"unknown schedule {s!r} (have: {', '.join(SCHEDULES)})")

    t0 = time.monotonic()
    verdict = run_campaign(schedules, seeds, n=args.n, ops_n=ops_n,
                           budget_s=budget, pairs=pairs)
    verdict["wall_s"] = round(time.monotonic() - t0, 2)
    line = {"ok": verdict["ok"], "runs": len(verdict["runs"]),
            "failed": [
                {"schedule": r.get("schedule"), "seed": r.get("seed"),
                 "error": r.get("error"),
                 "violations": r.get("check", {}).get("violations"),
                 # paxwatch live verdict: a stall schedule can now
                 # fail on detection alone (fired/attributed/cleared)
                 # even with every offline invariant green
                 "stall_live": (r.get("watch") or {}).get("stall")}
                for r in verdict["runs"] if not r.get("ok")],
            "wall_s": verdict["wall_s"]}
    print(f"[chaos] verdict: {json.dumps(line)}", flush=True)
    if args.json:
        Path(args.json).write_text(json.dumps(verdict, indent=1))
        print(f"[chaos] full verdict written to {args.json}", flush=True)
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
